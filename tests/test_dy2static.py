"""Control-flow conversion under to_static (ref: dy2static AST
transforms / SOT graph breaks — tensor-dependent if/while must compile
and match eager execution)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import dy2static


class TestTensorIf:
    def test_if_matches_eager(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        xs_pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        xs_neg = paddle.to_tensor(np.array([-3.0, 1.0], np.float32))
        sf = pjit.to_static(f)
        for x in (xs_pos, xs_neg):
            got = sf(x)
            want = f(x)
            np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-6)

    def test_if_without_else(self):
        def f(x):
            y = x + 1.0
            if x.mean() > 0:
                y = y * 10.0
            return y

        sf = pjit.to_static(f)
        x = paddle.to_tensor(np.array([0.5, 0.5], np.float32))
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)
        x2 = paddle.to_tensor(np.array([-0.5, -0.5], np.float32))
        np.testing.assert_allclose(sf(x2).numpy(), f(x2).numpy(), rtol=1e-6)

    def test_grad_flows_through_if(self):
        def step(x):
            x.stop_gradient = False
            if x.sum() > 0:
                y = (x * 3.0).sum()
            else:
                y = (x * 5.0).sum()
            y.backward()
            return y, x.grad

        sf = pjit.to_static(step)
        x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
        y, g = sf(x)
        np.testing.assert_allclose(g.numpy(), [3.0, 3.0], rtol=1e-6)
        x2 = paddle.to_tensor(np.array([-1.0, -1.0], np.float32))
        _, g2 = sf(x2)
        np.testing.assert_allclose(g2.numpy(), [5.0, 5.0], rtol=1e-6)

    def test_python_if_untouched(self):
        def make(mode):
            def f(x):
                if mode == "double":   # plain python predicate
                    y = x * 2.0
                else:
                    y = x * 3.0
                return y

            return pjit.to_static(f)

        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(make("double")(x).numpy(), [2.0])
        np.testing.assert_allclose(make("triple")(x).numpy(), [3.0])

    def test_nested_if(self):
        def f(x):
            if x.sum() > 0:
                if x.max() > 10:
                    y = x * 100.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        sf = pjit.to_static(f)
        for arr in ([20.0, 1.0], [1.0, 1.0], [-5.0, 1.0]):
            x = paddle.to_tensor(np.array(arr, np.float32))
            np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)


class TestTensorWhile:
    def test_while_matches_eager(self):
        def f(x):
            s = paddle.to_tensor(np.float32(0.0))
            i = paddle.to_tensor(np.float32(0.0))
            while i < 5.0:
                s = s + x.sum() * 0.0 + i
                i = i + 1.0
            return s

        sf = pjit.to_static(f)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        got = sf(x)
        np.testing.assert_allclose(float(got), 10.0, rtol=1e-6)

    def test_data_dependent_trip_count(self):
        """Collatz-ish halving: trip count depends on the data."""

        def f(x):
            n = paddle.to_tensor(np.float32(0.0))
            v = x.sum()
            while v > 1.0:
                v = v / 2.0
                n = n + 1.0
            return n

        sf = pjit.to_static(f)
        x = paddle.to_tensor(np.array([8.0], np.float32))
        assert float(sf(x)) == 3.0
        x2 = paddle.to_tensor(np.array([32.0], np.float32))
        assert float(sf(x2)) == 5.0


class TestGraphBreakError:
    def test_helper_function_gets_actionable_error(self):
        def helper(x):
            # not converted (called, not the entry fn) AND contains a
            # return inside the branch -> runtime graph-break message
            if x.sum() > 0:
                return x * 2.0
            return x * 3.0

        def f(x):
            return helper(x) + 1.0

        sf = pjit.to_static(f)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        with pytest.raises(RuntimeError, match="tensor-dependent Python control flow"):
            sf(x)

    def test_error_names_options(self):
        def f(x):
            if x.sum() > 0:   # return inside branch -> not converted
                return x * 2.0
            return x

        sf = pjit.to_static(f)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        with pytest.raises(RuntimeError, match="not_to_static"):
            sf(x)


def _module_level_helper(x):
    return x * 7.0


class TestConvertEdgeCases:
    def test_wrapped_functions_left_alone(self):
        import functools

        def deco(g):
            @functools.wraps(g)
            def inner(*a):
                return g(*a)

            return inner

        def add_one(x):
            if x.sum() > 0:
                y = x + 1.0
            else:
                y = x
            return y

        def mul_ten(x):
            if x.sum() > 0:
                y = x * 10.0
            else:
                y = x
            return y

        f1, f2 = dy2static.convert(deco(add_one)), dy2static.convert(deco(mul_ten))
        x = paddle.to_tensor(np.array([3.0], np.float32))
        np.testing.assert_allclose(f1(x).numpy(), [4.0])
        np.testing.assert_allclose(f2(x).numpy(), [30.0])

    def test_late_binding_globals(self):
        def f(x):
            if x.sum() > 0:
                y = _module_level_helper(x)
            else:
                y = x
            return y

        conv = dy2static.convert(f)
        # live globals: monkeypatching the module global is visible
        x = paddle.to_tensor(np.array([2.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [14.0])

    def test_concrete_counter_loop_keeps_grads(self):
        def step(x):
            x.stop_gradient = False
            i = 0
            y = x
            while i < 3:
                y = y * 2.0
                i += 1
            loss = y.sum()
            loss.backward()
            return loss, x.grad

        sf = pjit.to_static(step)
        _, g = sf(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(g.numpy(), [8.0, 8.0])

    def test_del_in_branch_blocks_conversion(self):
        def f(x):
            if True:
                tmp = x + 1.0
                y = tmp * 2.0
                del tmp
            return y

        conv = dy2static.convert(f)
        x = paddle.to_tensor(np.array([3.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [8.0])

    def test_unbound_after_untaken_branch_raises_like_eager(self):
        def f(x, flag):
            if flag:
                y = x * 2.0
            return y

        conv = dy2static.convert(f)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(conv(x, True).numpy(), [2.0])
        with pytest.raises(UnboundLocalError):
            conv(x, False)

    def test_closure_cells_stay_live(self):
        holder = {"scale": 2.0}

        def make():
            scale = paddle.to_tensor(np.array([2.0], np.float32))

            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x
                return y

            return f, (lambda v: None)

        f, _ = make()
        conv = dy2static.convert(f)
        x = paddle.to_tensor(np.array([3.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [6.0])


class TestConvertDirect:
    def test_convert_is_cached_and_identity_safe(self):
        def plain(x):
            return x + 1

        assert dy2static.convert(plain) is plain
        assert dy2static.convert(plain) is plain

    def test_single_branch_assignment_raises_clearly(self):
        def f(x):
            if x.sum() > 0:
                z = x * 2.0
            else:
                w = x * 3.0  # noqa: F841 -- different name on purpose
            return x

        conv = dy2static.convert(f)
        import jax

        with pytest.raises(ValueError, match="only one branch"):
            jax.jit(lambda v: conv(paddle.to_tensor(v))._data + 0)(
                np.array([1.0], np.float32)
            )
