"""Quantized EXECUTION paths: real int8 dots (llm.int8, converted QAT)
and fp8 GEMM — not fake-quant float (ref:
paddle/phi/kernels/impl/llm_int8_matmul_kernel_impl.h,
phi/kernels/fusion/cutlass fp8_gemm)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _jaxpr_has_int8_dot(fn, *args):
    jaxpr = str(jax.make_jaxpr(fn)(*args))
    return "i8[" in jaxpr and "preferred_element_type=int32" in jaxpr


class TestLlmInt8Linear:
    def test_executes_int8_dot(self):
        from paddle_tpu.nn.quant import int8_dynamic_matmul, weight_quantize

        rng = np.random.RandomState(0)
        w = paddle.to_tensor(rng.randn(32, 16).astype(np.float32) * 0.1)
        q, s = weight_quantize(w)
        a = rng.randn(4, 32).astype(np.float32)

        def raw(av):
            return int8_dynamic_matmul(av, q._data, s._data, outlier_threshold=6.0)

        assert _jaxpr_has_int8_dot(raw, a)

    def test_accuracy_vs_float(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        rng = np.random.RandomState(1)
        w = paddle.to_tensor(rng.randn(64, 32).astype(np.float32) * 0.05)
        x = paddle.to_tensor(rng.randn(8, 64).astype(np.float32))
        q, s = weight_quantize(w)
        got = llm_int8_linear(x, q, weight_scale=s).numpy()
        want = (x.numpy() @ w.numpy())
        # int8 weights + int8 activations: ~1% relative error on gaussians
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        assert rel < 0.02, rel

    def test_outlier_split_beats_plain_int8(self):
        """A huge activation outlier column wrecks plain int8 dynamic
        quantization; the llm.int8 top-K float split must recover it."""
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        rng = np.random.RandomState(2)
        w = paddle.to_tensor(rng.randn(64, 32).astype(np.float32) * 0.05)
        x_np = rng.randn(8, 64).astype(np.float32)
        x_np[:, 7] = 80.0  # outlier feature
        x = paddle.to_tensor(x_np)
        q, s = weight_quantize(w)
        want = x_np @ w.numpy()
        with_split = llm_int8_linear(x, q, weight_scale=s, threshold=6.0).numpy()
        no_split = llm_int8_linear(x, q, weight_scale=s, threshold=1e9).numpy()
        err_split = np.abs(with_split - want).mean()
        err_plain = np.abs(no_split - want).mean()
        assert err_split < err_plain / 2, (err_split, err_plain)

    def test_bias(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        rng = np.random.RandomState(3)
        w = paddle.to_tensor(rng.randn(16, 8).astype(np.float32) * 0.1)
        b = paddle.to_tensor(rng.randn(8).astype(np.float32))
        x = paddle.to_tensor(rng.randn(2, 16).astype(np.float32))
        q, s = weight_quantize(w)
        got = llm_int8_linear(x, q, bias=b, weight_scale=s).numpy()
        want = x.numpy() @ w.numpy() + b.numpy()
        assert np.abs(got - want).mean() / np.abs(want).mean() < 0.05


class TestLlmInt8Grads:
    def test_ste_gradient_matches_float_matmul(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        rng = np.random.RandomState(6)
        w = paddle.to_tensor(rng.randn(32, 16).astype(np.float32) * 0.1)
        q, s = weight_quantize(w)
        x = paddle.to_tensor(rng.randn(4, 32).astype(np.float32))
        x.stop_gradient = False
        out = llm_int8_linear(x, q, weight_scale=s)
        out.sum().backward()
        # straight-through: grad == float-matmul grad = row-sum of W_dequant
        w_deq = q.numpy().astype(np.float32) * s.numpy()
        want = np.broadcast_to(w_deq.sum(axis=1), x.shape)
        np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-4, atol=1e-5)


class TestQATInt8Convert:
    def test_convert_int8_runs_int8_and_matches(self):
        from paddle_tpu.quantization import (
            QAT, Int8InferenceLinear, QuantConfig, quanter,
        )

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        float_out = model(x).numpy()

        cfg = QuantConfig(activation=quanter(moving_rate=0.9),
                          weight=quanter(moving_rate=0.9))
        qat = QAT(cfg)
        model = qat.quantize(model)
        model(x)  # observe
        model = qat.convert(model, execute_dtype="int8")
        assert isinstance(model[0], Int8InferenceLinear)
        assert model[0].qweight.numpy().dtype == np.int8
        int8_out = model(x).numpy()
        rel = np.abs(int8_out - float_out).mean() / (np.abs(float_out).mean() + 1e-9)
        assert rel < 0.05, rel

        # the executed program must contain an int8 dot
        lin = model[0]

        def raw(av):
            from paddle_tpu.nn.quant import int8_dynamic_matmul

            return int8_dynamic_matmul(av, lin.qweight._data, lin.scale._data)

        assert _jaxpr_has_int8_dot(raw, x.numpy())

    def test_convert_default_still_folds(self):
        from paddle_tpu.nn import Linear
        from paddle_tpu.quantization import QAT, QuantConfig, quanter

        paddle.seed(1)
        model = nn.Sequential(nn.Linear(8, 8))
        qat = QAT(QuantConfig(activation=None, weight=quanter(moving_rate=0.9)))
        model = qat.quantize(model)
        x = paddle.to_tensor(np.random.RandomState(1).randn(2, 8).astype(np.float32))
        model(x)
        model = qat.convert(model)
        assert isinstance(model[0], Linear)


class TestFP8Gemm:
    def test_fp8_dot_executes_and_tolerates(self):
        import ml_dtypes

        from paddle_tpu.tensor.linalg import fp8_fp8_half_gemm_fused

        rng = np.random.RandomState(4)
        a = rng.randn(8, 32).astype(np.float32) * 0.5
        b = rng.randn(32, 16).astype(np.float32) * 0.5
        out = fp8_fp8_half_gemm_fused(
            paddle.to_tensor(a), paddle.to_tensor(b), output_dtype="bfloat16"
        )
        want = a @ b
        got = out.numpy().astype(np.float32)
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        assert rel < 0.06, rel  # e4m3 has ~2 decimal digits

        def raw(av, bv):
            aa = av.astype(ml_dtypes.float8_e4m3fn)
            bb = bv.astype(ml_dtypes.float8_e4m3fn)
            return jax.lax.dot_general(
                aa, bb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        jaxpr = str(jax.make_jaxpr(raw)(a, b))
        assert "f8_e4m3" in jaxpr

    def test_fp8_act_fusion(self):
        from paddle_tpu.tensor.linalg import fp8_fp8_half_gemm_fused

        rng = np.random.RandomState(5)
        a = rng.randn(4, 16).astype(np.float32)
        b = rng.randn(16, 8).astype(np.float32)
        out = fp8_fp8_half_gemm_fused(
            paddle.to_tensor(a), paddle.to_tensor(b), act="relu"
        ).numpy().astype(np.float32)
        assert (out >= 0).all()
        with pytest.raises(ValueError, match="unsupported act"):
            fp8_fp8_half_gemm_fused(
                paddle.to_tensor(a), paddle.to_tensor(b), act="tanh"
            )


class TestInt8Serving:
    """convert(execute_dtype='int8') wired into the generation decode
    path (ref: llm_int8_matmul_kernel_impl.h): int8 generate must run,
    stay close to the bf16/f32 logits, and keep argmax in the float
    top-5 (greedy match on a RANDOM-init model is a worst-case metric —
    near-tie logits flip under tiny perturbations; BASELINE.md records
    the measured 542M row)."""

    def test_int8_generate_matches_float_logits(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.generation import generate
        from paddle_tpu.quantization import QAT, QuantConfig

        paddle.seed(3)
        cfg_m = LlamaConfig.tiny(num_hidden_layers=2)
        model = LlamaForCausalLM(cfg_m)
        model.eval()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, cfg_m.vocab_size, (4, 12)).astype(np.int64))

        ref_logits = np.asarray(model(ids)._data[:, -1].astype("float32"))
        ref_out = generate(model, ids, max_new_tokens=6, temperature=0.0)

        qat = QAT(QuantConfig(activation=None, weight=None))
        model = qat.quantize(model)
        model = qat.convert(model, execute_dtype="int8")
        int8_logits = np.asarray(model(ids)._data[:, -1].astype("float32"))
        rel = np.abs(int8_logits - ref_logits).mean() / (
            np.abs(ref_logits).mean() + 1e-9)
        assert rel < 0.08, rel
        top5 = np.argsort(ref_logits, -1)[:, -5:]
        hits = sum(int8_logits[i].argmax() in top5[i] for i in range(4))
        assert hits >= 3, hits

        out = generate(model, ids, max_new_tokens=6, temperature=0.0,
                       decode_chunk=4)
        assert out.shape == ref_out.shape  # int8 decode runs end-to-end

    def test_observer_first_scale_is_absmax(self):
        """Regression: accum/state zero-init — one observation must set
        scale == absmax (the old 1.0 init skewed it ~(r+a)/(r+1))."""
        from paddle_tpu.quantization import FakeQuanterWithAbsMaxObserver

        q = FakeQuanterWithAbsMaxObserver(moving_rate=0.9)
        q.train()
        x = paddle.to_tensor(np.array([0.5, -2.0, 1.0], np.float32))
        q(x)
        np.testing.assert_allclose(float(q.scale), 2.0, rtol=1e-6)


class TestWeightOnlyInt4:
    """int4 weight-only path (ref: quantized_linear.py:39,156 with
    weight_only_int4): packed two-per-byte storage, per-channel or
    group-wise scales, exact linear vs the dequantized weight."""

    def test_pack_roundtrip_exact(self):
        from paddle_tpu.nn.quant import (
            weight_dequantize, weight_quantize,
        )

        rng = np.random.RandomState(0)
        w = rng.randn(128, 16).astype(np.float32)
        q, s = weight_quantize(paddle.to_tensor(w),
                               algo="weight_only_int4")
        assert list(q.shape) == [64, 16]  # packed along in-dim
        wd = weight_dequantize(q, s, algo="weight_only_int4",
                               out_dtype="float32").numpy()
        # every dequant value sits on the int4 grid of its channel
        scale = np.asarray(s.numpy())
        grid = np.round(wd / scale[None, :])
        assert np.abs(grid).max() <= 8
        np.testing.assert_allclose(wd, grid * scale[None, :], rtol=1e-5)
        # quant error bounded by half a step per element
        assert np.abs(wd - w).max() <= 0.5 * scale.max() + 1e-6

    @pytest.mark.parametrize("gs", [-1, 64, 128])
    def test_linear_matches_dequant(self, gs):
        from paddle_tpu.nn.quant import (
            weight_dequantize, weight_only_linear, weight_quantize,
        )

        rng = np.random.RandomState(1)
        w = rng.randn(128, 12).astype(np.float32)
        x = rng.randn(5, 128).astype(np.float32)
        q, s = weight_quantize(paddle.to_tensor(w),
                               algo="weight_only_int4", group_size=gs)
        if gs > 0:
            assert list(s.shape) == [128 // gs, 12]
        out = weight_only_linear(paddle.to_tensor(x), q, weight_scale=s,
                                 weight_dtype="int4", group_size=gs)
        # exactness vs the dequantized weight is the op's contract
        if gs > 0:
            sc = np.repeat(np.asarray(s.numpy()), gs, axis=0)
        else:
            sc = np.asarray(s.numpy())[None, :]
        import jax.numpy as jnp

        from paddle_tpu.nn.quant import _unpack_int4

        wd = np.asarray(_unpack_int4(q._data)).astype(np.float32) * sc
        np.testing.assert_allclose(out.numpy(), x @ wd, rtol=2e-4,
                                   atol=2e-4)

    def test_groupwise_beats_or_matches_per_channel_on_outliers(self):
        from paddle_tpu.nn.quant import weight_dequantize, weight_quantize

        rng = np.random.RandomState(2)
        w = rng.randn(128, 8).astype(np.float32)
        w[0, :] *= 50  # an outlier row blows up per-channel scales
        errs = {}
        for gs in (-1, 64):
            q, s = weight_quantize(paddle.to_tensor(w),
                                   algo="weight_only_int4", group_size=gs)
            wd = weight_dequantize(q, s, algo="weight_only_int4",
                                   out_dtype="float32").numpy()
            errs[gs] = np.abs(wd[64:] - w[64:]).mean()  # clean group rows
        # the outlier contaminates only ITS group: the clean group's
        # error must drop to plain-gaussian levels (per-channel scales
        # stay blown up everywhere)
        assert errs[64] < 0.2 * errs[-1], errs

    def test_convert_model_and_serve(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.quant import (
            WeightOnlyLinear, convert_to_weight_only,
        )

        paddle.seed(0)
        m = nn.Sequential(nn.Linear(64, 32), nn.GELU(), nn.Linear(32, 8))
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(4, 64).astype(np.float32))
        ref = m(x).numpy()
        n = convert_to_weight_only(m, weight_dtype="int4")
        assert n == 2
        assert isinstance(m[0], WeightOnlyLinear)
        out = m(x).numpy()
        rel = np.abs(out - ref).mean() / np.abs(ref).mean()
        assert rel < 0.2, rel  # int4 noise, but same function
        # under jit too
        sf = paddle.jit.to_static(lambda t: m(t), layers=[m])
        np.testing.assert_allclose(np.asarray(sf(x).numpy()), out,
                                   rtol=1e-3, atol=1e-3)

    def test_odd_input_dim_rejected(self):
        from paddle_tpu.nn.quant import weight_quantize

        with pytest.raises(ValueError, match="even"):
            weight_quantize(
                paddle.to_tensor(np.zeros((7, 4), np.float32)),
                algo="weight_only_int4")
