"""Elastic sharded-pretrain worker (ISSUE 16 tentpole).

Two modes, selected by ``ELASTIC_SHARD_MODE``:

- ``dist``: run under ``paddle_tpu.distributed.launch`` as one of 2
  processes x 1 device each — the global 2-device ("sharding",) mesh
  CROSSES the process boundary. Each rank trains stage-3 group-sharded
  under a TrainingSupervisor whose peer tier publishes SHARDED
  payloads (each rank ships only its own shards) to the shared
  FileKVStore, with ElasticManager membership and per-step telemetry.
  A ``train.kill_rank.<r>@N=kill`` chaos spec SIGKILLs the named rank
  mid-pretrain.
- ``solo``: one process x 2 devices, same logical ("sharding", 2)
  mesh. Used both for the uninjected reference run and for the
  post-kill relaunch: ElasticManager re-registers (the dead node has
  aged out → world shrinks 2→1, a re-mesh decision), resume() gathers
  BOTH saved ranks' shard payloads from the store and restores through
  the cross-topology reshard, then training continues to the same
  final loss BITWISE (2-way reductions are order-commutative, so the
  cross-process wave and the single-process wave agree to the bit).

Env: ``ELASTIC_DIR`` (shared scratch: KV store + elastic membership),
``TOTAL_STEPS``, ``ELASTIC_SETTLE_S`` (sleep before register so a
killed wave's heartbeats age out).
"""
import os
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
if "jax_num_cpu_devices" in jax.config.values:
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ.get("MC_LOCAL_DEVICES", "2")))
else:
    _n = int(os.environ.get("MC_LOCAL_DEVICES", "2"))
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_n}"
        ).strip()
# gloo only in dist mode: single-process runs have no distributed
# client for the gloo transport to attach to
if (os.environ.get("ELASTIC_SHARD_MODE") == "dist"
        and "jax_cpu_collectives_implementation" in jax.config.values):
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
import paddle_tpu.optimizer as popt  # noqa: E402
from paddle_tpu.base.tensor import Tensor  # noqa: E402
from paddle_tpu.utils.jax_compat import global_device_put  # noqa: E402

SHARD_DEGREE = 2


def batch_fn(index):
    rng = np.random.RandomState(1000 + int(index))
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 8, (8,)).astype(np.int64)
    return x, y


def build_model():
    paddle.seed(31)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))
    opt = popt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    return model, opt


def main():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.distributed.store import FileKVStore
    from paddle_tpu.training.peer_snapshot import PeerReplicator
    from paddle_tpu.training.supervisor import TrainingSupervisor
    from paddle_tpu.training.telemetry import TrainTelemetry
    from paddle_tpu.utils.retries import Deadline

    mode = os.environ.get("ELASTIC_SHARD_MODE", "solo")
    scratch = os.environ["ELASTIC_DIR"]
    total = int(os.environ.get("TOTAL_STEPS", "8"))
    settle = float(os.environ.get("ELASTIC_SETTLE_S", "0"))

    if mode == "dist":
        import paddle_tpu.distributed as dist

        dist.init_parallel_env()
        rank = dist.get_rank()
    else:
        rank = 0
    assert len(jax.devices()) == SHARD_DEGREE, jax.devices()

    # membership: a relaunch waits out the dead wave's heartbeats, so
    # register() sees only the CURRENT incarnation — the world-size
    # decision (2 in the pod wave, 1 after the kill) IS the re-mesh
    if settle > 0:
        time.sleep(settle)
    mgr = ElasticManager(
        os.path.join(scratch, "elastic"), node_id=f"n{rank}",
        np=("2" if mode == "dist" else "1:2"),
        heartbeat_interval=0.2, elastic_timeout=1.2)
    # elastic_timeout is tuned for fast dead-node age-out; assembly of
    # the 2-rank pod needs its own (longer) budget to ride out import
    # and jax-init skew between the launcher's children
    world_nodes = mgr.register(deadline=Deadline(60.0))
    W = len(world_nodes)
    print(f"rank {rank}: ELASTIC world={W} nodes={world_nodes}", flush=True)

    store = FileKVStore(os.path.join(scratch, "store"))
    peer = PeerReplicator(store, rank=rank, world_size=W, tag="esnap")
    telemetry = TrainTelemetry(store, rank, W)

    model, opt = build_model()

    # compiled later (after resume + sharding); the closure keeps the
    # supervisor's step_fn stable across both
    compiled_box = {}
    repl_box = {}

    def step_fn(batch):
        x_np, y_np = batch
        x = Tensor(global_device_put(x_np, repl_box["repl"]),
                   _internal=True)
        y = Tensor(global_device_put(y_np, repl_box["repl"]),
                   _internal=True)
        loss = compiled_box["step"](x, y)
        return float(np.asarray(loss._data))

    sup = TrainingSupervisor(
        step_fn, batch_fn, layers=[model], optimizers=[opt],
        snapshot_interval=2, peer=peer, telemetry=telemetry,
        elastic=mgr, rank=rank, sharded_state=True,
        state_layout={"world": W, "mesh": {"sharding": SHARD_DEGREE}})

    # resume BEFORE placement: the restore writes full host arrays;
    # group_sharded_parallel then places params + restored moments on
    # THIS incarnation's mesh (reshard-on-resume, in RAM)
    nxt = sup.resume()
    print(f"rank {rank}: RESUME next_step={nxt} "
          f"gather_ranks={peer.ranks()}", flush=True)

    for p in model.parameters():
        p._data = np.asarray(p._data)
    model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    mesh, axis = model._group_sharded_mesh
    assert dict(mesh.shape)[axis] == SHARD_DEGREE, mesh
    if mode == "dist":
        assert {d.process_index for d in mesh.devices.flat} == {0, 1}
    repl_box["repl"] = NamedSharding(mesh, P())

    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled_box["step"] = paddle.jit.to_static(
        step, layers=[model], optimizers=[opt])

    report = sup.run(total)
    loss = report["final_loss"]
    h = sup.health()
    wall = h["wall_seconds"]
    print(f"rank {rank}: final_step={report['final_step']}", flush=True)
    print(f"rank {rank}: final_loss={loss!r}", flush=True)
    print(f"rank {rank}: final_loss_hex="
          f"{np.float32(loss).tobytes().hex()}", flush=True)
    print(f"rank {rank}: reshard_resumes={h['reshard_resumes']}",
          flush=True)
    print(f"rank {rank}: elastic_world={h['elastic']['world_size']} "
          f"remesh_events={h['elastic']['remesh_events']}", flush=True)
    print(f"rank {rank}: LEDGER productive={wall['productive']:.4f} "
          f"rollback={wall['rollback']:.4f} "
          f"checkpoint={wall['checkpoint']:.4f} "
          f"stall={wall['stall']:.4f}", flush=True)
    mgr.exit()
    print(f"ESHARD_OK rank {rank}", flush=True)


if __name__ == "__main__":
    main()
