"""io package tests: datasets, samplers, DataLoader (sync + threaded).

Reference patterns: test/legacy_test/test_dataloader_dataset.py,
test_batch_sampler.py, test_multiprocess_dataloader_*.py — coverage of
ordering, drop_last arithmetic, per-rank sharding, and worker-error
propagation.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (
    BatchSampler,
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    DataLoader,
    Dataset,
    DistributedBatchSampler,
    IterableDataset,
    RandomSampler,
    SequenceSampler,
    Subset,
    TensorDataset,
    WeightedRandomSampler,
    random_split,
)


class RangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.float32(i), np.int64(i % 3)

    def __len__(self):
        return self.n


class StreamDataset(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.float32(i)


class TestDatasets:
    def test_tensor_dataset(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
        y = paddle.to_tensor(np.arange(6))
        ds = TensorDataset([x, y])
        assert len(ds) == 6
        xi, yi = ds[2]
        np.testing.assert_array_equal(xi, [4.0, 5.0])
        assert yi == 2

    def test_concat_and_subset(self):
        ds = ConcatDataset([RangeDataset(3), RangeDataset(4)])
        assert len(ds) == 7
        assert ds[5][0] == 2.0  # second dataset, index 2
        sub = Subset(ds, [0, 5])
        assert len(sub) == 2 and sub[1][0] == 2.0

    def test_compose(self):
        ds = ComposeDataset([RangeDataset(3), RangeDataset(3)])
        item = ds[1]
        assert len(item) == 4

    def test_chain(self):
        ds = ChainDataset([StreamDataset(2), StreamDataset(3)])
        assert len(list(ds)) == 5

    def test_random_split(self):
        a, b = random_split(RangeDataset(10), [7, 3])
        assert len(a) == 7 and len(b) == 3
        seen = sorted([a.indices[i] for i in range(7)] + [b.indices[i] for i in range(3)])
        assert seen == list(range(10))
        c, d = random_split(RangeDataset(10), [0.5, 0.5])
        assert len(c) == 5 and len(d) == 5


class TestSamplers:
    def test_sequence(self):
        assert list(SequenceSampler(RangeDataset(4))) == [0, 1, 2, 3]

    def test_random_is_permutation(self):
        idx = list(RandomSampler(RangeDataset(10)))
        assert sorted(idx) == list(range(10))

    def test_weighted(self):
        ws = WeightedRandomSampler([0.0, 1.0, 0.0], num_samples=5)
        assert list(ws) == [1] * 5

    def test_batch_sampler_drop_last(self):
        bs = BatchSampler(dataset=RangeDataset(10), batch_size=3, drop_last=True)
        batches = list(bs)
        assert len(bs) == 3 and all(len(b) == 3 for b in batches)
        bs2 = BatchSampler(dataset=RangeDataset(10), batch_size=3, drop_last=False)
        assert len(bs2) == 4 and len(list(bs2)[-1]) == 1

    def test_distributed_sharding_covers_all(self):
        n, ranks = 11, 4
        all_idx = []
        for r in range(ranks):
            s = DistributedBatchSampler(
                RangeDataset(n), batch_size=2, num_replicas=ranks, rank=r
            )
            for b in s:
                all_idx.extend(b)
        assert len(all_idx) == 12  # padded to 3 per rank
        assert set(all_idx) == set(range(n))

    def test_distributed_set_epoch_changes_order(self):
        s = DistributedBatchSampler(
            RangeDataset(16), batch_size=4, num_replicas=2, rank=0, shuffle=True
        )
        s.set_epoch(0)
        e0 = [i for b in s for i in b]
        s.set_epoch(1)
        e1 = [i for b in s for i in b]
        assert e0 != e1


class TestDataLoader:
    @pytest.mark.parametrize("num_workers", [0, 2])
    def test_order_and_content(self, num_workers):
        dl = DataLoader(
            RangeDataset(10), batch_size=4, num_workers=num_workers, shuffle=False
        )
        batches = list(dl)
        assert len(batches) == 3
        xs = np.concatenate([b[0].numpy() for b in batches])
        np.testing.assert_array_equal(xs, np.arange(10, dtype=np.float32))
        assert batches[0][1].dtype == "int64"

    def test_shuffle_epoch(self):
        dl = DataLoader(RangeDataset(16), batch_size=16, shuffle=True)
        a = next(iter(dl))[0].numpy()
        b = next(iter(dl))[0].numpy()
        assert sorted(a.tolist()) == list(range(16))
        assert not np.array_equal(a, b)

    def test_worker_error_propagates(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(DataLoader(Bad(), batch_size=2, num_workers=2))

    def test_iterable_dataset(self):
        dl = DataLoader(StreamDataset(7), batch_size=3)
        sizes = [len(b.numpy()) for b in dl]
        assert sizes == [3, 3, 1]
        dl2 = DataLoader(StreamDataset(7), batch_size=3, drop_last=True)
        assert [len(b.numpy()) for b in dl2] == [3, 3]

    def test_dict_collate(self):
        class DictDS(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.float32(i), "y": np.ones(2, np.float32) * i}

        b = next(iter(DataLoader(DictDS(), batch_size=4)))
        assert b["x"].shape == [4] and b["y"].shape == [4, 2]

    def test_return_numpy(self):
        dl = DataLoader(RangeDataset(4), batch_size=2, return_numpy=True)
        b = next(iter(dl))
        assert isinstance(b[0], np.ndarray)

    def test_feeds_training_loop(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt

        model = nn.Linear(2, 3)
        optimizer = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        ds = TensorDataset(
            [
                paddle.to_tensor(np.random.RandomState(0).randn(8, 2).astype(np.float32)),
                paddle.to_tensor(np.random.RandomState(1).randint(0, 3, (8,))),
            ]
        )
        dl = DataLoader(ds, batch_size=4, num_workers=2)
        for x, y in dl:
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
        assert np.isfinite(float(loss.numpy()))
