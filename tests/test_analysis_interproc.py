"""graft-verify: the interprocedural rules (ISSUE 5).

Every rule is proven both ways, matching PR 3's bar: >= 2 seeded true
violations it must catch AND >= 2 near-misses it must NOT flag. Plus
the engine mechanics the rules depend on: cross-file resolution,
recursion/budget bail-outs, COLL001 dedup, suppressions, the summary
cache, and the CLI surface (--interprocedural default, --format
github, documented exit codes).

Run standalone via ``pytest -m analysis``.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import analyze_paths, analyze_source

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO_ROOT, "tests", "_coll002_fixture.py")


@pytest.fixture(autouse=True)
def _isolated_summary_cache(tmp_path_factory, monkeypatch):
    """Point the summary disk cache (and the CLI subprocesses, which
    inherit the env) at a throwaway dir — the suite must neither
    pollute the developer's ~/.cache/graft-lint nor depend on what a
    previous checkout wrote there."""
    from paddle_tpu.analysis import interproc

    cache_dir = tmp_path_factory.mktemp("graft-lint-cache")
    monkeypatch.setenv("GRAFT_LINT_CACHE_DIR", str(cache_dir))
    monkeypatch.setattr(interproc, "_mem_cache", {})
    monkeypatch.setattr(interproc, "_disk_loaded", False)
    monkeypatch.setattr(interproc, "_disk_dirty", False)


def findings_for(src, rule, path="fixture.py"):
    return analyze_source(textwrap.dedent(src), path, select=[rule])


def lines_of(findings):
    return [f.line for f in findings]


# ---------------------------------------------------------------------------
# COLL002 — cross-function collective schedule divergence


class TestColl002:
    def test_catches_swapped_schedules_through_helpers(self):
        src = """
        import paddle_tpu.distributed as dist

        def sync_then_publish(t):
            dist.all_reduce(t)
            dist.broadcast(t, src=0)

        def publish_then_sync(t):
            dist.broadcast(t, src=0)
            dist.all_reduce(t)

        def train_step(t, rank):
            if rank == 0:               # line 13: the deadlock
                sync_then_publish(t)
            else:
                publish_then_sync(t)
        """
        got = findings_for(src, "COLL002")
        assert lines_of(got) == [13]
        assert got[0].severity == "error"
        assert "all_reduce -> broadcast" in got[0].message
        assert "broadcast -> all_reduce" in got[0].message
        # COLL001 cannot see it: no collective is textually in a branch
        assert findings_for(src, "COLL001") == []

    def test_catches_one_sided_collective_two_calls_deep(self):
        src = """
        import paddle_tpu.distributed as dist

        def checkpoint(t):
            shard_meta(t)

        def shard_meta(t):
            lst = []
            dist.all_gather(lst, t)

        def maybe_checkpoint(t):
            if dist.get_rank() == 0:    # line 12
                checkpoint(t)
            else:
                log_skip(t)

        def log_skip(t):
            print("skipping", t)
        """
        got = findings_for(src, "COLL002")
        assert lines_of(got) == [12]
        assert "all_gather" in got[0].message
        assert findings_for(src, "COLL001") == []

    def test_near_miss_same_schedule_via_different_helpers(self):
        src = """
        import paddle_tpu.distributed as dist

        def primary_path(t):
            dist.all_reduce(t)
            dist.broadcast(t, src=0)

        def replica_path(t):
            dist.all_reduce(t)
            dist.broadcast(t, src=0)

        def train_step(t, rank):
            if rank == 0:
                primary_path(t)
            else:
                replica_path(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_plain_conditional_collective_variants(self):
        """A data-conditional (non-rank) if/else choosing between two
        all_reduce call sites is ONE collective either way — not a
        sequence of two (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def reduce_maybe_scaled(t, scaled):
            if scaled:
                dist.all_reduce(t * 2)
            else:
                dist.all_reduce(t)

        def train_step(t, rank, scaled):
            if rank == 0:
                reduce_maybe_scaled(t, scaled)
            else:
                dist.all_reduce(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_nested_calls_record_in_evaluation_order(self):
        """`broadcast(all_reduce(t))` executes all_reduce FIRST — the
        fused form and the two-statement form are the same schedule
        (review fix: lexical order would invert nested calls)."""
        src = """
        import paddle_tpu.distributed as dist

        def fused(t):
            dist.broadcast(dist.all_reduce(t), src=0)

        def spelled_out(t):
            dist.all_reduce(t)
            dist.broadcast(t, src=0)

        def train_step(t, rank):
            if rank == 0:
                fused(t)
            else:
                spelled_out(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_rank_conditional_logging_helper(self):
        src = """
        import paddle_tpu.distributed as dist

        def log_metrics(t):
            print("loss", t)

        def train_step(t, rank):
            if rank == 0:
                log_metrics(t)
            dist.all_reduce(t)          # unconditional: every rank
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_looped_collective_is_unknown_multiplicity(self):
        """`for _ in range(2): all_reduce(t)` vs two literal calls is
        the same runtime schedule — loop bodies have statically
        unknown multiplicity, so no finding (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def reduce_rounds(t):
            for _ in range(2):
                dist.all_reduce(t)

        def reduce_twice(t):
            dist.all_reduce(t)
            dist.all_reduce(t)

        def train_step(t, rank):
            if rank == 0:
                reduce_rounds(t)
            else:
                reduce_twice(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_conditional_expression_forks(self):
        """`a(t) if fast else b(t)` runs ONE side — the ternary twin
        of an if/else helper is the same schedule set (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def ternary(t, fast):
            dist.all_reduce(t) if fast else dist.broadcast(t, src=0)

        def spelled(t, fast):
            if fast:
                dist.all_reduce(t)
            else:
                dist.broadcast(t, src=0)

        def train_step(t, rank, fast):
            if rank == 0:
                ternary(t, fast)
            else:
                spelled(t, fast)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_short_circuit_operand_is_optional(self):
        """`ok and dist.all_reduce(t)` may run zero collectives — it
        must not read as an unconditional issue (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def guarded(t, ok):
            return ok and dist.all_reduce(t)

        def plain(t):
            dist.all_reduce(t)

        def train_step(t, rank, ok):
            if rank == 0:
                guarded(t, ok)
            else:
                plain(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_near_miss_except_handler_is_an_alternative_path(self):
        """A retry-once handler's collective is an ALTERNATIVE, not an
        unconditional second issue — the normal paths agree, so no
        finding (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def reduce_with_retry(t):
            try:
                dist.all_reduce(t)
            except RuntimeError:
                dist.all_reduce(t)

        def reduce_plain(t):
            dist.all_reduce(t)

        def train_step(t, rank):
            if rank == 0:
                reduce_with_retry(t)
            else:
                reduce_plain(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_catches_direct_ops_outside_coll001s_vocabulary(self):
        """`gather` vs `reduce` directly in the branches: COLL001's
        set lacks them, so COLL002 must NOT stand down (review fix —
        previously a guaranteed deadlock shipped with zero
        findings)."""
        src = """
        import paddle_tpu.distributed as dist

        def collect(t, rank):
            if rank == 0:
                dist.gather(t)
            else:
                dist.reduce(t)
        """
        assert [f.rule for f in findings_for(src, "COLL002")] == \
            ["COLL002"]
        assert findings_for(src, "COLL001") == []

    def test_direct_mismatch_stays_coll001s_finding(self):
        """A collective textually inside the branch is COLL001's
        report; COLL002 must not double-report the same If."""
        src = """
        import paddle_tpu.distributed as dist

        def train_step(t, rank):
            if rank == 0:
                dist.broadcast(t, src=0)
            return t
        """
        assert findings_for(src, "COLL002") == []
        assert len(findings_for(src, "COLL001")) == 1

    def test_recursion_bails_to_no_finding(self):
        src = """
        import paddle_tpu.distributed as dist

        def ring_pass(t, depth):
            dist.all_reduce(t)
            ring_pass(t, depth - 1)

        def train_step(t, rank):
            if rank == 0:
                ring_pass(t, 3)
            else:
                dist.all_reduce(t)
        """
        assert findings_for(src, "COLL002") == []

    def test_branch_budget_bails_to_no_finding(self):
        """A callee whose rank-conditional forks exceed MAX_SCHEDULES
        possible expansions is *unknown* — no finding, no blow-up."""
        forks = "\n".join(
            f"    if rank == {i}:\n"
            f"        dist.all_reduce(t)\n"
            f"    else:\n"
            f"        dist.broadcast(t, src={i})"
            for i in range(6)  # 2**6 = 64 > MAX_SCHEDULES
        )
        src = (
            "import paddle_tpu.distributed as dist\n\n"
            "def forked(t, rank):\n" + forks + "\n\n"
            "def train_step(t, rank):\n"
            "    if rank == 0:\n"
            "        forked(t, rank)\n"
            "    else:\n"
            "        dist.all_reduce(t)\n"
        )
        assert analyze_source(src, "f.py", select=["COLL002"]) == []

    def test_cross_file_resolution(self, tmp_path):
        (tmp_path / "helpers.py").write_text(textwrap.dedent("""
        import paddle_tpu.distributed as dist

        def grad_sync_helper(t):
            dist.all_reduce(t)
        """))
        (tmp_path / "train.py").write_text(textwrap.dedent("""
        from helpers import grad_sync_helper

        def step(t, rank):
            if rank == 0:
                grad_sync_helper(t)
            else:
                pass
        """))
        got = analyze_paths([str(tmp_path)], select=["COLL002"])
        assert [f.rule for f in got] == ["COLL002"]
        assert got[0].path.endswith("train.py")

    def test_overlapping_path_arguments_do_not_mask_findings(
            self, tmp_path):
        """`graft-lint dir dir/file.py` must not summarize a file
        twice — duplicate summaries would make its functions ambiguous
        and silently disable the interprocedural rules (review fix)."""
        f = tmp_path / "fx.py"
        f.write_text(textwrap.dedent("""
        import paddle_tpu.distributed as dist

        def helper(t):
            dist.all_reduce(t)

        def step(t, rank):
            if rank == 0:
                helper(t)
        """))
        got = analyze_paths([str(tmp_path), str(f)], select=["COLL002"])
        assert [f_.rule for f_ in got] == ["COLL002"]

    def test_file_suppression_applies(self):
        src = """
        # graft-lint: disable=COLL002
        import paddle_tpu.distributed as dist

        def helper(t):
            dist.all_reduce(t)

        def step(t, rank):
            if rank == 0:
                helper(t)
        """
        assert findings_for(src, "COLL002") == []


# ---------------------------------------------------------------------------
# COLL003 — cross-function send/recv peer mismatch


class TestColl003:
    def test_catches_wrong_literal_peer_through_helpers(self):
        src = """
        import paddle_tpu.distributed as dist

        def push_to_worker(t):
            dist.send(t, dst=1)

        def pull_from_master(t):
            dist.recv(t, src=2)         # wrong: master is rank 0

        def exchange(t, rank):
            if rank == 0:               # line 11
                push_to_worker(t)
            else:
                pull_from_master(t)
        """
        got = findings_for(src, "COLL003")
        assert lines_of(got) == [11]
        assert got[0].severity == "error"
        assert "recv(peer=2)" in got[0].message
        assert "rank 0" in got[0].message

    def test_catches_same_direction_pairing(self):
        src = """
        import paddle_tpu.distributed as dist

        def push_grads(t):
            dist.send(t, dst=1)

        def push_metrics(t):
            dist.send(t, dst=0)         # should be recv(src=0)

        def shuffle(t, rank):
            if rank == 0:               # line 11
                push_grads(t)
            else:
                push_metrics(t)
        """
        got = findings_for(src, "COLL003")
        assert lines_of(got) == [11]
        assert "only send" in got[0].message

    def test_near_miss_one_to_many_scatter_counts(self):
        """Rank 0 sending once per peer against each peer's single
        recv is the standard world>2 scatter — count imbalance alone
        is NOT a deadlock (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def fan_out(t):
            dist.send(t, dst=1)
            dist.send(t, dst=2)

        def take_one(t):
            dist.recv(t, src=0)

        def scatter_manual(t, rank):
            if rank == 0:
                fan_out(t)
            else:
                take_one(t)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_correct_pairing_via_helpers(self):
        src = """
        import paddle_tpu.distributed as dist

        def push_to_worker(t):
            dist.send(t, dst=1)

        def pull_from_master(t):
            dist.recv(t, src=0)

        def exchange(t, rank):
            if rank == 0:
                push_to_worker(t)
            else:
                pull_from_master(t)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_dynamic_peers_stay_clean(self):
        src = """
        import paddle_tpu.distributed as dist

        def push(t, peer):
            dist.send(t, dst=peer)

        def pull(t, peer):
            dist.recv(t, src=peer)

        def exchange(t, rank, peer):
            if rank == 0:
                push(t, peer)
            else:
                pull(t, peer)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_plain_branch_in_helper_is_a_fork(self):
        """A NON-rank if/else in a callee runs exactly one side — it
        must not be flattened into 'two sends' (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def push(t, fast):
            if fast:
                dist.send(t, dst=1)
            else:
                dist.send(t, dst=1)

        def pull(t):
            dist.recv(t, src=0)

        def exchange(t, rank, fast):
            if rank == 0:
                push(t, fast)
            else:
                pull(t)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_positional_timeout_is_not_a_peer(self):
        """`eager_recv(src_var, 5000)` — the positional timeout_ms
        must not be misread as the peer rank (review fix)."""
        src = """
        from paddle_tpu.distributed.multi_controller import (
            eager_recv, eager_send)

        def push(t):
            eager_send(t, 1)

        def pull(src_var):
            return eager_recv(src_var, 5000)

        def exchange(t, rank, src_var):
            if rank == 0:
                push(t)
            else:
                pull(src_var)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_p2p_outside_the_branch_pairs_the_rest(self):
        """Unconditional ring send followed by rank-ordered recvs:
        both branches recv-only, but the matching sends sit right
        before the branch — no finding (review fix)."""
        src = """
        import paddle_tpu.distributed as dist

        def recv_left(t):
            dist.recv(t, src=1)

        def recv_right(t):
            dist.recv(t, src=0)

        def ring_exchange(t, rank, world):
            dist.send(t, dst=(rank + 1) % world)
            if rank == 0:
                recv_left(t)
            else:
                recv_right(t)
        """
        assert findings_for(src, "COLL003") == []

    def test_near_miss_balanced_symmetric_exchange(self):
        src = """
        import paddle_tpu.distributed as dist

        def master_side(t):
            dist.send(t, dst=1)
            dist.recv(t, src=1)

        def worker_side(t):
            dist.recv(t, src=0)
            dist.send(t, dst=0)

        def ping_pong(t, rank):
            if rank == 0:
                master_side(t)
            else:
                worker_side(t)
        """
        assert findings_for(src, "COLL003") == []


# ---------------------------------------------------------------------------
# DDL002 — interprocedural Deadline propagation


class TestDdl002:
    def test_catches_unthreaded_deadline_one_hop(self):
        src = """
        from paddle_tpu.utils.retries import Deadline

        def fetch(sock, deadline=None):
            if deadline is not None:
                sock.settimeout(deadline.timeout(5.0))
            return sock.recv(1024)

        def orchestrate(sock):
            return fetch(sock)          # line 10
        """
        got = findings_for(src, "DDL002")
        assert lines_of(got) == [10]
        assert got[0].severity == "warning"
        assert "fetch()" in got[0].message
        assert "deadline=" in got[0].message

    def test_catches_transitively_blocking_callee(self):
        src = """
        from paddle_tpu.utils.retries import Deadline

        def drain(work_q, deadline=None):
            return work_q.get()

        def collect(work_q, deadline=None):
            return drain(work_q, deadline=deadline)

        def top(work_q):
            return collect(work_q)      # line 11: two hops above leaf
        """
        got = findings_for(src, "DDL002")
        assert lines_of(got) == [11]
        assert "collect()" in got[0].message

    def test_near_miss_deadline_threaded(self):
        src = """
        from paddle_tpu.utils.retries import Deadline

        def fetch(sock, deadline=None):
            return sock.recv(1024)

        def orchestrate(sock, dl):
            return fetch(sock, deadline=dl)
        """
        assert findings_for(src, "DDL002") == []

    def test_near_miss_positional_threading(self):
        src = """
        from paddle_tpu.utils.retries import Deadline

        def fetch(sock, deadline=None):
            return sock.recv(1024)

        def orchestrate(sock):
            return fetch(sock, Deadline(5.0))
        """
        assert findings_for(src, "DDL002") == []

    def test_near_miss_callee_without_deadline_param(self):
        """No thread-through point == DDL001's business, not DDL002's."""
        src = """
        from paddle_tpu.utils.retries import Deadline

        def fetch(sock):
            return sock.recv(1024)

        def orchestrate(sock):
            return fetch(sock)
        """
        assert findings_for(src, "DDL002") == []

    def test_near_miss_bounded_callee(self):
        src = """
        from paddle_tpu.utils.retries import Deadline

        def drain(work_q, deadline=None):
            return work_q.get(timeout=5.0)

        def top(work_q):
            return drain(work_q)
        """
        assert findings_for(src, "DDL002") == []

    def test_only_applies_inside_the_retries_discipline(self):
        src = """
        def fetch(sock, deadline=None):
            return sock.recv(1024)

        def orchestrate(sock):
            return fetch(sock)
        """
        assert findings_for(src, "DDL002") == []

    def test_method_call_positional_deadline_accounts_for_self(self):
        """`c.fetch(k, dl)` fills the method's `self` slot with the
        receiver — the positional deadline IS threaded (review fix)."""
        src = """
        from paddle_tpu.utils.retries import Deadline

        class Client:
            def fetch(self, key, deadline=None):
                return self.sock.recv(1024)

        def poll_ok(c, opts):
            return c.fetch("k", opts.ttl)   # positional: threaded

        def poll_bad(c):
            return c.fetch("k")             # line 12: not threaded
        """
        got = findings_for(src, "DDL002")
        assert lines_of(got) == [12]

    def test_blocking_does_not_propagate_through_bounded_calls(self):
        """A wrapper that hard-bounds its blocking callee at the call
        site can never block indefinitely — its own callers stay
        clean (review fix)."""
        src = """
        from paddle_tpu.utils.retries import Deadline

        def drain(work_q, deadline=None):
            return work_q.get()

        def bounded_outer(work_q, deadline=None):
            return drain(work_q, deadline=5.0)

        def top(work_q):
            return bounded_outer(work_q)
        """
        assert findings_for(src, "DDL002") == []


# ---------------------------------------------------------------------------
# The seeded acceptance fixture (shared with the dynamic reproduction in
# tests/test_flight_recorder.py)


class TestSeededDeadlockFixture:
    def test_static_flags_fixture_that_coll001_misses(self):
        got = analyze_paths([FIXTURE], select=["COLL002"])
        assert [f.rule for f in got] == ["COLL002"]
        assert "train_step" in got[0].message
        # no pre-existing rule sees it: full default rule set minus
        # COLL002 is silent on the fixture
        rest = analyze_paths(
            [FIXTURE], ignore=["COLL002"])
        assert rest == [], "\n".join(f.format() for f in rest)


# ---------------------------------------------------------------------------
# Summary cache: per-file mtime/size keys, invalidation


class TestSummaryCache:
    def test_hit_then_invalidate_on_mtime_change(self, tmp_path):
        from paddle_tpu.analysis import interproc

        p = tmp_path / "mod.py"
        p.write_text("def collect_a(t):\n    return t\n")
        s1 = interproc.summarize_path(str(p))
        stats1 = interproc.cache_stats()
        s2 = interproc.summarize_path(str(p))
        stats2 = interproc.cache_stats()
        assert s2 is s1, "unchanged file must be served from cache"
        assert stats2["misses"] == stats1["misses"]
        assert stats2["hits"] == stats1["hits"] + 1

        p.write_text("def collect_b(t):\n    return t\n")
        os.utime(p, (1, 1))  # force a distinct mtime
        s3 = interproc.summarize_path(str(p))
        stats3 = interproc.cache_stats()
        assert stats3["misses"] == stats2["misses"] + 1
        assert [f.name for f in s3.functions] == ["collect_b"]

    def test_cache_hit_rebinds_to_the_requested_path_spelling(
            self, tmp_path, monkeypatch):
        """Findings (and suppression lookups) key by the path the
        caller passed; a cache hit recorded under another spelling
        must be rebound, not returned verbatim (review fix)."""
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""
        import paddle_tpu.distributed as dist

        def helper(t):
            dist.all_reduce(t)

        def step(t, rank):
            if rank == 0:
                helper(t)
        """))
        monkeypatch.chdir(tmp_path)
        f1 = analyze_paths(["m.py"], select=["COLL002"])
        assert [f.path for f in f1] == ["m.py"]
        f2 = analyze_paths([str(p)], select=["COLL002"])  # cache hit
        assert [f.path for f in f2] == [str(p)]

    def test_analysis_lane_stays_fast(self):
        """The whole-package interprocedural pass (warm summaries) must
        stay well inside the pytest -m analysis budget."""
        import time

        from paddle_tpu.analysis import interproc
        from paddle_tpu.analysis.core import iter_python_files

        pkg = os.path.join(REPO_ROOT, "paddle_tpu")
        files = list(iter_python_files([pkg]))
        interproc.build_project([(None, fp) for fp in files])  # warm
        t0 = time.monotonic()
        interproc.build_project([(None, fp) for fp in files])
        assert time.monotonic() - t0 < 10.0


# ---------------------------------------------------------------------------
# CLI surface


class TestInterprocCli:
    def test_interprocedural_is_the_default_and_flags_fixture(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", FIXTURE,
             "--no-baseline"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        assert "COLL002" in proc.stdout

    def test_no_interprocedural_disables_the_pass(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", FIXTURE,
             "--no-baseline", "--no-interprocedural"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_github_format_emits_annotations(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", FIXTURE,
             "--no-baseline", "--format", "github"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("::error "))
        assert "file=" in line and ",line=" in line and ",col=" in line
        assert "title=graft-lint COLL002" in line
        assert "\n" not in line.split("::", 2)[2]

    def test_github_format_escapes_property_values(self, tmp_path):
        """A ','/':' in the linted path must be %-escaped in the
        file= property or GitHub mis-parses the annotation
        (review fix)."""
        odd = tmp_path / "exp:v2,final"
        odd.mkdir()
        bad = odd / "bad.py"
        bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            print(x)
            return x
        """))
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", str(bad),
             "--no-baseline", "--format", "github"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 1
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("::error "))
        props = line.split("::", 2)[1]
        assert "%3A" in props and "%2C" in props
        assert "exp:v2,final" not in props

    def test_github_format_clean_run_exits_zero(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", str(ok),
             "--no-baseline", "--format", "github"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0
        assert "::error" not in proc.stdout

    def test_help_documents_exit_codes(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "--help"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0
        assert "exit status" in proc.stdout
        assert "--format" in proc.stdout
        assert "--no-interprocedural" in proc.stdout

    def test_list_rules_includes_interproc_scope(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "--list-rules"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        for rid in ("COLL002", "COLL003", "DDL002"):
            assert rid in proc.stdout
        assert "interproc" in proc.stdout
