"""sparse.nn stack (ref: python/paddle/sparse/nn/layer/conv.py:304,574;
norm/activation/pooling; phi sparse conv kernels): parity against DENSE
conv3d on fully-active inputs, submanifold semantics, gradients, a
trainable point-cloud classifier, and block-sparse attention parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import sparse as sp

rng = np.random.RandomState(0)


def _full_coo(n, d, h, w, c, seed=0):
    """Fully-active sparse tensor (every voxel stored) + its dense twin
    [N, C, D, H, W] for paddle dense conv3d."""
    r = np.random.RandomState(seed)
    dense_ndhwc = r.randn(n, d, h, w, c).astype(np.float32)
    coords = np.stack(np.meshgrid(
        np.arange(n), np.arange(d), np.arange(h), np.arange(w),
        indexing="ij"), axis=-1).reshape(-1, 4)
    vals = dense_ndhwc[coords[:, 0], coords[:, 1], coords[:, 2], coords[:, 3]]
    x = sp.sparse_coo_tensor(coords.T, vals, shape=[n, d, h, w, c])
    return x, np.moveaxis(dense_ndhwc, -1, 1)  # NCDHW


def _sparse_out_to_dense(y):
    """[N, D, H, W, C] sparse -> NCDHW numpy."""
    return np.moveaxis(np.asarray(y.to_dense().numpy()), -1, 1)


class TestConvParity:
    @pytest.mark.parametrize("stride,padding", [(1, 1), (2, 0), (1, 0)])
    def test_conv3d_matches_dense_on_full_input(self, stride, padding):
        n, d, h, w, ci, co, k = 1, 4, 4, 4, 3, 5, 3
        x, dense = _full_coo(n, d, h, w, ci, seed=1)
        wgt = rng.randn(k, k, k, ci, co).astype(np.float32) * 0.3
        bias = rng.randn(co).astype(np.float32)

        y = sp.nn.functional.conv3d(
            x, paddle.to_tensor(wgt), paddle.to_tensor(bias),
            stride=stride, padding=padding)
        # dense reference: NCDHW conv with OIDHW kernel
        ref = F.conv3d(
            paddle.to_tensor(dense),
            paddle.to_tensor(np.transpose(wgt, (4, 3, 0, 1, 2))),
            paddle.to_tensor(bias), stride=stride, padding=padding)
        got = _sparse_out_to_dense(y)
        np.testing.assert_allclose(got, ref.numpy(), rtol=2e-5, atol=2e-5)

    def test_subm_conv3d_matches_dense_at_active_sites(self):
        """Submanifold conv == dense conv EVALUATED AT the active sites
        when the input is fully active (output coords == input coords)."""
        n, d, h, w, ci, co, k = 1, 3, 4, 4, 2, 4, 3
        x, dense = _full_coo(n, d, h, w, ci, seed=2)
        wgt = rng.randn(k, k, k, ci, co).astype(np.float32) * 0.3
        y = sp.nn.functional.subm_conv3d(
            x, paddle.to_tensor(wgt), stride=1, padding=1)
        assert y.nnz == x.nnz  # submanifold: coords preserved
        ref = F.conv3d(
            paddle.to_tensor(dense),
            paddle.to_tensor(np.transpose(wgt, (4, 3, 0, 1, 2))),
            stride=1, padding=1)
        np.testing.assert_allclose(
            _sparse_out_to_dense(y), ref.numpy(), rtol=2e-5, atol=2e-5)

    def test_subm_keeps_sparsity_partial_input(self):
        """On a PARTIAL active set, subm conv must not dilate it while a
        regular sparse conv does."""
        coords = np.array([[0, 1, 1, 1], [0, 2, 2, 2]]).T
        vals = rng.randn(2, 3).astype(np.float32)
        x = sp.sparse_coo_tensor(coords, vals, shape=[1, 5, 5, 5, 3])
        wgt = paddle.to_tensor(rng.randn(3, 3, 3, 3, 4).astype(np.float32))
        ys = sp.nn.functional.subm_conv3d(x, wgt, padding=1)
        yc = sp.nn.functional.conv3d(x, wgt, padding=1)
        assert ys.nnz == 2
        assert yc.nnz > 2  # regular conv reaches neighboring voxels

    def test_max_pool3d_matches_dense_on_full_input(self):
        n, d, h, w, c = 1, 4, 4, 4, 3
        x, dense = _full_coo(n, d, h, w, c, seed=3)
        y = sp.nn.functional.max_pool3d(x, 2, stride=2)
        ref = F.max_pool3d(paddle.to_tensor(dense), 2, stride=2)
        np.testing.assert_allclose(
            _sparse_out_to_dense(y), ref.numpy(), rtol=1e-6)


class TestGradsAndTraining:
    def test_conv_grads_match_finite_difference(self):
        coords = np.array([[0, 0, 0, 0], [0, 1, 1, 1], [0, 1, 2, 2]]).T
        vals_np = rng.randn(3, 2).astype(np.float64)
        wgt_np = rng.randn(2, 2, 2, 2, 3).astype(np.float64) * 0.5

        def loss_of(w_np):
            x = sp.sparse_coo_tensor(
                coords, vals_np.astype(np.float32), shape=[1, 3, 3, 3, 2])
            w = paddle.to_tensor(w_np.astype(np.float32))
            w.stop_gradient = False
            y = sp.nn.functional.subm_conv3d(x, w, padding=1)
            loss = (y.values() * y.values()).sum()
            return loss, w

        loss, w = loss_of(wgt_np)
        loss.backward()
        g = np.asarray(w.grad.numpy(), np.float64)
        eps = 1e-3
        for idx in [(0, 0, 0, 0, 0), (1, 1, 1, 1, 2), (0, 1, 0, 1, 1)]:
            wp, wm = wgt_np.copy(), wgt_np.copy()
            wp[idx] += eps
            wm[idx] -= eps
            fd = (float(loss_of(wp)[0]) - float(loss_of(wm)[0])) / (2 * eps)
            np.testing.assert_allclose(g[idx], fd, rtol=2e-2, atol=1e-3)

    def test_point_cloud_classifier_trains(self):
        """A SubmConv3D->BN->ReLU->MaxPool->Conv3D->linear head stack
        must train on a tiny synthetic point-cloud task (loss drops by
        >2x over 30 steps)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as popt

        paddle.seed(0)

        class PCNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = sp.nn.SubmConv3D(1, 8, 3, padding=1)
                self.bn1 = sp.nn.BatchNorm(8)
                self.act = sp.nn.ReLU()
                self.pool = sp.nn.MaxPool3D(2, stride=2)
                self.c2 = sp.nn.Conv3D(8, 16, 2, stride=2)
                self.head = nn.Linear(16, 2)

            def forward(self, x):
                y = self.pool(self.act(self.bn1(self.c1(x))))
                y = self.c2(y)
                # global max over the active set -> dense features
                feats = y.values().max(axis=0, keepdim=True)
                return self.head(feats)

        net = PCNet()
        opt = popt.AdamW(learning_rate=5e-3, parameters=net.parameters())

        r = np.random.RandomState(5)
        clouds = []
        for label in (0, 1):
            for _ in range(4):
                npts = 12
                if label == 0:  # diagonal line
                    base = np.arange(npts) % 8
                    coords = np.stack([np.zeros(npts, int), base, base, base], 1)
                else:  # random scatter
                    coords = np.concatenate(
                        [np.zeros((npts, 1), int), r.randint(0, 8, (npts, 3))], 1)
                coords = np.unique(coords, axis=0)
                vals = np.ones((len(coords), 1), np.float32)
                clouds.append((coords, vals, label))

        def step():
            total = 0.0
            for coords, vals, label in clouds:
                x = sp.sparse_coo_tensor(
                    coords.T, vals, shape=[1, 8, 8, 8, 1])
                logits = net(x)
                loss = F.cross_entropy(
                    logits, paddle.to_tensor(np.array([label], np.int64)))
                loss.backward()
                total += float(loss)
            opt.step()
            opt.clear_grad()
            return total / len(clouds)

        first = step()
        for _ in range(29):
            last = step()
        assert last < first / 2, (first, last)


class TestSparseAttention:
    def test_matches_dense_attention_under_mask(self):
        b, hh, s, d = 2, 2, 8, 16
        q = rng.randn(b, hh, s, d).astype(np.float32)
        k = rng.randn(b, hh, s, d).astype(np.float32)
        v = rng.randn(b, hh, s, d).astype(np.float32)
        # banded sparsity pattern as a CSR mask
        mask = (np.abs(np.arange(s)[:, None] - np.arange(s)[None, :]) <= 2)
        mask_t = sp.sparse_csr_tensor(
            *_dense_to_csr_args(mask.astype(np.float32)), shape=[s, s])
        out = sp.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            mask_t)
        # dense reference with -inf masking
        scores = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d)
        scores = np.where(mask[None, None], scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = np.einsum("bhst,bhtd->bhsd", p, v)
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_grads_flow(self):
        s, d = 6, 8
        q = paddle.to_tensor(rng.randn(1, 1, s, d).astype(np.float32))
        q.stop_gradient = False
        k = paddle.to_tensor(rng.randn(1, 1, s, d).astype(np.float32))
        v = paddle.to_tensor(rng.randn(1, 1, s, d).astype(np.float32))
        mask = np.tril(np.ones((s, s), np.float32))
        out = sp.nn.functional.attention(
            q, k, v, paddle.to_tensor(mask))
        out.sum().backward()
        assert np.isfinite(np.asarray(q.grad.numpy())).all()


def _dense_to_csr_args(dense):
    crows = [0]
    cols = []
    vals = []
    for row in dense:
        nz = np.nonzero(row)[0]
        cols.extend(nz.tolist())
        vals.extend(row[nz].tolist())
        crows.append(len(cols))
    return np.asarray(crows, np.int64), np.asarray(cols, np.int64), np.asarray(vals, np.float32)


class TestSparseSoftmax:
    def test_scalar_values_per_row_softmax(self):
        """Scalar-valued 2-D COO: softmax normalizes each ROW's stored
        entries (ref sparse softmax semantics), not the global nnz."""
        coords = np.array([[0, 0], [0, 2], [1, 1], [2, 0], [2, 3]]).T
        vals = np.array([1.0, 2.0, 5.0, 0.5, 0.7], np.float32)
        x = sp.sparse_coo_tensor(coords, vals, shape=[3, 4])
        y = sp.nn.functional.softmax(x)
        out = np.asarray(y.values().numpy())
        # row 0: entries 0,1; row 1: entry 2; row 2: entries 3,4
        np.testing.assert_allclose(out[0] + out[1], 1.0, rtol=1e-6)
        np.testing.assert_allclose(out[2], 1.0, rtol=1e-6)
        np.testing.assert_allclose(out[3] + out[4], 1.0, rtol=1e-6)
        e = np.exp([1.0, 2.0])
        np.testing.assert_allclose(out[:2], e / e.sum(), rtol=1e-6)


def _full_coo_2d(n, h, w, c, seed=0):
    r = np.random.RandomState(seed)
    dense_nhwc = r.randn(n, h, w, c).astype(np.float32)
    coords = np.stack(np.meshgrid(
        np.arange(n), np.arange(h), np.arange(w),
        indexing="ij"), axis=-1).reshape(-1, 3)
    vals = dense_nhwc[coords[:, 0], coords[:, 1], coords[:, 2]]
    x = sp.sparse_coo_tensor(coords.T, vals, shape=[n, h, w, c])
    return x, np.moveaxis(dense_nhwc, -1, 1)  # NCHW


class TestConv2DParity:
    """2-D variants (ref: sparse/nn/layer/conv.py Conv2D/SubmConv2D,
    functional conv2d/subm_conv2d + igemm aliases) over the same
    dimension-generic rulebook."""

    @pytest.mark.parametrize("stride,padding", [(1, 1), (2, 0)])
    def test_conv2d_matches_dense_on_full_input(self, stride, padding):
        n, h, w, ci, co, k = 1, 5, 5, 3, 4, 3
        x, dense = _full_coo_2d(n, h, w, ci, seed=3)
        wgt = rng.randn(k, k, ci, co).astype(np.float32) * 0.3
        bias = rng.randn(co).astype(np.float32)
        y = sp.nn.functional.conv2d(
            x, paddle.to_tensor(wgt), paddle.to_tensor(bias),
            stride=stride, padding=padding)
        ref = F.conv2d(
            paddle.to_tensor(dense),
            paddle.to_tensor(np.transpose(wgt, (3, 2, 0, 1))),
            paddle.to_tensor(bias), stride=stride, padding=padding)
        got = np.moveaxis(np.asarray(y.to_dense().numpy()), -1, 1)
        np.testing.assert_allclose(got, ref.numpy(), rtol=2e-5, atol=2e-5)

    def test_subm_conv2d_keeps_coords_and_matches_dense(self):
        n, h, w, ci, co, k = 1, 4, 6, 2, 3, 3
        x, dense = _full_coo_2d(n, h, w, ci, seed=4)
        wgt = rng.randn(k, k, ci, co).astype(np.float32) * 0.3
        y = sp.nn.functional.subm_conv2d(
            x, paddle.to_tensor(wgt), stride=1, padding=1)
        assert y.nnz == x.nnz
        ref = F.conv2d(
            paddle.to_tensor(dense),
            paddle.to_tensor(np.transpose(wgt, (3, 2, 0, 1))),
            stride=1, padding=1)
        got = np.moveaxis(np.asarray(y.to_dense().numpy()), -1, 1)
        np.testing.assert_allclose(got, ref.numpy(), rtol=2e-5, atol=2e-5)
        # igemm alias is the same path
        y2 = sp.nn.functional.subm_conv2d_igemm(
            x, paddle.to_tensor(wgt), stride=1, padding=1)
        np.testing.assert_allclose(
            np.asarray(y2.values().numpy()), np.asarray(y.values().numpy()),
            rtol=1e-6)

    def test_conv2d_layer_trains(self):
        paddle.seed(0)
        layer = sp.nn.SubmConv2D(2, 4, 3, padding=1)
        x, _ = _full_coo_2d(1, 4, 4, 2, seed=5)
        y = layer(x)
        loss = (y.values() ** 2).sum()
        loss.backward()
        assert layer.weight.grad is not None

    def test_partial_2d_subm_no_dilation(self):
        coords = np.array([[0, 0, 0], [0, 2, 3], [0, 3, 1]]).T
        vals = rng.randn(3, 2).astype(np.float32)
        x = sp.sparse_coo_tensor(coords, vals, shape=[1, 4, 4, 2])
        wgt = paddle.to_tensor(rng.randn(3, 3, 2, 2).astype(np.float32))
        y = sp.nn.functional.subm_conv2d(x, wgt, padding=1)
        assert y.nnz == 3
        y2 = sp.nn.functional.conv2d(x, wgt, padding=1)
        assert y2.nnz > 3  # regular sparse conv dilates
