"""Auto-parallel (DistTensor/ProcessMesh) + distributed checkpoint tests
on the 8-device virtual CPU mesh.

Reference pattern: test/auto_parallel/test_shard_tensor_api.py,
test_reshard_*, test_dist_checkpoint_*.py — placement layouts, reshard
collective semantics (values preserved), save/load across topologies.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import (
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)


@pytest.fixture
def mesh2d():
    return ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])


class TestProcessMesh:
    def test_shape_and_names(self, mesh2d):
        assert mesh2d.shape == [2, 4]
        assert mesh2d.dim_names == ["dp", "mp"]
        assert mesh2d.get_dim_size("mp") == 4
        assert mesh2d.process_ids == list(range(8))

    def test_submesh(self, mesh2d):
        sub = mesh2d.get_mesh_with_dim("mp", 0)
        assert sub.shape == [2] and sub.dim_names == ["dp"]
        moved = mesh2d.get_mesh_with_dim("mp")
        assert moved.shape == [4, 2] and moved.dim_names == ["mp", "dp"]

    def test_bad_dim_names(self):
        with pytest.raises(ValueError):
            ProcessMesh(np.arange(4).reshape(2, 2), dim_names=["a"])


class TestShardTensor:
    def test_layout_and_values(self, mesh2d):
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        d = shard_tensor(x, mesh2d, [Shard(0), Shard(1)])
        assert not d._data.sharding.is_fully_replicated
        np.testing.assert_array_equal(np.asarray(d._data), x)
        assert d.placements == [Shard(0), Shard(1)]
        assert d.process_mesh is mesh2d
        # per-device shard shape: 8/2 x 16/4
        shard_shape = d._data.addressable_shards[0].data.shape
        assert tuple(shard_shape) == (4, 4)

    def test_replicate(self, mesh2d):
        x = np.ones((4, 4), np.float32)
        d = shard_tensor(x, mesh2d, [Replicate(), Replicate()])
        assert d._data.sharding.is_fully_replicated

    def test_reshard_preserves_values(self, mesh2d):
        x = np.random.RandomState(1).randn(8, 8).astype(np.float32)
        d = shard_tensor(x, mesh2d, [Shard(0), Replicate()])
        r = reshard(d, mesh2d, [Replicate(), Shard(1)])
        np.testing.assert_array_equal(np.asarray(r._data), x)
        assert r.placements == [Replicate(), Shard(1)]

    def test_computation_on_dist_tensors(self, mesh2d):
        a = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        b = np.random.RandomState(1).randn(16, 8).astype(np.float32)
        da = shard_tensor(a, mesh2d, [Shard(0), Replicate()])
        db = shard_tensor(b, mesh2d, [Replicate(), Shard(1)])
        out = paddle.matmul(da, db)
        np.testing.assert_allclose(np.asarray(out._data), a @ b, rtol=1e-4, atol=1e-5)

    def test_shard_out_of_range_raises(self, mesh2d):
        with pytest.raises(ValueError):
            shard_tensor(np.ones((4,), np.float32), mesh2d, [Shard(3)])

    def test_grad_flows_through_shard(self, mesh2d):
        x = paddle.to_tensor(np.ones((8, 4), np.float32))
        x.stop_gradient = False
        d = shard_tensor(x, mesh2d, [Shard(0), Replicate()])
        d.sum().backward()
        assert x.grad is not None
        np.testing.assert_array_equal(x.grad.numpy(), np.ones((8, 4)))


class TestShardLayerOptimizer:
    def test_shard_layer_and_optimizer_state(self, mesh2d):
        paddle.seed(0)
        model = nn.Linear(16, 8)

        def shard_fn(name, sublayer, mesh):
            for p in sublayer.parameters(include_sublayers=False):
                placements = [Replicate(), Shard(len(p.shape) - 1)]
                s = shard_tensor(p, mesh, placements)
                p._data = s._data

        shard_layer(model, mesh2d, shard_fn=shard_fn)
        assert not model.weight._data.sharding.is_fully_replicated

        optimizer = shard_optimizer(
            opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        )
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32))
        loss = model(x).sum()
        loss.backward()
        optimizer.step()
        m1 = optimizer._accumulators["moment1"][model.weight.name]
        assert m1.sharding == model.weight._data.sharding

    def test_training_matches_single_device(self, mesh2d):
        def run(shard):
            paddle.seed(3)
            model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
            optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
            if shard:
                shard_layer(model, mesh2d)
                optimizer = shard_optimizer(optimizer)
            losses = []
            rng = np.random.RandomState(0)
            for _ in range(3):
                x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
                y = paddle.to_tensor(rng.randint(0, 4, (8,)))
                loss = nn.functional.cross_entropy(model(x), y)
                loss.backward()
                optimizer.step()
                optimizer.clear_grad()
                losses.append(float(loss.numpy()))
            return losses

        np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


class TestDistCheckpoint:
    def test_save_load_roundtrip_sharded(self, mesh2d, tmp_path):
        from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict

        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        d = shard_tensor(x, mesh2d, [Shard(0), Shard(1)])
        save_state_dict({"w": d, "step": 7}, str(tmp_path))

        target = shard_tensor(np.zeros_like(x), mesh2d, [Shard(0), Shard(1)])
        load_state_dict({"w": target}, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(target._data), x)

    def test_cross_topology_reshard_on_load(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict

        mesh_a = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        mesh_b = ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
        x = np.random.RandomState(2).randn(8, 8).astype(np.float32)
        save_state_dict(
            {"w": shard_tensor(x, mesh_a, [Shard(0), Shard(1)])}, str(tmp_path)
        )
        target = shard_tensor(np.zeros_like(x), mesh_b, [Shard(1), Replicate()])
        load_state_dict({"w": target}, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(target._data), x)
        # layout followed the NEW topology
        assert tuple(target._data.addressable_shards[0].data.shape) == (8, 2)

    def test_nested_and_missing(self, mesh2d, tmp_path):
        from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict

        d = shard_tensor(np.ones((4, 4), np.float32), mesh2d, [Replicate(), Replicate()])
        save_state_dict({"opt": {"m": d}}, str(tmp_path))
        t = shard_tensor(np.zeros((4, 4), np.float32), mesh2d, [Replicate(), Replicate()])
        load_state_dict({"opt": {"m": t}}, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(t._data), np.ones((4, 4)))
        with pytest.raises(KeyError):
            load_state_dict({"nope": t}, str(tmp_path))
