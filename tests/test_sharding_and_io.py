"""Group-sharded (ZeRO 1/2/3) parity tests + paddle.save/load.

Pattern: every sharding stage must reproduce plain single-replica
numerics exactly — on TPU a stage is only a layout policy, so parity is
by construction and these tests pin that invariant (reference pattern:
test/collective/fleet/dygraph_group_sharded_stage{2,3}.py which compare
stage losses against DP losses).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import group_sharded_parallel


def _mlp():
    return nn.Sequential(
        nn.Linear(16, 64),
        nn.GELU(),
        nn.Linear(64, 64),
        nn.GELU(),
        nn.Linear(64, 8),
    )


def _train(model, optimizer, steps=4, use_jit=True):
    rng = np.random.RandomState(0)
    xs = [rng.randn(8, 16).astype(np.float32) for _ in range(steps)]
    ys = [rng.randint(0, 8, (8,)) for _ in range(steps)]

    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        return loss

    if use_jit:
        step = paddle.jit.to_static(step, layers=[model], optimizers=[optimizer])
    losses = []
    for x, y in zip(xs, ys):
        losses.append(float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()))
    return losses


def _baseline_losses():
    paddle.seed(7)
    model = _mlp()
    optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    return _train(model, optimizer)


class TestGroupSharded:
    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_stage_matches_baseline(self, level):
        base = _baseline_losses()

        paddle.seed(7)
        model = _mlp()
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, optimizer, _ = group_sharded_parallel(model, optimizer, level=level)
        losses = _train(model, optimizer)
        np.testing.assert_allclose(losses, base, rtol=1e-5, atol=1e-6)

    def test_stage3_param_layout_is_sharded(self):
        import jax

        paddle.seed(7)
        model = _mlp()
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, optimizer, _ = group_sharded_parallel(model, optimizer, level="p_g_os")
        w = model[0].weight._data
        assert not w.sharding.is_fully_replicated
        # state after a step stays sharded (placement survives donation)
        _train(model, optimizer, steps=1)
        m = optimizer._accumulators["moment1"]
        assert any(not a.sharding.is_fully_replicated for a in m.values())

    def test_save_group_sharded_model(self, tmp_path):
        from paddle_tpu.distributed import save_group_sharded_model

        paddle.seed(7)
        model = _mlp()
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, optimizer, _ = group_sharded_parallel(model, optimizer, level="p_g_os")
        _train(model, optimizer, steps=1)
        out = str(tmp_path / "ckpt")
        save_group_sharded_model(model, out, optimizer=optimizer)
        assert os.path.exists(os.path.join(out, "model.pdmodel"))
        sd = paddle.load(os.path.join(out, "model.pdmodel"))
        assert sd["0.weight"].shape == [16, 64]

    def test_dygraph_sharding_optimizer(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DygraphShardingOptimizer,
        )

        base = _baseline_losses()
        paddle.seed(7)
        model = _mlp()
        inner = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        optimizer = DygraphShardingOptimizer(inner)
        losses = _train(model, optimizer._inner_opt)
        np.testing.assert_allclose(losses, base, rtol=1e-5, atol=1e-6)


class TestSaveLoad:
    def test_state_dict_roundtrip(self, tmp_path):
        paddle.seed(1)
        model = _mlp()
        path = str(tmp_path / "m.pdparams")
        paddle.save(model.state_dict(), path)
        loaded = paddle.load(path)
        paddle.seed(2)
        model2 = _mlp()
        model2.set_state_dict(loaded)
        for (k1, p1), (k2, p2) in zip(
            model.named_parameters(), model2.named_parameters()
        ):
            assert k1 == k2
            np.testing.assert_array_equal(p1.numpy(), p2.numpy())

    def test_optimizer_state_roundtrip_resumes_loss_curve(self, tmp_path):
        paddle.seed(7)
        model = _mlp()
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        _train(model, optimizer, steps=2, use_jit=False)
        paddle.save(model.state_dict(), str(tmp_path / "m.pdparams"))
        paddle.save(optimizer.state_dict(), str(tmp_path / "m.pdopt"))
        cont = _train(model, optimizer, steps=2, use_jit=False)

        paddle.seed(9)
        model2 = _mlp()
        optimizer2 = opt.AdamW(learning_rate=1e-2, parameters=model2.parameters())
        model2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
        optimizer2.set_state_dict(paddle.load(str(tmp_path / "m.pdopt")))
        resumed = _train(model2, optimizer2, steps=2, use_jit=False)
        np.testing.assert_allclose(resumed, cont, rtol=1e-5, atol=1e-6)

    def test_nested_containers_and_scalars(self, tmp_path):
        obj = {
            "t": paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)),
            "nested": [{"a": paddle.to_tensor([1, 2])}, (3, "s")],
            "epoch": 7,
        }
        path = str(tmp_path / "obj.pdz")
        paddle.save(obj, path)
        back = paddle.load(path)
        np.testing.assert_array_equal(back["t"].numpy(), obj["t"].numpy())
        assert back["nested"][1] == (3, "s")
        assert back["epoch"] == 7
        arr = paddle.load(path, return_numpy=True)["t"]
        assert isinstance(arr, np.ndarray)

    def test_bf16_roundtrip(self, tmp_path):
        t = paddle.to_tensor(np.random.RandomState(0).randn(4, 4)).astype("bfloat16")
        path = str(tmp_path / "bf16.pdparams")
        paddle.save({"w": t}, path)
        back = paddle.load(path)["w"]
        assert back.dtype == "bfloat16"
        np.testing.assert_array_equal(
            back.astype("float32").numpy(), t.astype("float32").numpy()
        )

    def test_save_to_dir_raises(self, tmp_path):
        with pytest.raises(ValueError):
            paddle.save({}, str(tmp_path))
