"""Fused AdamW Pallas kernel (ISSUE 17 lever (a)).

Numerics contract: with stochastic rounding OFF the kernel reproduces
the reference ``AdamW._update_param`` math BIT-FOR-BIT against the
JITTED reference expressions (both production paths run under jit —
to_static compiles the train step, and interpret-mode pallas jits
internally — and XLA CPU contracts ``b1*m + (1-b1)*g`` into an FMA
under jit but not in eager dispatch, so the jitted reference is the
honest comparison; the eager deviation is <= 1 ulp). With SR on, the
writeback matches the reference lowbias32 hash element-for-element
given the same salts.

The HBM model: the kernel streams p/g/m/v through VMEM exactly once
(read p+g+m+v, write p+m+v) vs the reference's op-boundary schedule —
asserted >= 2x cheaper for every dtype combo, and handed to the
compiler as ``pl.CostEstimate``.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.amp as amp
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as popt
from paddle_tpu.ops.fused_adamw import (
    fused_adamw_hbm_bytes,
    fused_adamw_update,
    unfused_adamw_hbm_bytes,
)

pytestmark = [pytest.mark.kernels, pytest.mark.quick]

LR, B1, B2, EPS = 1e-2, 0.9, 0.999, 1e-8


def _ref_update(p, g, m, v, *, lr, wd, b1p, b2p, m_store):
    """The reference AdamW._update_param expressions, verbatim
    (beta pows already advanced — matching the kernel's contract)."""
    g32 = g.astype(jnp.float32)
    m32 = m.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    m_new = B1 * m32 + (1 - B1) * g32
    v_new = B2 * v32 + (1 - B2) * g32 * g32
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    delta = lr_t * m_new / (jnp.sqrt(v_new) + EPS * jnp.sqrt(1 - b2p))
    new = p.astype(jnp.float32) * (1.0 - lr * wd) - delta
    return new.astype(p.dtype), m_new.astype(m_store), v_new.astype(m_store)


def _ref_sr(x32, salts):
    """_stochastic_round_bf16's hash with pinned salts (C-order iota)."""
    u = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    i = jax.lax.iota(jnp.uint32, x32.size).reshape(x32.shape)
    b = i * jnp.uint32(0x9E3779B9) + salts[0]
    b = (b ^ (b >> 16)) * jnp.uint32(0x7FEB352D)
    b = (b ^ (b >> 15)) * jnp.uint32(0x846CA68B)
    b = (b ^ (b >> 16)) + salts[1]
    r = jax.lax.bitcast_convert_type(
        (u + (b & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000),
        jnp.float32)
    return jnp.where(jnp.isfinite(x32), r, x32).astype(jnp.bfloat16)


def _inputs(shape, p_dtype, m_dtype, seed=0):
    rng = np.random.RandomState(seed)
    p = jnp.asarray(rng.randn(*shape), p_dtype)
    g = jnp.asarray(0.1 * rng.randn(*shape), p_dtype)
    m = jnp.asarray(0.01 * rng.randn(*shape), m_dtype)
    v = jnp.asarray(0.01 * rng.rand(*shape), m_dtype)
    return p, g, m, v


class TestKernelParity:
    @pytest.mark.parametrize("p_dtype,m_dtype", [
        (jnp.float32, jnp.float32),
        (jnp.float32, jnp.bfloat16),
        (jnp.bfloat16, jnp.bfloat16),
        (jnp.bfloat16, jnp.float32),
    ], ids=["f32", "f32-m_bf16", "bf16", "bf16-m_f32"])
    @pytest.mark.parametrize("wd", [0.0, 0.01], ids=["wd0", "wd.01"])
    def test_bitwise_vs_jitted_reference(self, p_dtype, m_dtype, wd):
        # (37, 19): 703 elements — exercises the lane-grid zero padding
        p, g, m, v = _inputs((37, 19), p_dtype, m_dtype)
        # beta pows are f32 accumulators in production: round FIRST
        # (python-f64 scalars here would change 1-b1p by half an ulp)
        b1p = jnp.asarray(B1 ** 3, jnp.float32)  # step 3
        b2p = jnp.asarray(B2 ** 3, jnp.float32)
        got = fused_adamw_update(
            p, g, m, v, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=b1p, beta2_pow=b2p, weight_decay=wd)
        ref = jax.jit(functools.partial(
            _ref_update, lr=LR, wd=wd, b1p=b1p, b2p=b2p,
            m_store=m_dtype))(p, g, m, v)
        for a, b, name in zip(got, ref, "pmv"):
            assert a.dtype == b.dtype, name
            np.testing.assert_array_equal(
                np.asarray(a).view(np.uint8), np.asarray(b).view(np.uint8),
                err_msg=f"{name} not bitwise-identical")

    def test_multi_tile_grid_bitwise(self):
        # 39000 elements -> 305 rows -> bt=256, grid=(2,): the tile
        # index offset must keep the flat-index bookkeeping exact
        p, g, m, v = _inputs((300, 130), jnp.float32, jnp.float32)
        b1p = jnp.asarray(B1, jnp.float32)
        b2p = jnp.asarray(B2, jnp.float32)
        got = fused_adamw_update(
            p, g, m, v, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=b1p, beta2_pow=b2p)
        ref = jax.jit(functools.partial(
            _ref_update, lr=LR, wd=0.0, b1p=b1p, b2p=b2p,
            m_store=jnp.float32))(p, g, m, v)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sr_writeback_matches_reference_hash(self):
        # multi-tile shape: the global flat index the in-kernel hash
        # sees (tile*bt*128 + row*128 + lane) must equal the
        # reference's C-order iota over the unflattened array
        salts = jnp.asarray([0xDEADBEEF, 0x12345678], jnp.uint32)
        b1p = jnp.asarray(B1, jnp.float32)
        b2p = jnp.asarray(B2, jnp.float32)
        p, g, m, v = _inputs((300, 130), jnp.bfloat16, jnp.bfloat16)
        got_p, _, _ = fused_adamw_update(
            p, g, m, v, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=b1p, beta2_pow=b2p, weight_decay=0.01,
            sr_salts=salts)

        def ref(p, g, m, v):
            new, _, _ = _ref_update(p, g, m, v, lr=LR, wd=0.01,
                                    b1p=B1, b2p=B2, m_store=jnp.float32)
            # reference rounds the pre-cast f32 value
            g32 = g.astype(jnp.float32)
            m_new = B1 * m.astype(jnp.float32) + (1 - B1) * g32
            v_new = B2 * v.astype(jnp.float32) + (1 - B2) * g32 * g32
            lr_t = LR * jnp.sqrt(1 - b2p) / (1 - b1p)
            d = lr_t * m_new / (jnp.sqrt(v_new) + EPS * jnp.sqrt(1 - b2p))
            x32 = p.astype(jnp.float32) * (1.0 - LR * 0.01) - d
            return _ref_sr(x32, salts)

        ref_p = jax.jit(ref)(p, g, m, v)
        np.testing.assert_array_equal(
            np.asarray(got_p).view(np.uint8),
            np.asarray(ref_p).view(np.uint8))

    def test_sr_deterministic_and_salt_sensitive(self):
        p, g, m, v = _inputs((64, 64), jnp.bfloat16, jnp.bfloat16)
        kw = dict(lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
                  beta1_pow=B1, beta2_pow=B2)
        s1 = jnp.asarray([1, 2], jnp.uint32)
        a, _, _ = fused_adamw_update(p, g, m, v, sr_salts=s1, **kw)
        b, _, _ = fused_adamw_update(p, g, m, v, sr_salts=s1, **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c, _, _ = fused_adamw_update(
            p, g, m, v, sr_salts=jnp.asarray([3, 4], jnp.uint32), **kw)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_sr_requires_bf16(self):
        p, g, m, v = _inputs((8, 8), jnp.float32, jnp.float32)
        with pytest.raises(ValueError, match="bf16"):
            fused_adamw_update(
                p, g, m, v, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
                beta1_pow=B1, beta2_pow=B2,
                sr_salts=jnp.zeros((2,), jnp.uint32))

    def test_skip_veto_returns_inputs_bitwise(self):
        for salts in (None, jnp.asarray([9, 9], jnp.uint32)):
            p, g, m, v = _inputs((33, 7), jnp.bfloat16, jnp.bfloat16)
            out = fused_adamw_update(
                p, g, m, v, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
                beta1_pow=B1, beta2_pow=B2, sr_salts=salts,
                skip=jnp.asarray(True))
            for a, b in zip(out, (p, m, v)):
                np.testing.assert_array_equal(
                    np.asarray(a).view(np.uint8),
                    np.asarray(b).view(np.uint8))

    def test_empty_param_noop(self):
        p = jnp.zeros((0,), jnp.float32)
        out = fused_adamw_update(
            p, p, p, p, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=B1, beta2_pow=B2)
        assert all(o.size == 0 for o in out)


class TestHbmModel:
    @pytest.mark.parametrize("p_dtype,m_dtype", [
        (jnp.float32, jnp.float32),
        (jnp.float32, jnp.bfloat16),
        (jnp.bfloat16, jnp.bfloat16),
    ], ids=["f32", "f32-m_bf16", "bf16"])
    def test_fused_at_least_2x_cheaper(self, p_dtype, m_dtype):
        n = 1 << 20
        fused = fused_adamw_hbm_bytes(n, p_dtype, p_dtype, m_dtype)
        unfused = unfused_adamw_hbm_bytes(n, p_dtype, p_dtype, m_dtype)
        assert fused * 2 <= unfused, (fused, unfused)

    def test_model_matches_one_streamed_pass(self):
        # one read of p/g/m/v + one write of p/m/v, nothing else
        n = 1000
        assert fused_adamw_hbm_bytes(
            n, jnp.float32, jnp.float32, jnp.float32) == n * 4 * 7
        assert fused_adamw_hbm_bytes(
            n, jnp.bfloat16, jnp.bfloat16, jnp.bfloat16) == n * 2 * 7

    @pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                        reason="pl.CostEstimate is only authoritative on "
                               "the TPU compile path (interpret mode "
                               "lowers to plain XLA ops)")
    def test_cost_analysis_reports_the_model(self):  # pragma: no cover
        n = 256 * 128
        p = jnp.ones((n,), jnp.float32)
        f = jax.jit(functools.partial(
            fused_adamw_update, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=B1, beta2_pow=B2))
        c = f.lower(p, p, p, p).compile().cost_analysis()
        c = c[0] if isinstance(c, list) else c
        model = fused_adamw_hbm_bytes(n, jnp.float32, jnp.float32,
                                      jnp.float32)
        assert abs(c["bytes accessed"] - model) <= 0.25 * model

    def test_interpret_path_traffic_bounded(self):
        # CPU sanity: the interpret lowering (pad/reshape round trips
        # included) must stay within a small multiple of the model —
        # a second streamed pass sneaking into the kernel would blow
        # straight through this bound (measured ~3.9x on jax 0.4.37)
        n = 1000
        p = jnp.ones((n,), jnp.float32)
        f = jax.jit(functools.partial(
            fused_adamw_update, lr=LR, beta1=B1, beta2=B2, epsilon=EPS,
            beta1_pow=B1, beta2_pow=B2, interpret=True))
        c = f.lower(p, p, p, p).compile().cost_analysis()
        c = c[0] if isinstance(c, list) else c
        model = fused_adamw_hbm_bytes(n, jnp.float32, jnp.float32,
                                      jnp.float32)
        assert c["bytes accessed"] <= 8 * model


def _train(fused, steps=10, interleave=False, scaler=None, seed=3,
           **adamw_kw):
    paddle.seed(seed)
    m = nn.Linear(8, 8)
    o = popt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                   weight_decay=0.01, fused=fused,
                   interleave_updates=interleave, **adamw_kw)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 8).astype(np.float32))
    loss = None
    for _ in range(steps):
        loss = (m(x) ** 2).mean()
        if scaler is not None:
            scaler.scale(loss).backward()
            scaler.step(o)
            scaler.update()
        else:
            loss.backward()
            o.step()
        o.clear_grad()
    return ([np.asarray(p._data) for p in m.parameters()],
            float(np.asarray(loss._data)))


class TestFusedOptimizerBackend:
    def test_tracks_reference_training(self):
        # eager reference vs fused (interpret jits internally): the only
        # deviation is XLA's jit-time FMA contraction, <= 1 ulp/step
        pr, lr_ = _train(False)
        pf, lf = _train(True)
        for a, b in zip(pr, pf):
            np.testing.assert_allclose(a, b, atol=5e-6)
        assert abs(lr_ - lf) < 1e-6

    def test_moment_dtype_bf16_tracks_reference(self):
        pr, _ = _train(False, moment_dtype="bfloat16")
        pf, _ = _train(True, moment_dtype="bfloat16")
        for a, b in zip(pr, pf):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_multi_precision_master_weights(self):
        pr, _ = _train(False, multi_precision=True)
        pf, _ = _train(True, multi_precision=True)
        for a, b in zip(pr, pf):
            np.testing.assert_allclose(a, b, atol=5e-6)

    def test_sr_deterministic_under_fixed_seed(self):
        def run():
            paddle.seed(11)
            m = nn.Linear(8, 8)
            m.bfloat16()
            o = popt.AdamW(learning_rate=1e-2,
                           parameters=m.parameters(), fused=True,
                           use_stochastic_rounding=True)
            x = paddle.to_tensor(np.random.RandomState(0)
                                 .randn(16, 8).astype(np.float32))
            for _ in range(5):
                loss = (m(x.astype("bfloat16")) ** 2).mean()
                loss.backward()
                o.step()
                o.clear_grad()
            return [np.asarray(p._data) for p in m.parameters()]

        a, b = run(), run()
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x.view(np.uint8),
                                          y.view(np.uint8))

    def test_compiled_step_with_donated_state(self):
        # to_static defaults to donate_state=True: the fused backend's
        # accumulator writebacks must be donation-safe (distinct
        # buffers, no aliased reuse of a donated input)
        paddle.seed(5)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 4))
        o = popt.AdamW(learning_rate=1e-2, parameters=model.parameters(),
                       fused=True)

        def body(x, y):
            import paddle_tpu.nn.functional as F
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        compiled = paddle.jit.to_static(body, layers=[model],
                                        optimizers=[o])
        rng = np.random.RandomState(7)
        x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype(np.int64))
        losses = [float(np.asarray(compiled(x, y)._data))
                  for _ in range(6)]
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()


class TestScalerFusedInterleave:
    """GradScaler x interleave_updates seam: fused=True is the one
    interleaved configuration the scaler accepts — the kernel's
    found-inf veto plus the scaler's snapshot rollback keep a skipped
    step bitwise clean even though updates land DURING backward."""

    def test_finite_path_matches_unscaled_reference(self):
        pr, lr_ = _train(False)
        sc = amp.GradScaler(init_loss_scaling=2.0 ** 10)
        pi, li = _train(True, interleave=True, scaler=sc)
        for a, b in zip(pr, pi):
            np.testing.assert_allclose(a, b, atol=5e-6)
        assert abs(lr_ - li) < 1e-6

    def test_inf_grad_leaves_params_bitwise_untouched(self):
        paddle.seed(3)
        m = nn.Linear(8, 8)
        o = popt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                       fused=True, interleave_updates=True)
        sc = amp.GradScaler(init_loss_scaling=2.0 ** 10)
        before = [np.asarray(p._data).copy() for p in m.parameters()]
        # chaos-shaped injection: the batch itself is poisoned, so the
        # inf appears mid-backward — after some layers may already
        # have seen their (vetoed or rolled-back) fused update
        x = paddle.to_tensor(np.full((4, 8), np.inf, np.float32))
        loss = (m(x) ** 2).mean()
        sc.scale(loss).backward()
        sc.step(o)
        sc.update()
        o.clear_grad()
        after = [np.asarray(p._data) for p in m.parameters()]
        for a, b in zip(before, after):
            np.testing.assert_array_equal(a.view(np.uint8),
                                          b.view(np.uint8))
        assert sc.n_skipped_steps == 1

    def test_recovers_after_skipped_step(self):
        paddle.seed(3)
        m = nn.Linear(8, 8)
        o = popt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                       fused=True, interleave_updates=True)
        sc = amp.GradScaler(init_loss_scaling=2.0 ** 10)
        bad = paddle.to_tensor(np.full((4, 8), np.inf, np.float32))
        good = paddle.to_tensor(
            np.random.RandomState(0).randn(16, 8).astype(np.float32))
        losses = []
        for i in range(6):
            x = bad if i == 0 else good
            loss = (m(x) ** 2).mean()
            sc.scale(loss).backward()
            sc.step(o)
            sc.update()
            o.clear_grad()
            if i > 0:
                losses.append(float(np.asarray(loss._data)))
        assert sc.n_skipped_steps == 1
        assert losses[-1] < losses[0]

    def test_non_fused_interleave_still_refused(self):
        paddle.seed(3)
        m = nn.Linear(4, 4)
        o = popt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                       interleave_updates=True)
        assert o._interleave  # keep the registry weakref alive
        sc = amp.GradScaler()
        with pytest.raises(ValueError, match="interleave_updates"):
            sc.scale(paddle.to_tensor(np.float32(1.0)))
