"""Chaos fault-injection harness (paddle_tpu/testing/chaos.py) and the
recovery behaviour it exists to prove.

Covers: seeded schedules are reproducible; each fault kind fires
exactly where scheduled and is observable in the monkey's event log;
the TCP store's reconnect-with-backoff absorbs injected resets; a
dropped heartbeat really loses the beat; a mid-save kill leaves a torn
checkpoint that resume() skips; and the end-to-end recovery contract —
worker killed mid-training → elastic relaunch → auto-checkpoint resume
→ loss parity with an uninterrupted run.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.testing import chaos
from paddle_tpu.testing.chaos import ChaosClock, ChaosSchedule
from paddle_tpu.utils.retries import Deadline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_monkey():
    yield
    chaos.uninstall()


class TestSchedule:
    def test_explicit_plan_fires_exactly_where_scheduled(self):
        s = ChaosSchedule().at("site", 3, "reset").every("other", 2, "drop")
        hits = [s.fault_for("site", i) for i in range(1, 6)]
        assert [h.kind if h else None for h in hits] == [
            None, None, "reset", None, None]
        assert [s.fault_for("other", i) is not None
                for i in range(1, 7)] == [False, True] * 3

    def test_seeded_bernoulli_is_reproducible(self):
        a = ChaosSchedule(seed=42).with_probability("s", 0.3, "hang", 0.01)
        b = ChaosSchedule(seed=42).with_probability("s", 0.3, "hang", 0.01)
        c = ChaosSchedule(seed=43).with_probability("s", 0.3, "hang", 0.01)
        pa = [a.fault_for("s", i) is not None for i in range(1, 200)]
        pb = [b.fault_for("s", i) is not None for i in range(1, 200)]
        pc = [c.fault_for("s", i) is not None for i in range(1, 200)]
        assert pa == pb
        assert pa != pc
        assert 20 < sum(pa) < 100  # actually Bernoulli(0.3)-ish
        # draws depend only on (seed, site, index): query order is free
        assert a.fault_for("s", 150) == b.fault_for("s", 150)

    def test_spec_round_trip(self):
        s = (ChaosSchedule(seed=9)
             .at("store.request", 2, "reset")
             .every("elastic.heartbeat", 3, "drop")
             .with_probability("serving.step", 0.25, "slow", 0.01))
        r = ChaosSchedule.from_spec(s.to_spec())
        assert r.seed == 9
        for site, idx in (("store.request", 2), ("elastic.heartbeat", 6),
                          ("serving.step", 17)):
            assert r.fault_for(site, idx) == s.fault_for(site, idx)

    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            ChaosSchedule().at("s", 1, "explode")


class TestInjection:
    def test_reset_and_drop_and_counts(self):
        with chaos.active(ChaosSchedule()
                          .at("s", 2, "reset").at("s", 3, "drop")) as mk:
            assert chaos.inject("s") is True
            with pytest.raises(ConnectionResetError, match="chaos"):
                chaos.inject("s")
            assert chaos.inject("s") is False  # drop
            assert chaos.inject("s") is True
            assert mk.counts["s"] == 4
            assert mk.events == [("s", 2, "reset"), ("s", 3, "drop")]
        assert chaos.monkey() is None  # uninstalled on exit

    def test_hang_advances_the_chaos_clock_not_wall_time(self):
        clk = ChaosClock()
        with chaos.active(ChaosSchedule().at("s", 1, "hang", 3600.0),
                          clock=clk):
            chaos.inject("s")
        assert clk.now() == 3600.0  # a virtual hour, zero real seconds

    def test_uninstalled_is_a_noop(self):
        assert chaos.inject("anything") is True


class TestStoreChaos:
    def test_tcp_store_retries_through_injected_resets(self):
        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer

        srv = TCPStoreServer(host="127.0.0.1")
        try:
            from paddle_tpu.utils.retries import RetryPolicy

            st = TCPKVStore("127.0.0.1", srv.port,
                            retry=RetryPolicy(max_attempts=4, base_delay=0.01,
                                              transient=TCPKVStore._is_transient))
            # request #2 (the get) is reset twice; the retry layer must
            # absorb both and still return the value
            with chaos.active(ChaosSchedule()
                              .at("store.request", 2, "reset")
                              .at("store.request", 3, "reset")) as mk:
                st.set("k", "v")                     # request 1: clean
                assert st.get("k") == "v"            # requests 2-4: retried
                assert [e[2] for e in mk.events] == ["reset", "reset"]
                assert mk.counts["store.request"] == 4
        finally:
            srv.stop()

    def test_wait_alive_waits_through_restart_and_times_out_when_dead(self):
        import socket as _socket
        import threading

        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        st = TCPKVStore("127.0.0.1", port, timeout=2.0)
        # nothing listening: a bounded wait raises TimeoutError (not a
        # raw ConnectionRefusedError/ValueError leaking through)
        with pytest.raises(TimeoutError, match="not reachable"):
            st.wait_alive(deadline=Deadline(0.5))

        reborn = []
        t = threading.Timer(
            0.3, lambda: reborn.append(
                TCPStoreServer(host="127.0.0.1", port=port)))
        t.start()
        try:
            st.wait_alive(deadline=Deadline(10.0))  # returns once it's up
        finally:
            t.join()
            for srv in reborn:
                srv.stop()

    def test_dropped_request_is_a_lost_message_not_an_empty_reply(self):
        """A chaos 'drop' at store.request must look like a lost
        message (transient failure → retried), never a fabricated None
        response that wait_alive/dump would misread."""
        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer
        from paddle_tpu.utils.retries import RetryPolicy

        srv = TCPStoreServer(host="127.0.0.1")
        try:
            st = TCPKVStore("127.0.0.1", srv.port,
                            retry=RetryPolicy(max_attempts=3, base_delay=0.01,
                                              transient=TCPKVStore._is_transient))
            with chaos.active(ChaosSchedule()
                              .at("store.request", 1, "drop")) as mk:
                st.set("k", "v")  # drop absorbed by retry, op still lands
                assert mk.events == [("store.request", 1, "drop")]
            assert st.get("k") == "v"
        finally:
            srv.stop()

    def test_retry_exhaustion_surfaces_the_reset(self):
        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer
        from paddle_tpu.utils.retries import RetryPolicy

        srv = TCPStoreServer(host="127.0.0.1")
        try:
            st = TCPKVStore("127.0.0.1", srv.port,
                            retry=RetryPolicy(max_attempts=2, base_delay=0.01,
                                              transient=TCPKVStore._is_transient))
            with chaos.active(ChaosSchedule().every("store.request", 1,
                                                    "reset")):
                with pytest.raises(ConnectionError):
                    st.get("k")
        finally:
            srv.stop()

    def test_store_reconnects_after_real_server_restart(self):
        """Not just injected faults: kill the real server between ops;
        the store must ride its retry policy through the new server."""
        import socket as _socket

        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer
        from paddle_tpu.utils.retries import RetryPolicy

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = TCPStoreServer(host="127.0.0.1", port=port)
        st = TCPKVStore("127.0.0.1", port, timeout=5.0,
                        retry=RetryPolicy(max_attempts=10, base_delay=0.05,
                                          transient=TCPKVStore._is_transient))
        st.set("a", "1")
        srv.stop()

        import threading

        reborn = []

        def restart():
            reborn.append(TCPStoreServer(host="127.0.0.1", port=port))

        t = threading.Timer(0.3, restart)
        t.start()
        try:
            # issued while the server is DOWN: retries until the
            # restarted server answers (fresh store: value is gone,
            # but the op succeeds instead of raising into the caller)
            assert st.get("a") is None
        finally:
            t.join()
            for s in reborn:
                s.stop()


class TestAddExactlyOnce:
    def test_replayed_add_rid_does_not_double_increment(self):
        """A retried 'add' whose first RESPONSE was lost must not
        double-increment: the server dedups on the request id and
        replays the cached result (rpc barriers count exact arrivals)."""
        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer

        srv = TCPStoreServer(host="127.0.0.1")
        try:
            st = TCPKVStore("127.0.0.1", srv.port)
            assert st._req(op="add", k="ctr", amount=1, rid="r-1") == 1
            # the retry after a lost reply re-sends the SAME rid
            assert st._req(op="add", k="ctr", amount=1, rid="r-1") == 1
            assert st.get("ctr") == "1"
            assert st.add("ctr", 1) == 2  # fresh rid increments normally
        finally:
            srv.stop()

    def test_replayed_set_if_absent_rid_keeps_the_winner_winning(self):
        """Same lost-reply hazard for the claim op: the retried request
        replays True to the rightful winner instead of telling it the
        key (its own) is already taken."""
        from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer

        srv = TCPStoreServer(host="127.0.0.1")
        try:
            st = TCPKVStore("127.0.0.1", srv.port)
            assert st._req(op="set_if_absent", k="rank/0", v="alice",
                           rid="c-1") is True
            # the winner's retry after a lost reply: still True
            assert st._req(op="set_if_absent", k="rank/0", v="alice",
                           rid="c-1") is True
            # a genuine second claimant still loses
            assert st.set_if_absent("rank/0", "bob") is False
            assert st.get("rank/0") == "alice"
        finally:
            srv.stop()


class TestDroppedSaves:
    def test_dropped_write_saves_nothing(self, tmp_path):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
            AutoCheckpoint,
        )

        paddle.seed(3)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), layers=[m],
                            save_interval_steps=1, async_save=False)
        with chaos.active(ChaosSchedule().at("ckpt.write", 1, "drop")):
            ac.save_now(1, block=True)
        assert os.listdir(str(tmp_path)) == []
        assert ac.resume() == 0

    def test_dropped_publish_leaves_torn_tmp_resume_skips(self, tmp_path):
        """'drop' at ckpt.publish abandons the save after the payload:
        same torn-tmp shape as a mid-save kill, provable in-process."""
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
            AutoCheckpoint,
        )

        paddle.seed(4)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), layers=[m],
                            save_interval_steps=1, async_save=False)
        ac.save_now(1, block=True)
        with chaos.active(ChaosSchedule().at("ckpt.publish", 1, "drop")):
            ac.save_now(2, block=True)
        names = os.listdir(str(tmp_path))
        assert any(n.endswith(".tmp") for n in names), names
        assert ac.resume() == 2  # the step-1 checkpoint, not the torn 2


class TestElasticChaos:
    def test_dropped_heartbeat_loses_the_beat(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(str(tmp_path), node_id="n0", np=1,
                           heartbeat_interval=0.05, elastic_timeout=1.0)
        with chaos.active(ChaosSchedule().at("elastic.heartbeat", 2, "drop")):
            m._beat()  # lands
            v1 = m.store.get("nodes/n0")
            assert v1 is not None
            m._beat()  # dropped: the stored entry must not change
            assert m.store.get("nodes/n0") == v1
            m._beat()  # next beat lands again
            assert m.store.get("nodes/n0") != v1

    def test_register_honors_caller_deadline(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(str(tmp_path), node_id="solo", np=3,
                           heartbeat_interval=0.05, elastic_timeout=60.0)
        dl = Deadline(0.3)
        with pytest.raises(TimeoutError):
            m.register(deadline=dl)  # 0.3s, NOT the 60s elastic_timeout
        assert dl.expired()

    def test_watch_returns_on_deadline_with_membership_intact(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(str(tmp_path), node_id="n0", np=1,
                           heartbeat_interval=0.05, elastic_timeout=5.0)
        m.register()
        try:
            assert m.watch(deadline=Deadline(0.2)) == 0
        finally:
            m.exit()


class TestMidSaveKill:
    def test_kill_between_payload_and_publish_leaves_resumable_state(
            self, tmp_path):
        """A chaos 'kill' at ckpt.publish dies after the payload write
        but before the done marker: the torn tmp must be invisible to
        resume(), which falls back to the previous valid checkpoint."""
        script = (
            "import os\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import paddle_tpu as paddle\n"
            "import paddle_tpu.nn as nn\n"
            "from paddle_tpu.incubate.checkpoint.auto_checkpoint import "
            "AutoCheckpoint\n"
            "paddle.seed(0)\n"
            "m = nn.Linear(4, 2)\n"
            "ac = AutoCheckpoint(os.environ['CKPT_DIR'], layers=[m],\n"
            "                    save_interval_steps=1, async_save=False)\n"
            "ac.save_now(1, block=True)   # valid checkpoint\n"
            "ac.save_now(2, block=True)   # killed mid-save by chaos\n"
            "raise SystemExit('unreachable: chaos kill did not fire')\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep + os.environ.get(
                       "PYTHONPATH", ""),
                   CKPT_DIR=str(tmp_path),
                   PADDLE_CHAOS="ckpt.publish@2=kill:9")
        p = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=240)
        assert p.returncode == 9, (p.returncode, p.stderr[-1500:])
        # the torn save exists on disk but has no done marker
        names = os.listdir(str(tmp_path))
        assert any(n.endswith(".tmp") for n in names), names
        assert not any(n == "ckpt-" + "2".zfill(12) for n in names)

        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
            AutoCheckpoint,
        )

        paddle.seed(0)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), layers=[m], save_interval_steps=1)
        assert ac.resume() == 2  # step-1 checkpoint, NOT the torn step-2


class TestServingDeadlines:
    """Per-request deadlines in the continuous-batching engine. Lazily
    imports the engine (its module chain needs a Pallas-capable jax) and
    SKIPS — visibly, not via a hidden collection error — where that is
    unavailable, so the feature is exercised wherever it can run."""

    @pytest.fixture()
    def serving(self):
        try:
            from paddle_tpu.inference import serving as mod
            from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        except Exception as e:  # noqa: BLE001 — version-gated import chain
            pytest.skip(f"serving engine unavailable here: {e!r}")
        import paddle_tpu as paddle

        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())

        def reference(prompt, max_new):
            from paddle_tpu.models.generation import generate

            ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
            out = generate(model, ids, max_new_tokens=max_new,
                           use_jit=False)
            return list(np.asarray(out.numpy())[0][len(prompt):])

        return mod.ContinuousBatchingEngine, model, reference

    def test_expired_queue_request_is_rejected_at_admission(self, serving):
        """A request whose Deadline lapsed while queued must not burn a
        prefill: it surfaces as completed with status='expired' and no
        tokens."""
        Engine, model, reference = serving
        rng = np.random.RandomState(7)
        clk = ChaosClock()
        eng = Engine(model, max_batch=1, max_len=32, block_size=8,
                     num_blocks=4, prompt_pad=8)
        p = rng.randint(0, 250, (4,))
        eng.add_request("late", p, max_new_tokens=4,
                        deadline=Deadline(1.0, clock=clk))
        eng.add_request("ok", p, max_new_tokens=4)
        clk.advance(2.0)  # "late" expires before any engine step
        done = eng.run()
        assert done["late"].status == "expired"
        assert done["late"].out == []
        assert done["ok"].status == "ok"
        assert done["ok"].out == reference(p, 4)
        assert eng.manager.free_blocks == 4

    def test_expired_inflight_slot_is_evicted_and_blocks_recycle(
            self, serving):
        """One stuck/abandoned client cannot pin a slot: when its budget
        expires mid-decode the slot is evicted, its blocks recycle into
        the next admission, and the survivor's tokens stay exact."""
        Engine, model, reference = serving
        rng = np.random.RandomState(8)
        p_stuck = rng.randint(0, 250, (4,))
        p_live = rng.randint(0, 250, (5,))
        p_next = rng.randint(0, 250, (6,))
        clk = ChaosClock()

        # 4 blocks, 2 per request: "next" NEEDS the eviction to admit
        eng = Engine(model, max_batch=2, max_len=32, block_size=8,
                     num_blocks=4, prompt_pad=8)
        eng.add_request("stuck", p_stuck, max_new_tokens=12,
                        deadline=Deadline(1.0, clock=clk))
        eng.add_request("live", p_live, max_new_tokens=6)
        eng.add_request("next", p_next, max_new_tokens=5)

        eng.step()
        assert eng.num_active == 2  # stuck + live admitted, next waiting
        clk.advance(5.0)  # stuck's budget lapses mid-flight
        eng.step()
        assert eng._completed["stuck"].status == "expired"
        done = eng.run()
        assert set(done) == {"stuck", "live", "next"}
        assert done["live"].out == reference(p_live, 6)
        assert done["next"].out == reference(p_next, 5)
        assert done["next"].status == done["live"].status == "ok"
        assert eng.manager.free_blocks == 4


class TestEndToEndRelaunch:
    """The acceptance contract: kill mid-training via chaos → elastic
    relaunch → auto-checkpoint resume → final loss EQUALS the
    uninterrupted run's (same data schedule)."""

    def _run_worker(self, scratch, total, spec=None):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("PADDLE_CHAOS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["CHAOS_DIR"] = scratch
        env["CHAOS_TOTAL"] = str(total)
        if spec:
            env["PADDLE_CHAOS"] = spec
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tests", "_chaos_worker.py")],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=240)

    @staticmethod
    def _final_loss(stdout):
        for line in stdout.splitlines():
            if "final_loss=" in line:
                return float(line.split("final_loss=")[1])
        return None

    def test_kill_relaunch_resume_loss_parity(self, tmp_path):
        total, kill_step = 14, 10

        ref = self._run_worker(str(tmp_path / "ref"), total)
        assert ref.returncode == 0, ref.stderr[-2000:]
        want = self._final_loss(ref.stdout)
        assert want is not None

        # wave 1: chaos kills the worker at step 10 (checkpoint at 8)
        scratch = str(tmp_path / "el")
        w1 = self._run_worker(
            scratch, total, spec=f"train.step@{kill_step}=kill:17")
        assert w1.returncode == 17, (w1.returncode, w1.stderr[-2000:])
        assert self._final_loss(w1.stdout) is None  # it really died mid-run

        # the relaunch agent (this test — the loop fleet.elastic/launch
        # implement) restarts the job; it resumes and completes
        w2 = self._run_worker(scratch, total)
        assert w2.returncode == 0, w2.stderr[-2000:]
        assert "resumed at step 9" in w2.stdout, w2.stdout
        got = self._final_loss(w2.stdout)
        assert got is not None
        np.testing.assert_allclose(got, want, rtol=1e-7)


class TestPodScaleSites:
    """The ISSUE 16 sites: ``train.kill_rank.<rank>`` (SIGKILL a NAMED
    rank at a scheduled executed step — the pod-scale one-worker-dies
    fault) and ``elastic.remesh`` (force a re-mesh decision with the
    membership intact)."""

    def test_kill_rank_spec_round_trips_and_targets_only_named_rank(self):
        s = (ChaosSchedule(seed=5)
             .at("train.kill_rank.1", 3, "kill")
             .at("elastic.remesh", 2, "drop"))
        r = ChaosSchedule.from_spec(s.to_spec())
        for site in ("train.kill_rank.0", "train.kill_rank.1",
                     "elastic.remesh"):
            for i in range(1, 6):
                a, b = s.fault_for(site, i), r.fault_for(site, i)
                assert (a is None) == (b is None)
                if a is not None:
                    assert (a.kind, a.arg) == (b.kind, b.arg)
        # the schedule names rank 1: rank 0's suffix never draws a fault
        assert all(s.fault_for("train.kill_rank.0", i) is None
                   for i in range(1, 20))
        hit = s.fault_for("train.kill_rank.1", 3)
        assert hit is not None and hit.kind == "kill"

    def test_supervisor_kill_rank_site_kills_exactly_the_named_rank(self):
        # a minimal supervised loop in a child per rank, sharing ONE
        # spec: rank 1 must die by SIGKILL at its 3rd executed step,
        # rank 0 must run to completion untouched
        prog = (
            "import os; os.environ.setdefault('JAX_PLATFORMS','cpu');\n"
            "import numpy as np\n"
            "from paddle_tpu.training.supervisor import TrainingSupervisor\n"
            "sup = TrainingSupervisor(lambda b: float(np.sum(b)),\n"
            "    lambda i: np.ones(2, np.float32) * (1 + 0.01 * i),\n"
            "    rank=int(os.environ['SUP_RANK']), snapshot_interval=100)\n"
            "sup.run(6)\n"
            "print('SUP_DONE step', sup.report()['final_step'])\n"
        )
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["PADDLE_CHAOS"] = "train.kill_rank.1@3=kill"
        out = {}
        for rank in (0, 1):
            env["SUP_RANK"] = str(rank)
            out[rank] = subprocess.run(
                [sys.executable, "-c", prog], env=env, cwd=REPO,
                capture_output=True, text=True, timeout=180)
        assert out[0].returncode == 0, out[0].stderr[-2000:]
        assert "SUP_DONE step 6" in out[0].stdout
        # rc < 0 is the genuine worker-death shape (SIGKILL)
        assert out[1].returncode < 0, (out[1].returncode,
                                       out[1].stderr[-2000:])
        assert "SUP_DONE" not in out[1].stdout

    def test_remesh_drop_forces_world_changed_and_latches_events(
            self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(str(tmp_path), node_id="n0", np=1,
                           heartbeat_interval=0.05, elastic_timeout=5.0)
        m._beat()
        m._registered_world = m.alive_nodes()
        assert m.world_changed() is False
        assert m.remesh_events == 0
        with chaos.active(ChaosSchedule().at("elastic.remesh", 1, "drop")):
            assert m.world_changed() is True  # forced: membership intact
            assert m.remesh_events == 1
            assert m.world_changed() is False  # settles; latch resets
            assert m.remesh_events == 1
        # a REAL membership change counts once however often it is
        # re-polled (watch() asks every beat)
        m.store.delete("nodes/n0")
        assert m.world_changed() is True
        assert m.world_changed() is True
        assert m.remesh_events == 2
        assert m.health()["remesh_events"] == 2
