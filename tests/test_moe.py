"""MoE / expert-parallel tests.

Reference pattern: test/collective/fleet/test_moe_api / incubate moe
tests — routing correctness (top1/top2 combine sums to 1 when under
capacity), capacity overflow drops, aux-loss value, training
convergence, and EP-sharded run matching the replicated run.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.meta_parallel.moe import (
    ExpertMLP,
    MoELayer,
    TopKGate,
    place_experts_on_mesh,
)


class TestGate:
    def test_top1_dispatch_shapes_and_combine(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=1, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        dispatch, combine, l_aux = gate(x)
        assert dispatch.shape == [8, 4, gate.capacity(8)]
        # capacity ample -> every token routed once with weight 1 (top1)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)
        assert float(l_aux.numpy()) > 0

    def test_top2_combine_weights_sum_to_one(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=2, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        _, combine, _ = gate(x)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)

    def test_capacity_overflow_drops_tokens(self):
        paddle.seed(0)
        gate = TopKGate(8, num_experts=2, top_k=1, capacity_factor=0.5)
        # cap = ceil(16/2*0.5) = 4; at most 8 of 16 tokens routable
        x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8).astype(np.float32))
        dispatch, combine, _ = gate(x)
        routed = combine.numpy().sum(axis=(1, 2))
        assert (routed > 0).sum() <= 2 * gate.capacity(16)
        # each expert bucket holds at most one token per slot
        assert dispatch.numpy().sum(axis=(0,)).max() <= 1.0 + 1e-6


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2,
                       capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        out = moe(x)
        assert out.shape == [2, 8, 16]
        assert moe.l_aux is not None

    def test_single_expert_equals_dense_ffn(self):
        """E=1: routing is the identity, MoE must equal its expert MLP."""
        paddle.seed(1)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=1, top_k=1,
                       capacity_factor=100.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 4, 8).astype(np.float32))
        out = moe(x).numpy()

        w1 = np.asarray(moe.experts.w1.numpy())[0]
        w2 = np.asarray(moe.experts.w2.numpy())[0]
        h = np.asarray(jax.nn.gelu(np.asarray(x.numpy()).reshape(4, 8) @ w1))
        ref = (h @ w2).reshape(1, 4, 8)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_trains_and_aux_loss_differentiable(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2)
        head = nn.Linear(16, 4)
        params = list(moe.parameters()) + list(head.parameters())
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=params)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (4, 8)))
        losses = []
        for _ in range(5):
            logits = head(moe(x))
            ce = nn.functional.cross_entropy(
                logits.reshape([32, 4]), y.reshape([32])
            )
            loss = ce + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]
        assert moe.gate.weight.grad is None  # cleared
        # gate received gradient during training (aux + combine paths)

    def test_under_to_static(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4)
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=moe.parameters())

        def step(x):
            loss = moe(x).square().mean() + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, layers=[moe], optimizers=[optimizer])
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        l1 = float(compiled(x).numpy())
        l2 = float(compiled(x).numpy())
        assert np.isfinite(l1) and l2 < l1


class TestExpertParallel:
    def test_ep_sharding_matches_replicated(self):
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology,
            HybridCommunicateGroup,
        )

        def run(shard):
            paddle.seed(5)
            moe = MoELayer(d_model=16, d_hidden=32, num_experts=8, top_k=2,
                           capacity_factor=4.0)
            if shard:
                topo = CommunicateTopology(["dp", "ep"], [2, 4])
                hcg = HybridCommunicateGroup(topo)
                place_experts_on_mesh(moe, hcg.mesh, ep_axis="ep")
                assert not moe.experts.w1._data.sharding.is_fully_replicated
            x = paddle.to_tensor(
                np.random.RandomState(0).randn(2, 8, 16).astype(np.float32)
            )
            return moe(x).numpy()

        np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)
