"""MoE / expert-parallel tests.

Reference pattern: test/collective/fleet/test_moe_api / incubate moe
tests — routing correctness (top1/top2 combine sums to 1 when under
capacity), capacity overflow drops, aux-loss value, training
convergence, and EP-sharded run matching the replicated run.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.meta_parallel.moe import (
    ExpertMLP,
    MoELayer,
    TopKGate,
    place_experts_on_mesh,
)


class TestGate:
    def test_top1_dispatch_shapes_and_combine(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=1, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        dispatch, combine, l_aux = gate(x)
        assert dispatch.shape == [8, 4, gate.capacity(8)]
        # capacity ample -> every token routed once with weight 1 (top1)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)
        assert float(l_aux.numpy()) > 0

    def test_top2_combine_weights_sum_to_one(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=2, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        _, combine, _ = gate(x)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)

    def test_capacity_overflow_drops_tokens(self):
        paddle.seed(0)
        gate = TopKGate(8, num_experts=2, top_k=1, capacity_factor=0.5)
        # cap = ceil(16/2*0.5) = 4; at most 8 of 16 tokens routable
        x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8).astype(np.float32))
        dispatch, combine, _ = gate(x)
        routed = combine.numpy().sum(axis=(1, 2))
        assert (routed > 0).sum() <= 2 * gate.capacity(16)
        # each expert bucket holds at most one token per slot
        assert dispatch.numpy().sum(axis=(0,)).max() <= 1.0 + 1e-6


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2,
                       capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        out = moe(x)
        assert out.shape == [2, 8, 16]
        assert moe.l_aux is not None

    def test_single_expert_equals_dense_ffn(self):
        """E=1: routing is the identity, MoE must equal its expert MLP."""
        paddle.seed(1)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=1, top_k=1,
                       capacity_factor=100.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 4, 8).astype(np.float32))
        out = moe(x).numpy()

        w1 = np.asarray(moe.experts.w1.numpy())[0]
        w2 = np.asarray(moe.experts.w2.numpy())[0]
        h = np.asarray(jax.nn.gelu(np.asarray(x.numpy()).reshape(4, 8) @ w1))
        ref = (h @ w2).reshape(1, 4, 8)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_trains_and_aux_loss_differentiable(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2)
        head = nn.Linear(16, 4)
        params = list(moe.parameters()) + list(head.parameters())
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=params)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (4, 8)))
        losses = []
        for _ in range(5):
            logits = head(moe(x))
            ce = nn.functional.cross_entropy(
                logits.reshape([32, 4]), y.reshape([32])
            )
            loss = ce + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]
        assert moe.gate.weight.grad is None  # cleared
        # gate received gradient during training (aux + combine paths)

    def test_under_to_static(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4)
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=moe.parameters())

        def step(x):
            loss = moe(x).square().mean() + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, layers=[moe], optimizers=[optimizer])
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        l1 = float(compiled(x).numpy())
        l2 = float(compiled(x).numpy())
        assert np.isfinite(l1) and l2 < l1


class TestExpertParallel:
    def test_ep_sharding_matches_replicated(self):
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology,
            HybridCommunicateGroup,
        )

        def run(shard):
            paddle.seed(5)
            moe = MoELayer(d_model=16, d_hidden=32, num_experts=8, top_k=2,
                           capacity_factor=4.0)
            if shard:
                topo = CommunicateTopology(["dp", "ep"], [2, 4])
                hcg = HybridCommunicateGroup(topo)
                place_experts_on_mesh(moe, hcg.mesh, ep_axis="ep")
                assert not moe.experts.w1._data.sharding.is_fully_replicated
            x = paddle.to_tensor(
                np.random.RandomState(0).randn(2, 8, 16).astype(np.float32)
            )
            return moe(x).numpy()

        np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)


class TestSortDispatch:
    """dispatch_mode='sort': scatter dispatch must match the dense
    einsum path when capacity is ample, train, and bound per-expert
    load on overflow."""

    def _pair(self, top_k, cf=4.0, e=4):
        paddle.seed(3)
        a = MoELayer(d_model=16, d_hidden=32, num_experts=e, top_k=top_k,
                     capacity_factor=cf, dispatch_mode="einsum")
        b = MoELayer(d_model=16, d_hidden=32, num_experts=e, top_k=top_k,
                     capacity_factor=cf, dispatch_mode="sort")
        for pb, pa in zip(b.parameters(), a.parameters()):
            pb.set_value(pa)
        return a, b

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_einsum_under_capacity(self, top_k):
        a, b = self._pair(top_k)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, 16).astype(np.float32))
        out_a, out_b = a(x), b(x)
        np.testing.assert_allclose(out_b.numpy(), out_a.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(b.l_aux), float(a.l_aux), rtol=1e-5)

    def test_grads_match_einsum_under_capacity(self):
        a, b = self._pair(2)
        x = np.random.RandomState(2).randn(2, 8, 16).astype(np.float32)
        grads = {}
        for name, m in (("einsum", a), ("sort", b)):
            loss = (m(paddle.to_tensor(x)) ** 2).sum() + 0.1 * m.l_aux
            loss.backward()
            grads[name] = [np.asarray(p.grad.numpy()) for p in m.parameters()]
            for p in m.parameters():
                p.clear_grad()
        for ga, gb in zip(grads["einsum"], grads["sort"]):
            np.testing.assert_allclose(gb, ga, rtol=2e-3, atol=1e-5)

    def test_overflow_bounded_and_trains(self):
        paddle.seed(5)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=2, top_k=1,
                       capacity_factor=0.5, dispatch_mode="sort")
        head = nn.Linear(8, 3)
        o = opt.SGD(learning_rate=0.1,
                    parameters=[*moe.parameters(), *head.parameters()])
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 3, (4, 8)).astype(np.int64))
        import paddle_tpu.nn.functional as F
        from paddle_tpu.tensor import manipulation as M

        losses = []
        for _ in range(30):
            logits = head(moe(x))
            b, s, c = logits.shape
            loss = F.cross_entropy(M.reshape(logits, [b * s, c]),
                                   M.reshape(y, [b * s])) + 0.01 * moe.l_aux
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_sort_under_to_static(self):
        paddle.seed(7)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, top_k=2,
                       dispatch_mode="sort")
        o = opt.SGD(learning_rate=0.05, parameters=moe.parameters())

        def step(x):
            loss = (moe(x) ** 2).mean() + 0.01 * moe.l_aux
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        sf = paddle.jit.to_static(step, layers=[moe], optimizers=[o])
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(2, 4, 8).astype(np.float32))
        l0 = float(sf(x))
        for _ in range(10):
            l1 = float(sf(x))
        assert np.isfinite(l1) and l1 < l0

    def test_overflow_renormalizes_to_survivors(self):
        # identity experts (relu(x@[I,-I]) @ [I;-I] == x) make the layer
        # output w_tok * x where w_tok is the token's total combine
        # weight: post-drop renormalization requires w_tok in {0, 1}
        # even when one of a token's two choices overflowed
        import jax.numpy as jnp

        paddle.seed(9)
        h, e, n = 8, 4, 32
        moe = MoELayer(d_model=h, d_hidden=2 * h, num_experts=e, top_k=2,
                       capacity_factor=0.7, activation="relu",
                       dispatch_mode="sort")
        eye = np.eye(h, dtype=np.float32)
        w1 = np.concatenate([eye, -eye], axis=1)  # [h, 2h]
        w2 = np.concatenate([eye, -eye], axis=0)  # [2h, h]
        moe.experts.w1.set_value(paddle.to_tensor(
            np.broadcast_to(w1, (e, h, 2 * h)).copy()))
        moe.experts.w2.set_value(paddle.to_tensor(
            np.broadcast_to(w2, (e, 2 * h, h)).copy()))
        x_np = np.random.RandomState(4).randn(1, n, h).astype(np.float32)
        out = moe(paddle.to_tensor(x_np)).numpy()[0]
        # per-token weight = out . x / (x . x)
        w_tok = (out * x_np[0]).sum(-1) / (x_np[0] ** 2).sum(-1)
        ok = np.isclose(w_tok, 1.0, atol=1e-4) | np.isclose(
            w_tok, 0.0, atol=1e-4)
        assert ok.all(), w_tok
        # the overflow config must actually drop something
        assert np.isclose(w_tok, 0.0, atol=1e-4).any() or (
            np.abs(out - x_np[0]).max() < 1e-4)
