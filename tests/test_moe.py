"""MoE / expert-parallel tests.

Reference pattern: test/collective/fleet/test_moe_api / incubate moe
tests — routing correctness (top1/top2 combine sums to 1 when under
capacity), capacity overflow drops, aux-loss value, training
convergence, and EP-sharded run matching the replicated run.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.meta_parallel.moe import (
    ExpertMLP,
    MoELayer,
    TopKGate,
    place_experts_on_mesh,
)


class TestGate:
    def test_top1_dispatch_shapes_and_combine(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=1, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        dispatch, combine, l_aux = gate(x)
        assert dispatch.shape == [8, 4, gate.capacity(8)]
        # capacity ample -> every token routed once with weight 1 (top1)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)
        assert float(l_aux.numpy()) > 0

    def test_top2_combine_weights_sum_to_one(self):
        paddle.seed(0)
        gate = TopKGate(16, num_experts=4, top_k=2, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        _, combine, _ = gate(x)
        np.testing.assert_allclose(combine.numpy().sum(axis=(1, 2)), 1.0, rtol=1e-5)

    def test_capacity_overflow_drops_tokens(self):
        paddle.seed(0)
        gate = TopKGate(8, num_experts=2, top_k=1, capacity_factor=0.5)
        # cap = ceil(16/2*0.5) = 4; at most 8 of 16 tokens routable
        x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8).astype(np.float32))
        dispatch, combine, _ = gate(x)
        routed = combine.numpy().sum(axis=(1, 2))
        assert (routed > 0).sum() <= 2 * gate.capacity(16)
        # each expert bucket holds at most one token per slot
        assert dispatch.numpy().sum(axis=(0,)).max() <= 1.0 + 1e-6


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2,
                       capacity_factor=4.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        out = moe(x)
        assert out.shape == [2, 8, 16]
        assert moe.l_aux is not None

    def test_single_expert_equals_dense_ffn(self):
        """E=1: routing is the identity, MoE must equal its expert MLP."""
        paddle.seed(1)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=1, top_k=1,
                       capacity_factor=100.0)
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 4, 8).astype(np.float32))
        out = moe(x).numpy()

        w1 = np.asarray(moe.experts.w1.numpy())[0]
        w2 = np.asarray(moe.experts.w2.numpy())[0]
        h = np.asarray(jax.nn.gelu(np.asarray(x.numpy()).reshape(4, 8) @ w1))
        ref = (h @ w2).reshape(1, 4, 8)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_trains_and_aux_loss_differentiable(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2)
        head = nn.Linear(16, 4)
        params = list(moe.parameters()) + list(head.parameters())
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=params)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (4, 8)))
        losses = []
        for _ in range(5):
            logits = head(moe(x))
            ce = nn.functional.cross_entropy(
                logits.reshape([32, 4]), y.reshape([32])
            )
            loss = ce + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]
        assert moe.gate.weight.grad is None  # cleared
        # gate received gradient during training (aux + combine paths)

    def test_under_to_static(self):
        paddle.seed(0)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=4)
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=moe.parameters())

        def step(x):
            loss = moe(x).square().mean() + 0.01 * moe.l_aux
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, layers=[moe], optimizers=[optimizer])
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        l1 = float(compiled(x).numpy())
        l2 = float(compiled(x).numpy())
        assert np.isfinite(l1) and l2 < l1


class TestExpertParallel:
    def test_ep_sharding_matches_replicated(self):
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology,
            HybridCommunicateGroup,
        )

        def run(shard):
            paddle.seed(5)
            moe = MoELayer(d_model=16, d_hidden=32, num_experts=8, top_k=2,
                           capacity_factor=4.0)
            if shard:
                topo = CommunicateTopology(["dp", "ep"], [2, 4])
                hcg = HybridCommunicateGroup(topo)
                place_experts_on_mesh(moe, hcg.mesh, ep_axis="ep")
                assert not moe.experts.w1._data.sharding.is_fully_replicated
            x = paddle.to_tensor(
                np.random.RandomState(0).randn(2, 8, 16).astype(np.float32)
            )
            return moe(x).numpy()

        np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)


class TestSortDispatch:
    """dispatch_mode='sort': scatter dispatch must match the dense
    einsum path when capacity is ample, train, and bound per-expert
    load on overflow."""

    def _pair(self, top_k, cf=4.0, e=4):
        paddle.seed(3)
        a = MoELayer(d_model=16, d_hidden=32, num_experts=e, top_k=top_k,
                     capacity_factor=cf, dispatch_mode="einsum")
        b = MoELayer(d_model=16, d_hidden=32, num_experts=e, top_k=top_k,
                     capacity_factor=cf, dispatch_mode="sort")
        for pb, pa in zip(b.parameters(), a.parameters()):
            pb.set_value(pa)
        return a, b

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_einsum_under_capacity(self, top_k):
        a, b = self._pair(top_k)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, 16).astype(np.float32))
        out_a, out_b = a(x), b(x)
        np.testing.assert_allclose(out_b.numpy(), out_a.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(b.l_aux), float(a.l_aux), rtol=1e-5)

    def test_grads_match_einsum_under_capacity(self):
        a, b = self._pair(2)
        x = np.random.RandomState(2).randn(2, 8, 16).astype(np.float32)
        grads = {}
        for name, m in (("einsum", a), ("sort", b)):
            loss = (m(paddle.to_tensor(x)) ** 2).sum() + 0.1 * m.l_aux
            loss.backward()
            grads[name] = [np.asarray(p.grad.numpy()) for p in m.parameters()]
            for p in m.parameters():
                p.clear_grad()
        for ga, gb in zip(grads["einsum"], grads["sort"]):
            np.testing.assert_allclose(gb, ga, rtol=2e-3, atol=1e-5)

    def test_overflow_bounded_and_trains(self):
        paddle.seed(5)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=2, top_k=1,
                       capacity_factor=0.5, dispatch_mode="sort")
        head = nn.Linear(8, 3)
        o = opt.SGD(learning_rate=0.1,
                    parameters=[*moe.parameters(), *head.parameters()])
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 3, (4, 8)).astype(np.int64))
        import paddle_tpu.nn.functional as F
        from paddle_tpu.tensor import manipulation as M

        losses = []
        for _ in range(30):
            logits = head(moe(x))
            b, s, c = logits.shape
            loss = F.cross_entropy(M.reshape(logits, [b * s, c]),
                                   M.reshape(y, [b * s])) + 0.01 * moe.l_aux
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_sort_under_to_static(self):
        paddle.seed(7)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, top_k=2,
                       dispatch_mode="sort")
        o = opt.SGD(learning_rate=0.05, parameters=moe.parameters())

        def step(x):
            loss = (moe(x) ** 2).mean() + 0.01 * moe.l_aux
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        sf = paddle.jit.to_static(step, layers=[moe], optimizers=[o])
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(2, 4, 8).astype(np.float32))
        l0 = float(sf(x))
        for _ in range(10):
            l1 = float(sf(x))
        assert np.isfinite(l1) and l1 < l0

    def test_overflow_renormalizes_to_survivors(self):
        # identity experts (relu(x@[I,-I]) @ [I;-I] == x) make the layer
        # output w_tok * x where w_tok is the token's total combine
        # weight: post-drop renormalization requires w_tok in {0, 1}
        # even when one of a token's two choices overflowed
        import jax.numpy as jnp

        paddle.seed(9)
        h, e, n = 8, 4, 32
        moe = MoELayer(d_model=h, d_hidden=2 * h, num_experts=e, top_k=2,
                       capacity_factor=0.7, activation="relu",
                       dispatch_mode="sort")
        eye = np.eye(h, dtype=np.float32)
        w1 = np.concatenate([eye, -eye], axis=1)  # [h, 2h]
        w2 = np.concatenate([eye, -eye], axis=0)  # [2h, h]
        moe.experts.w1.set_value(paddle.to_tensor(
            np.broadcast_to(w1, (e, h, 2 * h)).copy()))
        moe.experts.w2.set_value(paddle.to_tensor(
            np.broadcast_to(w2, (e, 2 * h, h)).copy()))
        x_np = np.random.RandomState(4).randn(1, n, h).astype(np.float32)
        out = moe(paddle.to_tensor(x_np)).numpy()[0]
        # per-token weight = out . x / (x . x)
        w_tok = (out * x_np[0]).sum(-1) / (x_np[0] ** 2).sum(-1)
        ok = np.isclose(w_tok, 1.0, atol=1e-4) | np.isclose(
            w_tok, 0.0, atol=1e-4)
        assert ok.all(), w_tok
        # the overflow config must actually drop something
        assert np.isclose(w_tok, 0.0, atol=1e-4).any() or (
            np.abs(out - x_np[0]).max() < 1e-4)


class TestExpertAwareGradClip:
    """ROADMAP 5b: ClipGradForMOEByGlobalNorm — the reference
    moe/grad_clip.py behavior our module docstring cites. Plain
    ClipGradByGlobalNorm under real EP sees only the local expert
    shard's grad mass; the MoE clip folds the cross-rank expert
    norm back in so every rank applies the SAME scale."""

    def _params_grads(self, seed=0):
        """A (dp, ep)-style parameter set: ep-sharded stacked experts
        (ep_axis tagged) + replicated dense params, with fixed grads."""
        from paddle_tpu.base.tensor import Tensor

        rng = np.random.RandomState(seed)
        paddle.seed(3)
        experts = ExpertMLP(num_experts=4, d_model=8, d_hidden=16)
        dense = nn.Linear(8, 8)
        pg = []
        for p in list(experts.parameters()) + list(dense.parameters()):
            g = Tensor(rng.randn(*p.shape).astype(np.float32),
                       _internal=True)
            pg.append((p, g))
        return pg

    def test_single_controller_parity_vs_dense_clip(self):
        """Stacked global expert arrays (this repo's default): the MoE
        clip must equal ClipGradByGlobalNorm EXACTLY — same norm, same
        scale, same clipped grads."""
        from paddle_tpu.distributed.fleet.meta_parallel.moe import (
            ClipGradForMOEByGlobalNorm,
        )
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm

        pg = self._params_grads()
        ref = ClipGradByGlobalNorm(clip_norm=0.5)(
            [(p, g) for p, g in pg])
        got = ClipGradForMOEByGlobalNorm(clip_norm=0.5)(pg)
        for (_, a), (_, b) in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a.numpy()), np.asarray(b.numpy()),
                rtol=1e-6, atol=1e-7)

    def test_parity_on_dp_ep_mesh(self):
        """Same check with the experts actually device_put-sharded over
        the ep axis of a (dp, ep) mesh — jax global arrays keep the
        math identical regardless of placement."""
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology,
            HybridCommunicateGroup,
        )
        from paddle_tpu.distributed.fleet.meta_parallel.moe import (
            ClipGradForMOEByGlobalNorm,
        )
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm

        pg = self._params_grads(seed=1)
        topo = CommunicateTopology(["dp", "ep"], [2, 4])
        hcg = HybridCommunicateGroup(topo)
        experts_holder = [p for p, _ in pg if getattr(p, "ep_axis", None)
                          is not None]
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec

        for p in experts_holder:
            spec = [None] * p._data.ndim
            spec[p.ep_axis] = "ep"
            p._data = _jax.device_put(
                p._data, NamedSharding(hcg.mesh, PartitionSpec(*spec)))
        ref = ClipGradByGlobalNorm(clip_norm=0.3)([(p, g) for p, g in pg])
        got = ClipGradForMOEByGlobalNorm(clip_norm=0.3)(pg)
        for (_, a), (_, b) in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a.numpy()), np.asarray(b.numpy()),
                rtol=1e-6, atol=1e-7)

    def test_simulated_ep_ranks_match_dense_global_norm(self):
        """The cross-rank math itself: two simulated EP ranks each hold
        HALF the experts; with the peer's expert sq-norm folded in
        (the allreduce seam), every rank's scale must equal the dense
        full-expert clip — and WITHOUT it (plain clip per rank) it
        provably does not, which is the silent wrongness 5b names."""
        from paddle_tpu.base.tensor import Tensor
        from paddle_tpu.distributed.fleet.meta_parallel.moe import (
            ClipGradForMOEByGlobalNorm,
        )
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm

        pg = self._params_grads(seed=2)
        expert_pg = [(p, g) for p, g in pg
                     if getattr(p, "ep_axis", None) is not None]
        dense_pg = [(p, g) for p, g in pg
                    if getattr(p, "ep_axis", None) is None]
        # dense reference over the FULL parameter set
        full = ClipGradForMOEByGlobalNorm(clip_norm=0.25)(pg)

        # build per-rank views: expert grads split over dim ep_axis
        def rank_view(rank):
            halves = []
            for p, g in expert_pg:
                e = p.shape[p.ep_axis]
                lo, hi = (0, e // 2) if rank == 0 else (e // 2, e)
                gp = Tensor(np.asarray(g.numpy())[lo:hi].copy(),
                            _internal=True)
                pp = type("P", (), {})()  # stub param carrying the tag
                pp.ep_axis = p.ep_axis
                pp.need_clip = True
                halves.append((pp, gp))
            return halves + dense_pg

        def peer_expert_sq(rank):
            other = rank_view(1 - rank)
            return sum(
                float((np.asarray(g.numpy(), np.float64) ** 2).sum())
                for p, g in other if getattr(p, "ep_axis", None) is not None)

        class TwoRankClip(ClipGradForMOEByGlobalNorm):
            """allreduce seam override: add the (precomputed) peer
            contribution — exactly what distributed.all_reduce does
            over a real 2-rank ep group."""

            def __init__(self, peer_sq, **kw):
                super().__init__(**kw)
                self.peer_sq = peer_sq

            def _reduce_expert_sq(self, sq):
                return sq + float(self.peer_sq)

        for rank in (0, 1):
            got = TwoRankClip(peer_expert_sq(rank), clip_norm=0.25)(
                rank_view(rank))
            # dense params are replicated: their clipped grads must be
            # BITWISE-identical to the full dense reference on every
            # rank (the desync the naive clip causes)
            got_dense = [g for p, g in got
                         if getattr(p, "ep_axis", None) is None]
            ref_dense = [g for p, g in full
                         if getattr(p, "ep_axis", None) is None]
            for a, b in zip(got_dense, ref_dense):
                np.testing.assert_allclose(
                    np.asarray(a.numpy()), np.asarray(b.numpy()),
                    rtol=1e-6, atol=1e-7)
            # expert shards must equal the corresponding slice of the
            # full reference
            got_exp = [(p, g) for p, g in got
                       if getattr(p, "ep_axis", None) is not None]
            ref_exp = [(p, g) for p, g in full
                       if getattr(p, "ep_axis", None) is not None]
            for (pp, a), (p, b) in zip(got_exp, ref_exp):
                e = p.shape[p.ep_axis]
                lo, hi = (0, e // 2) if rank == 0 else (e // 2, e)
                np.testing.assert_allclose(
                    np.asarray(a.numpy()),
                    np.asarray(b.numpy())[lo:hi],
                    rtol=1e-6, atol=1e-7)
            # and the NAIVE per-rank clip disagrees (the bug exists)
            naive = ClipGradByGlobalNorm(clip_norm=0.25)(rank_view(rank))
            naive_dense = [g for p, g in naive
                           if getattr(p, "ep_axis", None) is None]
            assert not np.allclose(
                np.asarray(naive_dense[0].numpy()),
                np.asarray(ref_dense[0].numpy()))

    def test_optimizer_integration(self):
        """The clip slots into the optimizer's grad_clip hook."""
        from paddle_tpu.distributed.fleet.meta_parallel.moe import (
            ClipGradForMOEByGlobalNorm,
        )

        paddle.seed(4)
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, top_k=2)
        sgd = opt.SGD(learning_rate=0.1, parameters=moe.parameters(),
                      grad_clip=ClipGradForMOEByGlobalNorm(clip_norm=0.1))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4, 8).astype(np.float32))
        loss = (moe(x) ** 2).mean() + moe.l_aux
        loss.backward()
        before = np.asarray(moe.experts.w1.numpy()).copy()
        sgd.step()
        after = np.asarray(moe.experts.w1.numpy())
        assert not np.allclose(before, after)
