"""Flight-recorder contract worker (plain subprocess, 2 ranks).

Usage: ``python _fr_worker.py RANK STORE_PORT MODE``

Each rank records the collective schedule its program issues into the
collective flight recorder, then runs ``collective_contract()``
against the parent's TCPStoreServer. Modes:

- ``fixture``: execute ``_coll002_fixture.train_step`` — the seeded
  cross-function deadlock. The rank branches issue swapped schedules,
  so the contract must raise on BOTH ranks.
- ``reorder``: both ranks run the IDENTICAL program (all_reduce then
  broadcast); the parent sets ``PADDLE_CHAOS=comm.reorder@1=drop`` for
  rank 1 only, so the chaos site defers rank 1's all_reduce behind its
  broadcast — the dynamically injected schedule swap the contract must
  catch.

Exit codes: 0 = schedules agreed; 3 = CollectiveScheduleMismatch (the
expected outcome for both modes; the diff is printed to stdout);
anything else = harness failure.

The ``dist`` shim records signatures exactly where the real
multi-controller eager collectives would (the instrumented
``multi_controller._record`` path) without needing a JAX coordination
service — the contract and recorder are transport-independent.
"""
import sys


class RecordingDist:
    """Schedule-recording stand-in for paddle_tpu.distributed: each
    call appends the signature the real eager collective would."""

    def __init__(self, fr):
        self._fr = fr

    def all_reduce(self, t):
        self._fr.record("all_reduce[sum]", (2,), "float32")

    def broadcast(self, t, src=0):
        self._fr.record("broadcast", (2,), "float32", detail=f"src={src}")


def main():
    rank, port, mode = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from paddle_tpu.analysis import (
        CollectiveScheduleMismatch,
        collective_contract,
    )
    from paddle_tpu.distributed.communication import flight_recorder as fr
    from paddle_tpu.distributed.store import TCPKVStore

    dist = RecordingDist(fr)
    if mode == "fixture":
        from _coll002_fixture import train_step

        train_step(dist, object(), rank)
    elif mode == "reorder":
        # identical program on every rank — only the chaos injection
        # (installed from PADDLE_CHAOS on rank 1) diverges the record
        dist.all_reduce(None)
        dist.broadcast(None, src=0)
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    store = TCPKVStore("127.0.0.1", port)
    try:
        collective_contract(store, rank, 2, last_n=8, deadline=60.0)
    except CollectiveScheduleMismatch as e:
        print(f"CONTRACT_MISMATCH rank {rank}", flush=True)
        print(str(e), flush=True)
        raise SystemExit(3)
    print(f"CONTRACT_OK rank {rank}", flush=True)


if __name__ == "__main__":
    main()
