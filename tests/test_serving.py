"""Continuous batching engine (round-4 verdict Next #8).

Correctness contract: greedy engine outputs are token-identical to
isolated generate() runs — ESPECIALLY after evictions recycle blocks
into newly admitted sequences (the failure mode block tables exist to
prevent; ref: incubate/nn/functional/block_multihead_attention.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate

# NOTE: no module-level slow mark — this file is in conftest's
# _SLOW_FILES, which auto-marks every test here slow EXCEPT those with
# an explicit quick marker (TestRecompilePin: the compile-count gate
# must run in the tier-1/-m analysis lanes)


def _model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny())


def _reference_tokens(model, prompt, max_new):
    ids = paddle.to_tensor(np.asarray(prompt, np.int64)[None])
    out = generate(model, ids, max_new_tokens=max_new, use_jit=False)
    return list(np.asarray(out.numpy())[0][len(prompt):])


class TestContinuousBatching:
    def test_mixed_prompts_match_isolated_generate(self):
        model = _model()
        rng = np.random.RandomState(0)
        prompts = {
            "a": rng.randint(0, 250, (5,)),
            "b": rng.randint(0, 250, (11,)),
            "c": rng.randint(0, 250, (3,)),
        }
        budgets = {"a": 6, "b": 4, "c": 8}

        eng = ContinuousBatchingEngine(
            model, max_batch=3, max_len=64, block_size=8, num_blocks=24,
            prompt_pad=16)
        for rid, p in prompts.items():
            eng.add_request(rid, p, max_new_tokens=budgets[rid])
        done = eng.run()
        assert set(done) == set(prompts)
        for rid, p in prompts.items():
            want = _reference_tokens(model, p, budgets[rid])
            assert done[rid].out == want, (rid, done[rid].out, want)

    def test_eviction_recycles_blocks_without_corruption(self):
        """max_batch=2, pool sized so the 3rd request MUST reuse the 1st
        request's freed blocks while the 2nd is still decoding — the
        survivor's and the newcomer's tokens must both stay exact."""
        model = _model()
        rng = np.random.RandomState(1)
        p_short = rng.randint(0, 250, (4,))   # finishes first
        p_long = rng.randint(0, 250, (6,))    # survives the eviction
        p_new = rng.randint(0, 250, (7,))     # admitted into freed blocks

        # per request: ceil(max(prompt+new, pad)/bs) blocks = 2 each;
        # 4 total blocks => the third request CANNOT be admitted until
        # the first frees its 2
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, block_size=8, num_blocks=4,
            prompt_pad=8)
        eng.add_request("short", p_short, max_new_tokens=3)
        eng.add_request("long", p_long, max_new_tokens=10)
        eng.add_request("new", p_new, max_new_tokens=5)

        first_batch = eng.step()
        assert eng.num_active == 2  # "new" had to wait for blocks
        done = eng.run()
        assert set(done) == {"short", "long", "new"}
        for rid, p, n in (("short", p_short, 3), ("long", p_long, 10),
                          ("new", p_new, 5)):
            want = _reference_tokens(model, p, n)
            assert done[rid].out == want, (rid, done[rid].out, want)
        # blocks really recycled: everything freed at the end
        assert eng.manager.free_blocks == 4

    def test_eos_finishes_early_and_frees_blocks(self):
        model = _model()
        p = np.random.RandomState(2).randint(0, 250, (4,))
        ref = _reference_tokens(model, p, 8)
        eos = ref[2]  # force an early stop at the 3rd generated token

        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=4,
            prompt_pad=8, eos_token_id=eos)
        eng.add_request("x", p, max_new_tokens=8)
        done = eng.run()
        assert done["x"].out == ref[:3]  # stopped AT the eos token
        assert eng.manager.free_blocks == 4

    def test_admission_rejects_oversized(self):
        model = _model()
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=4,
            prompt_pad=8)
        with pytest.raises(ValueError, match="prompt length"):
            eng.add_request("big", np.zeros(9, np.int32))
        with pytest.raises(ValueError, match="max_len"):
            eng.add_request("long", np.zeros(8, np.int32),
                            max_new_tokens=100)

    def test_sustained_throughput_counters(self):
        """The stats the benchmark row reports: decode tokens + steps
        accumulate across arrivals/finishes."""
        model = _model()
        rng = np.random.RandomState(3)
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, block_size=8, num_blocks=8,
            prompt_pad=8)
        for i in range(4):
            eng.add_request(i, rng.randint(0, 250, (4,)), max_new_tokens=4)
        done = eng.run()
        assert len(done) == 4
        # 4 requests x 4 tokens, one from each prefill => 12 decode
        assert eng.decode_tokens == 12
        assert eng.steps >= 6  # two waves of 2 + drain

    def test_weight_updates_after_construction_are_served(self):
        """The engine must serve the params' CURRENT values (and leave
        them intact), not an init-time snapshot."""
        import jax.numpy as jnp

        model = _model()
        p = np.random.RandomState(4).randint(0, 250, (4,))
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=4,
            prompt_pad=8)
        eng.add_request("r1", p, max_new_tokens=4)
        out1 = eng.run()["r1"].out

        # perturb the lm head; outputs must change and params survive
        head = model.lm_head.weight if hasattr(model, "lm_head") else None
        target = head if head is not None else model.parameters()[-1]
        before = np.asarray(target._data).copy()
        target._data = target._data + jnp.asarray(
            np.random.RandomState(5).randn(*before.shape).astype(
                before.dtype) * 0.5)
        after = np.asarray(target._data).copy()

        eng.add_request("r2", p, max_new_tokens=4)
        out2 = eng.run()["r2"].out
        want = _reference_tokens(model, p, 4)
        assert out2 == want  # serves the NEW weights
        assert out2 != out1 or np.allclose(before, after)
        np.testing.assert_array_equal(np.asarray(target._data), after)

    # NOTE: the per-request deadline tests (admission rejection +
    # in-flight eviction) live in tests/test_chaos.py so they run in
    # environments where this file's module-level engine import chain
    # is unavailable (they import the engine lazily and skip).

    def test_chunked_mode_matches_legacy_engine(self):
        """Small quick cross-check: the chunked-prefill scheduler must
        produce byte-identical outputs to the whole-prompt engine (and
        hence to generate()) on prompts that span partial/multiple
        chunks, under a tight token budget."""
        model = _model()
        rng = np.random.RandomState(7)
        prompts = {r: rng.randint(0, 250, (l,))
                   for r, l in enumerate((3, 7, 13, 5))}

        def run(**kw):
            eng = ContinuousBatchingEngine(
                model, max_batch=2, max_len=48, block_size=8,
                num_blocks=12, **kw)
            for r, p in prompts.items():
                eng.add_request(r, p, max_new_tokens=6)
            return eng, {r: q.out for r, q in eng.run().items()}

        legacy, base = run(prompt_pad=16)
        chunked, got = run(prefill_chunk=4, max_num_batched_tokens=6)
        assert got == base
        assert chunked.max_step_tokens <= 6
        assert chunked.prefill_tokens == sum(
            p.size for p in prompts.values())
        assert chunked.manager.free_blocks == 12

    def test_decode_chunk_matches_unchunked(self):
        """decode_chunk=K scans K steps per dispatch; tokens must be
        identical to the per-step engine (and hence to generate()),
        including eos-mid-chunk truncation and evictions."""
        model = _model()
        rng = np.random.RandomState(6)
        prompts = {r: rng.randint(0, 250, (3 + r,)) for r in range(4)}

        def run(chunk, eos=None):
            eng = ContinuousBatchingEngine(
                model, max_batch=2, max_len=48, block_size=8,
                num_blocks=12, prompt_pad=8, eos_token_id=eos,
                decode_chunk=chunk)
            for r, p in prompts.items():
                eng.add_request(r, p, max_new_tokens=9)
            return {r: q.out for r, q in eng.run().items()}

        base = run(1)
        chunked = run(3)
        assert chunked == base
        # eos mid-chunk: force an early stop on request 0
        eos = base[0][4]
        base_eos = run(1, eos=eos)
        chunk_eos = run(3, eos=eos)
        assert chunk_eos == base_eos
        # stopped at the FIRST occurrence of the eos token
        first = base[0].index(eos)
        assert base_eos[0] == base[0][:first + 1]


class TestChunkedPrefill:
    """Sarathi-Serve-style chunked prefill + token-budget scheduling
    (ISSUE 2 tentpole): long prompts feed ``prefill_chunk`` tokens at a
    time at the slot's current cache_len offset, interleaved with the
    running decode batch under ``max_num_batched_tokens``."""

    def test_mixed_128_to_4096_token_identical_and_budgeted(self):
        """The acceptance contract: mixed 128–4096 prompt lengths are
        token-identical to isolated generate(), prompts FAR beyond any
        whole-prompt pad are served, and no engine step processes more
        than max_num_batched_tokens real tokens."""
        paddle.seed(0)
        model = LlamaForCausalLM(
            LlamaConfig.tiny(max_position_embeddings=4608))
        rng = np.random.RandomState(10)
        prompts = {
            "s": rng.randint(0, 250, (128,)),
            "m": rng.randint(0, 250, (513,)),   # not a chunk multiple
            "l": rng.randint(0, 250, (4096,)),
        }
        budgets = {"s": 5, "m": 4, "l": 3}

        budget = 2 + 256
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=4160, block_size=64,
            num_blocks=2 * 65 + 4, prefill_chunk=256,
            max_num_batched_tokens=budget)
        for rid, p in prompts.items():
            eng.add_request(rid, p, max_new_tokens=budgets[rid])
        done = eng.run()
        assert set(done) == set(prompts)
        for rid, p in prompts.items():
            want = _reference_tokens(model, p, budgets[rid])
            assert done[rid].out == want, (rid, done[rid].out, want)
        assert eng.max_step_tokens <= budget
        assert eng.prefill_tokens == sum(p.size for p in prompts.values())
        assert eng.manager.free_blocks == 2 * 65 + 4
        # latency plumbing the benchmark reads
        for rid in prompts:
            assert done[rid].ttft() is not None
            assert len(done[rid].times) == len(done[rid].out)

    def test_prefill_interleaves_with_decode(self):
        """A long prompt arriving mid-decode must NOT stall the running
        request: while the newcomer prefills chunk by chunk, the
        in-flight slot keeps producing one token per engine step."""
        model = _model()
        rng = np.random.RandomState(11)
        p_run = rng.randint(0, 250, (4,))
        p_long = rng.randint(0, 250, (40,))

        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=64, block_size=8, num_blocks=16,
            prefill_chunk=8, max_num_batched_tokens=10)
        eng.add_request("run", p_run, max_new_tokens=12)
        eng.step()  # admit "run": its whole prompt fits one chunk

        def run_out_len():
            return next(len(s.req.out) for s in eng._slots
                        if s.req is not None and s.req.req_id == "run")

        eng.add_request("long", p_long, max_new_tokens=3)
        # 40-token prompt / 8-token chunks = 5 chunked steps (budget 10
        # = 2 decode lanes + one 8-token chunk); "run" must gain
        # exactly one token on each of them
        for _ in range(5):
            before = run_out_len()
            eng.step()
            assert run_out_len() == before + 1  # decode never stalled
        assert eng.max_step_tokens <= 10
        done = eng.run()
        for rid, p, n in (("run", p_run, 12), ("long", p_long, 3)):
            assert done[rid].out == _reference_tokens(model, p, n)

    def test_mid_prefill_eviction_recycles_blocks(self):
        """Deadline eviction must work BETWEEN chunks: a partially
        prefilled slot's blocks return to the pool, the half-written KV
        is unreachable (trash table), and a successor request admitted
        into the recycled blocks stays token-exact."""
        from paddle_tpu.utils.retries import Deadline

        model = _model()
        rng = np.random.RandomState(12)
        p_long = rng.randint(0, 250, (30,))
        p_next = rng.randint(0, 250, (6,))

        clk = {"t": 0.0}
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=40, block_size=8, num_blocks=5,
            prefill_chunk=8)
        eng.add_request("doomed", p_long, max_new_tokens=4,
                        deadline=Deadline(1.0, clock=lambda: clk["t"]))
        eng.step()  # admit + first chunk only (budget 1+8)
        slot = eng._slots[0]
        assert slot.prefilling and slot.prefill_pos == 8
        assert eng.manager.free_blocks == 0  # 5 blocks reserved
        clk["t"] = 2.0  # deadline lapses between chunks
        eng.step()
        doomed = eng._completed["doomed"]
        assert doomed.status == "expired" and doomed.out == []
        assert eng.manager.free_blocks == 5  # mid-prefill blocks recycled
        assert not eng._slots[0].active

        eng.add_request("next", p_next, max_new_tokens=4)
        done = eng.run()
        assert done["next"].out == _reference_tokens(model, p_next, 4)
        assert eng.manager.free_blocks == 5

    def test_queued_request_expired_before_any_chunk_is_rejected(self):
        """A request whose deadline lapses while QUEUED is rejected at
        admission — no chunk is ever dispatched for it."""
        from paddle_tpu.utils.retries import Deadline

        model = _model()
        rng = np.random.RandomState(13)
        clk = {"t": 0.0}
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=40, block_size=8, num_blocks=5,
            prefill_chunk=8)
        eng.add_request("late", rng.randint(0, 250, (20,)),
                        max_new_tokens=4,
                        deadline=Deadline(1.0, clock=lambda: clk["t"]))
        clk["t"] = 5.0
        done = eng.run()
        assert done["late"].status == "expired"
        assert done["late"].out == []
        assert eng.prefill_tokens == 0  # never burned a chunk
        assert eng.manager.free_blocks == 5

    def test_budget_validation(self):
        model = _model()
        with pytest.raises(ValueError, match="max_num_batched_tokens"):
            ContinuousBatchingEngine(
                model, max_batch=4, max_len=64, block_size=8,
                num_blocks=16, prefill_chunk=8, max_num_batched_tokens=3)
        # legacy mode still rejects prompts beyond the whole-prompt pad;
        # chunked mode serves them
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=64, block_size=8, num_blocks=8,
            prompt_pad=8)
        with pytest.raises(ValueError, match="prompt length"):
            eng.add_request("big", np.zeros(9, np.int32))
        eng2 = ContinuousBatchingEngine(
            model, max_batch=1, max_len=64, block_size=8, num_blocks=8,
            prefill_chunk=8)
        eng2.add_request("big", np.zeros(40, np.int32), max_new_tokens=2)
        assert len(eng2._queue) == 1


class TestPrefixReuse:
    """ISSUE 6: radix-style prefix KV reuse. The contract is twofold:
    cache hits save prefill tokens (measured via ``prefix_stats``), and
    outputs stay token-identical to isolated generate() runs — the KV a
    later request adopts is bit-for-bit what its own prefill would have
    written."""

    def test_shared_prefix_hits_and_stays_token_exact(self):
        model = _model()
        rng = np.random.RandomState(3)
        prefix = rng.randint(0, 250, (16,))  # 2 full blocks at bs=8
        tails = {"a": rng.randint(0, 250, (5,)),
                 "b": rng.randint(0, 250, (3,)),
                 "c": rng.randint(0, 250, (7,))}
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=64, block_size=8, num_blocks=12,
            prompt_pad=24, prefix_cache=True)
        outs = {}
        for rid, tail in tails.items():
            p = np.concatenate([prefix, tail])
            eng.add_request(rid, p, max_new_tokens=4)
            outs[rid] = (p, eng.run()[rid])
        for rid, (p, req) in outs.items():
            assert req.status == "ok"
            want = _reference_tokens(model, p, 4)
            assert req.out == want, (rid, req.out, want)
        # b and c each reused the 16-token prefix a prefilled
        assert eng.prefix_hit_tokens == 32
        st = eng.prefix_stats()
        assert st["enabled"] and st["hit_rate"] > 0.3
        # prefill skipped exactly the cached tokens
        assert eng.prefill_tokens == sum(
            16 + t.size for t in tails.values()) - 32

    def test_fully_cached_prompt_forks_and_preserves_readers(self):
        """A prompt whose length is an exact block multiple and fully
        cached recomputes only its last token — the write lands inside
        the last SHARED block, so copy-on-write must fork it and the
        cache's copy must keep serving later requests byte-exact."""
        model = _model()
        rng = np.random.RandomState(4)
        p = rng.randint(0, 250, (16,))  # exactly 2 blocks
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=64, block_size=8, num_blocks=12,
            prompt_pad=16, prefix_cache=True)
        want = _reference_tokens(model, p, 5)
        for rid in ("cold", "hot", "again"):
            eng.add_request(rid, p, max_new_tokens=5)
            req = eng.run()[rid]
            assert req.out == want, (rid, req.out, want)
        assert eng.prefix_forks >= 2          # hot + again both forked
        assert eng.prefix_hit_tokens == 30    # 15 cached tokens twice

    def test_chunked_mode_prefix_reuse_token_exact(self):
        model = _model()
        rng = np.random.RandomState(5)
        prefix = rng.randint(0, 250, (24,))
        a = np.concatenate([prefix, rng.randint(0, 250, (9,))])
        b = np.concatenate([prefix, rng.randint(0, 250, (4,))])
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=64, block_size=8, num_blocks=16,
            prefill_chunk=8, prefix_cache=True)
        eng.add_request("a", a, max_new_tokens=4)
        done = eng.run()
        eng.add_request("b", b, max_new_tokens=6)
        done = eng.run()
        assert done["a"].out == _reference_tokens(model, a, 4)
        assert done["b"].out == _reference_tokens(model, b, 6)
        assert eng.prefix_hit_tokens == 24    # b adopted 3 full blocks
        # b's prefill fed only the un-cached remainder
        assert eng.prefill_tokens == a.size + (b.size - 24)

    def test_offset_prefill_near_max_len_stays_exact(self):
        """Regression: a cache-hit whole-prompt prefill writes its full
        static ``prompt_pad`` width starting at the cached offset; the
        padded lanes then run PAST the table row. They must be DROPPED
        — take_along_axis clamping would alias the garbage onto the
        last real block's early offsets and corrupt prompt KV written
        in the same dispatch."""
        model = _model()
        rng = np.random.RandomState(8)
        p = rng.randint(0, 250, (28,))  # fills the row to its last block
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=8,
            prompt_pad=28, prefix_cache=True)
        want = _reference_tokens(model, p, 4)
        for rid in ("cold", "hot"):  # hot: cached_len=24, writes 24..51
            eng.add_request(rid, p, max_new_tokens=4)
            assert eng.run()[rid].out == want, rid
        assert eng.prefix_hit_tokens == 24

    def test_cache_eviction_keeps_admission_alive(self):
        """A pool mostly full of cached prefixes must still admit new
        work: LRU cache entries are reclaimed instead of head-of-line
        blocking (the cache can never deadlock admission)."""
        model = _model()
        rng = np.random.RandomState(6)
        # pool of 6 blocks; each request needs 2 (pad 8 + 4 gen -> 12
        # tokens) and caches 1 full prompt block; distinct prompts, so
        # the cache only ever GROWS until eviction kicks in
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, block_size=8, num_blocks=6,
            prompt_pad=8, prefix_cache=True)
        prompts = {i: rng.randint(0, 250, (8,)) for i in range(6)}
        for rid, p in prompts.items():
            eng.add_request(rid, p, max_new_tokens=4)
        done = eng.run()
        assert set(done) == set(prompts)
        for rid, p in prompts.items():
            assert done[rid].status == "ok"
            assert done[rid].out == _reference_tokens(model, p, 4)
        assert eng.prefix_cache.evicted_blocks > 0

    def test_cache_off_is_bit_for_bit_legacy(self):
        """prefix_cache=False (the default) keeps the exact legacy
        behaviour — zero stats, no cache object."""
        model = _model()
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=4,
            prompt_pad=8)
        assert eng.prefix_cache is None
        p = np.arange(5) % 250
        eng.add_request("x", p, max_new_tokens=3)
        assert eng.run()["x"].out == _reference_tokens(model, p, 3)
        assert eng.prefix_stats() == {
            "enabled": False, "hit_tokens": 0, "prefill_tokens": 5,
            "forks": 0, "hit_rate": 0.0}


@pytest.mark.quick
@pytest.mark.analysis
class TestRecompilePin:
    """ISSUE 3: the recompile_guard sanitizer pins the engine's compile
    counts — the static-shape design promises ONE XLA program per
    (prefill chunk width, decode batch shape), and a silent per-step
    retrace (a Python scalar leaking into the traced signature, a shape
    that stopped being padded) must fail THIS test instead of 10x'ing
    latency in production."""

    def test_one_compile_per_chunk_width_and_decode_shape(self):
        from paddle_tpu.analysis import recompile_guard

        model = _model()
        rng = np.random.RandomState(21)
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=64, block_size=8, num_blocks=16,
            prefill_chunk=8, max_num_batched_tokens=10)
        # mixed prompts: sub-chunk, chunk-multiple, non-multiple — all
        # must share the single width-8 prefill program
        wave1 = {"a": 3, "b": 16, "c": 9}
        for rid, n in wave1.items():
            eng.add_request(rid, rng.randint(0, 250, (n,)),
                            max_new_tokens=3)
        with recompile_guard(match=r"^(prefill|decode)") as g:
            done = eng.run()
        assert set(done) == set(wave1)
        # exactly one compile per phase program: one prefill (chunk
        # width 8), one decode (batch shape [2]) — NOT one per prompt
        # length and NOT one per engine step
        assert sorted(g.names()) == ["decode", "prefill"], g.names()
        for ev in g.events():
            assert ev.shapes  # the (width/shape) identity is recorded

        # steady state: a second mixed wave must be 100% cache hits
        wave2 = {"d": 5, "e": 23, "f": 8}
        for rid, n in wave2.items():
            eng.add_request(rid, rng.randint(0, 250, (n,)),
                            max_new_tokens=3)
        with recompile_guard(max_compiles=0, match=r"^(prefill|decode)"):
            done = eng.run()
        assert set(wave2) <= set(done)  # run() returns cumulative map

    def test_whole_prompt_mode_pins_too(self):
        """Legacy (unchunked) mode: one prompt_pad-wide prefill program
        + one decode program, then cache hits only."""
        from paddle_tpu.analysis import recompile_guard

        model = _model()
        rng = np.random.RandomState(22)
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, block_size=8, num_blocks=8,
            prompt_pad=8)
        for rid in range(2):
            eng.add_request(rid, rng.randint(0, 250, (4,)),
                            max_new_tokens=2)
        with recompile_guard(match=r"^(prefill|decode)") as g:
            eng.run()
        assert sorted(g.names()) == ["decode", "prefill"]
        eng.add_request("late", rng.randint(0, 250, (6,)),
                        max_new_tokens=2)
        with recompile_guard(max_compiles=0, match=r"^(prefill|decode)"):
            eng.run()
