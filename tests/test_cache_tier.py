"""Host-RAM prefix-cache tier (ISSUE 19): byte-exactness + failure matrix.

Layers of proof:

- ``TestFrameRoundTrip`` — model-free ``BlockManager`` export -> tier
  ``put`` -> ``lookup`` -> ``import_blocks`` round trips, byte-exact
  for bf16 pools (compared as raw uint16 words) AND int8 pools with
  their scale rows carried; longest-block-aligned-prefix selection and
  the ``min_tokens`` floor.
- ``TestChaosSpill`` — the ``cache.spill`` chaos site: a ``corrupt``
  fault is CRC-rejected at lookup (a miss, never bad KV) and the bad
  frame is purged; a ``drop`` fault loses the spill silently
  (``put_drops``) and a later re-put heals it.
- ``TestLRUAndNamespaces`` — byte-budget LRU eviction (lookup
  refreshes recency), idempotent re-puts, oversize-frame rejection,
  and per-tenant namespace isolation (same tokens under two tenants
  are distinct keys; neither leaks into the default namespace).
- ``TestEngineRestore`` — the engine seam: a working set that
  overflows the HBM pool replays TOKEN-EXACT through tier restores
  (byte-exact KV => identical greedy argmax), with
  ``prefix_stats()["tier"]`` accounting for the spills and restores.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.cache_tier import HostTier
from paddle_tpu.ops.paged_attention import BlockManager
from paddle_tpu.testing import chaos
from paddle_tpu.testing.chaos import ChaosSchedule

pytestmark = pytest.mark.autoscale


@pytest.fixture(autouse=True)
def _no_chaos():
    yield
    chaos.uninstall()


def _make_pools(layers=2, kvh=2, blocks=8, bs=4, d=8, dtype="bf16",
                seed=0):
    """KV pools shaped like the engine's: [kvh, blocks, bs, d] per k/v
    per layer. ``dtype='int8'`` adds per-token scale rows, matching the
    quantized-KV pool layout."""
    rng = np.random.RandomState(seed)
    pools = []
    for _ in range(layers):
        if dtype == "int8":
            k = jnp.asarray(rng.randint(-127, 128, (kvh, blocks, bs, d)),
                            jnp.int8)
            v = jnp.asarray(rng.randint(-127, 128, (kvh, blocks, bs, d)),
                            jnp.int8)
            ks = jnp.asarray(rng.rand(kvh, blocks, bs), jnp.float32)
            vs = jnp.asarray(rng.rand(kvh, blocks, bs), jnp.float32)
            pools.append((k, v, ks, vs))
        else:
            k = jnp.asarray(rng.randn(kvh, blocks, bs, d), jnp.bfloat16)
            v = jnp.asarray(rng.randn(kvh, blocks, bs, d), jnp.bfloat16)
            pools.append((k, v))
    return pools


def _bits(a):
    """Raw-word view for byte-exact comparison (bf16 has no native
    numpy equality semantics worth trusting here)."""
    a = np.asarray(a)
    if a.dtype.itemsize == 2:
        return a.view(np.uint16)
    return a


class TestFrameRoundTrip:
    def test_bf16_roundtrip_byte_exact(self):
        src = BlockManager(8, 4)
        src.allocate("x", 10)  # 3 blocks, last partial
        pools = _make_pools()
        pages, scales, meta = src.export_blocks("x", pools, num_tokens=8)
        assert scales is None and meta["num_blocks"] == 2

        tier = HostTier()
        tokens = np.arange(100, 108, dtype=np.int32)
        assert tier.put("t0", tokens, pages, scales, meta)
        hit = tier.lookup("t0", np.arange(100, 110), block_size=4)
        assert hit is not None
        n, rpages, rscales, rmeta = hit
        assert n == 8 and rscales is None
        np.testing.assert_array_equal(_bits(rpages), _bits(pages))

        dst = BlockManager(16, 4)
        dst.allocate("occupant", 12)  # different free-list shape
        dpools = _make_pools(seed=9)
        dpools, blocks = dst.import_blocks("x", rpages, rscales, rmeta,
                                           dpools)
        srow = np.asarray(src.owned_blocks("x"))[:2]
        drow = np.asarray(blocks)
        for es, ed in zip(pools, dpools):
            for j in range(2):  # k, v
                np.testing.assert_array_equal(
                    _bits(np.asarray(es[j])[:, srow]),
                    _bits(np.asarray(ed[j])[:, drow]))

    def test_int8_scales_roundtrip_byte_exact(self):
        src = BlockManager(8, 4)
        src.allocate("q", 8)
        pools = _make_pools(dtype="int8")
        pages, scales, meta = src.export_blocks("q", pools, num_tokens=8)
        assert pages.dtype == np.int8 and scales is not None
        assert meta["quantized"]

        tier = HostTier()
        tokens = np.arange(8, dtype=np.int32)
        assert tier.put(None, tokens, pages, scales, meta)
        n, rpages, rscales, rmeta = tier.lookup(
            None, tokens, block_size=4)
        assert n == 8
        np.testing.assert_array_equal(rpages, pages)
        np.testing.assert_array_equal(rscales, scales)

        dst = BlockManager(8, 4)
        dpools = _make_pools(dtype="int8", seed=7)
        dpools, blocks = dst.import_blocks("q", rpages, rscales, rmeta,
                                           dpools)
        srow = np.asarray(src.owned_blocks("q"))
        drow = np.asarray(blocks)
        for es, ed in zip(pools, dpools):
            for j in range(4):  # k, v, k_scale, v_scale
                np.testing.assert_array_equal(
                    np.asarray(es[j])[:, srow],
                    np.asarray(ed[j])[:, drow])

    def test_longest_block_aligned_prefix_wins(self):
        tier = HostTier()
        toks = np.arange(16)
        pages = np.zeros((1, 2, 4, 2), np.float32)
        meta = {"num_blocks": 1}
        tier.put(None, toks[:4], pages, None, meta)
        tier.put(None, toks[:12], pages, None, meta)
        n, _, _, _ = tier.lookup(None, toks, block_size=4)
        assert n == 12  # not the shorter 4-token frame
        # min_tokens floors the search: the HBM tree already covers 12
        assert tier.lookup(None, toks, block_size=4,
                           min_tokens=12) is None
        # non-aligned queries truncate to full blocks first
        n2, _, _, _ = tier.lookup(None, toks[:14], block_size=4)
        assert n2 == 12


class TestChaosSpill:
    def _frame_args(self):
        src = BlockManager(8, 4)
        src.allocate("x", 8)
        pools = _make_pools()
        pages, scales, meta = src.export_blocks("x", pools, num_tokens=8)
        return np.arange(8, dtype=np.int32), pages, scales, meta

    def test_corrupt_spill_is_crc_rejected_miss(self):
        tokens, pages, scales, meta = self._frame_args()
        tier = HostTier()
        chaos.install(ChaosSchedule(seed=1).at("cache.spill", 1,
                                               "corrupt"))
        assert tier.put("t", tokens, pages, scales, meta)  # stored...
        assert len(tier) == 1
        assert tier.lookup("t", tokens, block_size=4) is None  # ...bad
        assert tier.corrupt_rejected == 1
        assert len(tier) == 0  # purged, not retried forever
        chaos.uninstall()
        # a healthy re-put heals the entry
        assert tier.put("t", tokens, pages, scales, meta)
        hit = tier.lookup("t", tokens, block_size=4)
        assert hit is not None and hit[0] == 8
        np.testing.assert_array_equal(_bits(hit[1]), _bits(pages))

    def test_dropped_spill_never_stored(self):
        tokens, pages, scales, meta = self._frame_args()
        tier = HostTier()
        chaos.install(ChaosSchedule(seed=2).at("cache.spill", 1, "drop"))
        assert not tier.put("t", tokens, pages, scales, meta)
        assert tier.put_drops == 1 and len(tier) == 0
        assert tier.lookup("t", tokens, block_size=4) is None
        st = tier.stats()
        assert st["puts"] == 1 and st["hits"] == 0


class TestLRUAndNamespaces:
    def _put(self, tier, ns, lo, n=4):
        toks = np.arange(lo, lo + n, dtype=np.int32)
        pages = np.full((1, 1, 4, 2), float(lo), np.float32)
        assert tier.put(ns, toks, pages, None, {"num_blocks": 1})
        return toks

    def test_lru_eviction_and_lookup_refresh(self):
        tier = HostTier()
        t1 = self._put(tier, None, 100)
        t2 = self._put(tier, None, 200)
        tier.capacity_bytes = tier.stats()["bytes"]  # exactly two fit
        # touching t1 makes t2 the LRU victim for the next insert
        assert tier.lookup(None, t1, block_size=4) is not None
        t3 = self._put(tier, None, 300)
        assert tier.evictions == 1 and len(tier) == 2
        assert tier.lookup(None, t2, block_size=4) is None
        assert tier.lookup(None, t1, block_size=4) is not None
        assert tier.lookup(None, t3, block_size=4) is not None
        assert tier.stats()["bytes"] <= tier.capacity_bytes

    def test_oversize_frame_rejected(self):
        tier = HostTier(capacity_bytes=16)  # smaller than any frame
        toks = np.arange(4, dtype=np.int32)
        assert not tier.put(None, toks,
                            np.zeros((1, 1, 4, 2), np.float32), None,
                            {"num_blocks": 1})
        assert tier.put_drops == 1 and len(tier) == 0

    def test_idempotent_reput_refreshes_only(self):
        tier = HostTier()
        t1 = self._put(tier, None, 0)
        self._put(tier, None, 0)
        assert len(tier) == 1 and tier.stats()["puts"] == 2
        assert tier.lookup(None, t1, block_size=4) is not None

    def test_namespace_isolation(self):
        tier = HostTier()
        toks = self._put(tier, "tenantA", 0)
        # same tokens, different tenant / default ns: all misses
        assert tier.lookup("tenantB", toks, block_size=4) is None
        assert tier.lookup(None, toks, block_size=4) is None
        assert tier.lookup("tenantA", toks, block_size=4) is not None
        # the shared-system-prompt namespace is just another ns
        self._put(tier, "*", 0)
        assert len(tier) == 2  # distinct keys, no aliasing


class TestEngineRestore:
    def test_replay_token_exact_through_tier_restores(self):
        """Working set (4 prompts x 2 full blocks) overflows an 8-block
        HBM pool: the replay pass can only hit through host-tier
        restores, and restored KV must reproduce the warm pass's greedy
        tokens exactly."""
        from paddle_tpu.inference.serving import ContinuousBatchingEngine
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        config = LlamaConfig.tiny()
        model = LlamaForCausalLM(config)
        tier = HostTier()
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, block_size=8, num_blocks=8,
            prompt_pad=24, prefix_cache=True, cache_tier=tier)
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, config.vocab_size, (17,)).astype(np.int32)
                   for _ in range(4)]

        def run(tag):
            outs = []
            for j, p in enumerate(prompts):
                rid = f"{tag}-{j}"
                eng.add_request(rid, p, 4)
                for _ in range(512):
                    if rid in eng._completed:
                        break
                    eng.step()
                req = eng._completed[rid]
                assert req.status == "ok"
                outs.append(list(req.out))
            return outs

        warm = run("warm")
        replay = run("replay")
        assert replay == warm  # byte-exact KV => identical argmax
        st = eng.prefix_stats()
        assert st["tier"]["restores"] >= 1
        assert st["tier"]["restore_tokens"] >= 16
        assert st["tier"]["puts"] >= 4
        assert st["hit_tokens"] > 0
