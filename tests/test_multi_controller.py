"""Real multi-controller execution (round-4 verdict Next #2).

Spawns the framework launcher, which starts 2 actual worker processes;
each calls jax.distributed.initialize (via init_parallel_env), forms
the 4-device global mesh across both processes, runs one eager
collective from each family (all_reduce / all_gather / send+recv)
across the process boundary, and trains a DP step whose loss must match
a serial full-batch run. This is the class of evidence the
single-controller 8-vdev mesh cannot provide: coordination-service
rendezvous, per-process device locality, process-spanning collectives.

ref: test/legacy_test/test_dist_base.py:952 (spawn trainers, compare
losses), test/collective/test_communication_api_base.py:28.
"""
import os
import socket
import subprocess
import sys

import jax
import pytest

# the workers need 2 virtual CPU devices per process AND a working
# cross-process CPU collectives implementation. Newer jax provides
# jax_num_cpu_devices (and defaults CPU collectives to gloo); 0.4.37
# lacks that option but the workers fall back to
# XLA_FLAGS=--xla_force_host_platform_device_count=2 plus
# jax_cpu_collectives_implementation=gloo. Only a build with NEITHER
# path (no device-count control or no gloo) skips.
pytestmark = [
    pytest.mark.skipif(
        not ("jax_num_cpu_devices" in jax.config.values
             or "jax_cpu_collectives_implementation" in jax.config.values),
        reason="this jax build has neither jax_num_cpu_devices nor the "
               "XLA_FLAGS+gloo fallback the 2-process workers require"),
    pytest.mark.mc2,
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_mc_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_launcher_two_process_collectives_and_dp_parity(tmp_path):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip axon registration in workers
    env["JAX_PLATFORMS"] = "cpu"
    # workers run by absolute script path: repo root must be importable
    # (APPEND to PYTHONPATH — the axon site dir must stay on it)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the workers manage their own device count; drop the test
    # harness's 8-vdev forcing so each worker gets jax_num_cpu_devices=2
    env.pop("XLA_FLAGS", None)
    log_dir = str(tmp_path / "logs")
    port = _free_port()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--master", f"127.0.0.1:{port}", "--nproc", "2",
         "--max_restart", "0", "--log_dir", log_dir,
         "--job_id", "mc", WORKER],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=480,
    )
    logs = {}
    for r in (0, 1):
        path = os.path.join(log_dir, f"mc.rank{r}.log")
        logs[r] = open(path).read() if os.path.exists(path) else "<missing>"
    detail = (f"launcher rc={proc.returncode}\nstderr:\n{proc.stderr[-1500:]}"
              + "".join(f"\n--- rank{r} ---\n{logs[r][-3000:]}" for r in logs))
    assert proc.returncode == 0, detail
    for r in (0, 1):
        assert f"MC_WORKER_OK rank {r}" in logs[r], detail
        assert "collectives OK" in logs[r], detail
        assert "flight recorder OK" in logs[r], detail
        assert "DP loss parity OK" in logs[r], detail
        # hybrid-parallel schedules with the mesh SPANNING the process
        # boundary: TP (mp axis pairs devices across processes),
        # sharding stage 3 (4-way shard axis, shard 2|3 on process 1),
        # and the scan+ppermute pipeline (stage 1 on process 1)
        assert "TP loss parity OK" in logs[r], detail
        assert "sharding3 loss parity OK" in logs[r], detail
        assert "pipeline loss parity OK" in logs[r], detail
