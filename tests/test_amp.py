"""AMP tests: auto_cast O1/O2 casting, decorate, GradScaler dynamics.

Mirrors the reference's amp test patterns (test/amp/test_amp_api.py,
test_grad_scaler.py): white-list ops run low-precision, black-list ops
promote back to fp32, scaler skips steps on inf and adapts the scale.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt


def test_auto_cast_o1_white_black():
    x = paddle.randn([4, 8])
    w = paddle.randn([8, 8])
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        y = paddle.matmul(x, w)  # white: bf16
        assert str(y.dtype) == "bfloat16"
        z = F.softmax(y)  # black: promoted to fp32
        assert str(z.dtype) == "float32"
        s = paddle.add(x, x)  # neither: keeps input dtype
        assert str(s.dtype) == "float32"
    # outside the context: no casting
    y = paddle.matmul(x, w)
    assert str(y.dtype) == "float32"


def test_auto_cast_custom_lists():
    x = paddle.randn([4, 4])
    with paddle.amp.auto_cast(custom_black_list=["matmul"], dtype="bfloat16"):
        y = paddle.matmul(x, x)
        assert str(y.dtype) == "float32"
    with paddle.amp.auto_cast(custom_white_list=["relu"], dtype="bfloat16"):
        y = F.relu(x)
        assert str(y.dtype) == "bfloat16"
    with pytest.raises(ValueError):
        paddle.amp.AutoCastLists(custom_white_list=["relu"], custom_black_list=["relu"])


def test_auto_cast_grads_flow():
    x = paddle.randn([4, 8])
    w = paddle.randn([8, 8])
    w.stop_gradient = False
    with paddle.amp.auto_cast(dtype="bfloat16"):
        y = paddle.matmul(x, w)
        loss = y.sum()
    loss.backward()
    assert w.grad is not None
    assert str(w.grad.dtype) == "float32"  # cast-back lands grads in param dtype


def test_decorate_o2():
    model = nn.Sequential(nn.Linear(8, 16), nn.LayerNorm(16), nn.Linear(16, 4))
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    model, optimizer = paddle.amp.decorate(
        model, optimizers=optimizer, level="O2", dtype="bfloat16"
    )
    assert str(model[0].weight.dtype) == "bfloat16"
    # LayerNorm params stay fp32 (excluded like the reference)
    assert str(model[1].weight.dtype) == "float32"
    assert optimizer._multi_precision


def test_bf16_training_converges():
    paddle.seed(7)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    xs = paddle.randn([64, 8])
    ys = (xs.sum(axis=1, keepdim=True) * 0.5)
    losses = []
    for _ in range(30):
        with paddle.amp.auto_cast(dtype="bfloat16"):
            pred = model(xs)
            loss = F.mse_loss(pred.astype("float32"), ys)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_grad_scaler_scales_and_unscales():
    paddle.seed(0)
    model = nn.Linear(4, 4)
    optimizer = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    x = paddle.randn([2, 4])
    loss = model(x).sum()
    scaled = scaler.scale(loss)
    assert np.allclose(np.asarray(scaled._data), np.asarray(loss._data) * 1024.0, rtol=1e-5)
    scaled.backward()
    before = np.asarray(model.weight.grad._data).copy()
    scaler.unscale_(optimizer)
    after = np.asarray(model.weight.grad._data)
    assert np.allclose(after, before / 1024.0, rtol=1e-5)
    scaler.step(optimizer)
    scaler.update()


def test_grad_scaler_skips_on_inf_and_decreases_scale():
    paddle.seed(0)
    model = nn.Linear(4, 4)
    optimizer = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(
        init_loss_scaling=1024.0, decr_every_n_nan_or_inf=1
    )
    w_before = np.asarray(model.weight._data).copy()
    x = paddle.to_tensor(np.full((2, 4), np.inf, np.float32))
    loss = model(x).sum()
    scaler.scale(loss).backward()
    scaler.step(optimizer)
    scaler.update()
    # params unchanged (step skipped), scale halved
    assert np.allclose(np.asarray(model.weight._data), w_before)
    assert scaler.get_scale_value() == 512.0
    optimizer.clear_grad()

    # a clean step afterwards does update params
    x = paddle.randn([2, 4])
    loss = model(x).sum()
    scaler.scale(loss).backward()
    scaler.step(optimizer)
    scaler.update()
    assert not np.allclose(np.asarray(model.weight._data), w_before)


def test_grad_scaler_increases_scale_after_good_steps():
    model = nn.Linear(2, 2)
    optimizer = opt.SGD(learning_rate=0.01, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2)
    x = paddle.randn([2, 2])
    for _ in range(2):
        loss = model(x).sum()
        scaler.scale(loss).backward()
        scaler.step(optimizer)
        scaler.update()
        optimizer.clear_grad()
    assert scaler.get_scale_value() == 16.0


def test_grad_scaler_state_dict_roundtrip():
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
    sd = scaler.state_dict()
    other = paddle.amp.GradScaler()
    other.load_state_dict(sd)
    assert other.get_scale_value() == 64.0


def test_grad_scaler_under_jit():
    """Scaler-wrapped train step must trace under to_static (the
    where-select skip design; SURVEY §4 implication (d))."""
    paddle.seed(3)
    model = nn.Linear(4, 4)
    optimizer = opt.SGD(learning_rate=0.05, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=16.0, use_dynamic_loss_scaling=False)

    def step(x):
        loss = model(x).sum()
        scaler.scale(loss).backward()
        scaler.step(optimizer)
        optimizer.clear_grad()
        scaler._opt_states.clear()
        scaler._found_inf = __import__("jax").numpy.asarray(False)
        return loss

    compiled = paddle.jit.to_static(step, layers=[model], optimizers=[optimizer])
    x = paddle.randn([2, 4])
    eager_w = np.asarray(model.weight._data).copy()
    l1 = compiled(x)
    l2 = compiled(x)
    assert np.isfinite(float(np.asarray(l1._data)))
    assert not np.allclose(np.asarray(model.weight._data), eager_w)
