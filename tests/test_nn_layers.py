"""nn.Layer / layers tests (ref test pattern: test/legacy_test API tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLayerBase:
    def test_parameters_and_naming(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        names = [n for n, _ in m.named_parameters()]
        assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
        assert len(m.parameters()) == 4

    def test_state_dict_roundtrip(self):
        m1 = nn.Linear(4, 3)
        m2 = nn.Linear(4, 3)
        m2.set_state_dict(m1.state_dict())
        np.testing.assert_array_equal(m1.weight.numpy(), m2.weight.numpy())
        np.testing.assert_array_equal(m1.bias.numpy(), m2.bias.numpy())

    def test_state_dict_missing_unexpected(self):
        m = nn.Linear(4, 3)
        missing, unexpected = m.set_state_dict({"weight": m.weight.numpy(), "junk": np.zeros(3)})
        assert missing == ["bias"]
        assert unexpected == ["junk"]

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(5)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_forward_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        m(paddle.to_tensor(np.zeros((1, 2), "float32")))
        assert calls == [1]
        h.remove()
        m(paddle.to_tensor(np.zeros((1, 2), "float32")))
        assert calls == [1]

    def test_cast_bfloat16(self):
        m = nn.Linear(4, 3)
        m.bfloat16()
        assert m.weight.dtype == np.dtype(paddle.bfloat16)

    def test_apply_fn(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        seen = []
        m.apply(lambda l: seen.append(type(l).__name__))
        assert seen.count("Linear") == 2


class TestFunctionalNumerics:
    def test_linear_matches_numpy(self):
        x = np.random.randn(3, 4).astype("float32")
        w = np.random.randn(4, 5).astype("float32")
        b = np.random.randn(5).astype("float32")
        out = F.linear(paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), x @ w + b, rtol=1e-5)

    def test_conv2d_matches_manual(self):
        x = np.random.randn(1, 1, 4, 4).astype("float32")
        w = np.ones((1, 1, 2, 2), "float32")
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        expected = np.zeros((1, 1, 3, 3), "float32")
        for i in range(3):
            for j in range(3):
                expected[0, 0, i, j] = x[0, 0, i : i + 2, j : j + 2].sum()
        np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)

    def test_layer_norm(self):
        x = np.random.randn(2, 5).astype("float32")
        out = F.layer_norm(paddle.to_tensor(x), 5)
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(), (x - mu) / np.sqrt(sd**2 + 1e-5), rtol=1e-4)

    def test_batch_norm_train_updates_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = paddle.to_tensor(np.random.randn(4, 3, 5, 5).astype("float32") * 3 + 1)
        bn.train()
        bn(x)
        assert not np.allclose(bn._mean.numpy(), 0.0)

    def test_softmax_cross_entropy_vs_numpy(self):
        logits = np.random.randn(6, 4).astype("float32")
        labels = np.random.randint(0, 4, (6,))
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = -np.log(p[np.arange(6), labels]).mean()
        np.testing.assert_allclose(float(loss), expected, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 3).astype("float32")
        labels = np.array([0, 1, -100, 2])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        keep = labels != -100
        expected = -np.log(p[np.arange(4), np.where(keep, labels, 0)])[keep].mean()
        np.testing.assert_allclose(float(loss), expected, rtol=1e-5)

    def test_dropout_zero_in_eval(self):
        x = paddle.to_tensor(np.ones((10, 10), "float32"))
        out = F.dropout(x, 0.5, training=False)
        np.testing.assert_array_equal(out.numpy(), x.numpy())

    def test_dropout_scales_in_train(self):
        paddle.seed(0)
        x = paddle.to_tensor(np.ones((1000,), "float32"))
        out = F.dropout(x, 0.5, training=True).numpy()
        assert set(np.unique(out)).issubset({0.0, 2.0})

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([0, 3]))
        out = emb(ids)
        np.testing.assert_array_equal(out.numpy()[0], np.zeros(4, "float32"))

    def test_sdpa_matches_naive(self):
        np.random.seed(0)
        q = np.random.randn(2, 4, 2, 8).astype("float32")
        k = np.random.randn(2, 4, 2, 8).astype("float32")
        v = np.random.randn(2, 4, 2, 8).astype("float32")
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v), is_causal=True
        )
        # naive reference
        qh, kh, vh = [a.transpose(0, 2, 1, 3) for a in (q, k, v)]
        logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(8)
        mask = np.tril(np.ones((4, 4), bool))
        logits = np.where(mask, logits, -np.inf)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = (p @ vh).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)

    def test_pad_reflect(self):
        x = paddle.to_tensor(np.arange(12).reshape(1, 1, 3, 4).astype("float32"))
        out = F.pad(x, [1, 1, 0, 0], mode="reflect")
        assert out.shape == [1, 1, 3, 6]


class TestGradClip:
    def test_global_norm_clip(self):
        w = paddle.to_tensor(np.ones(4, "float32"), stop_gradient=False)
        (w * paddle.to_tensor(np.full(4, 10.0, "float32"))).sum().backward()
        clip = nn.ClipGradByGlobalNorm(1.0)
        (_, g), = clip([(w, w.grad)])
        assert abs(np.linalg.norm(g.numpy()) - 1.0) < 1e-5

    def test_clip_by_value(self):
        w = paddle.to_tensor(np.ones(3, "float32"), stop_gradient=False)
        clip = nn.ClipGradByValue(0.5)
        g = paddle.to_tensor(np.array([1.0, -2.0, 0.1], "float32"))
        (_, gc), = clip([(w, g)])
        np.testing.assert_allclose(gc.numpy(), [0.5, -0.5, 0.1])
