"""Recompute (gradient checkpointing) + saved_tensors_hooks tests.

Reference pattern: test/collective/fleet/test_dygraph_recompute*.py —
recomputed runs must produce identical losses AND identical grads to
the plain run, including with dropout (RNG state must not correlate
segments), and must compose with to_static.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.utils import recompute, recompute_sequential


def _block(hidden=32):
    return nn.Sequential(
        nn.Linear(hidden, hidden * 4),
        nn.GELU(),
        nn.Linear(hidden * 4, hidden),
    )


class Net(nn.Layer):
    def __init__(self, use_recompute, segments=0):
        super().__init__()
        self.blocks = nn.LayerList([_block() for _ in range(3)])
        self.head = nn.Linear(32, 4)
        self.use_recompute = use_recompute
        self.segments = segments

    def forward(self, x):
        for b in self.blocks:
            if self.use_recompute:
                x = recompute(b, x)
            else:
                x = b(x)
        return self.head(x)


def _grads_and_loss(use_recompute):
    paddle.seed(11)
    net = Net(use_recompute)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 32).astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 4, (8,)))
    loss = F.cross_entropy(net(x), y)
    loss.backward()
    grads = {k: np.asarray(p.grad.numpy()) for k, p in net.named_parameters()}
    return float(loss.numpy()), grads


class TestRecompute:
    def test_matches_plain_backward(self):
        l0, g0 = _grads_and_loss(False)
        l1, g1 = _grads_and_loss(True)
        assert abs(l0 - l1) < 1e-6
        assert g0.keys() == g1.keys()
        for k in g0:
            np.testing.assert_allclose(g1[k], g0[k], rtol=1e-5, atol=1e-6, err_msg=k)

    def test_under_to_static_trains(self):
        paddle.seed(11)
        net = Net(True)
        optimizer = opt.AdamW(learning_rate=1e-2, parameters=net.parameters())

        def step(x, y):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, layers=[net], optimizers=[optimizer])
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (8,)))
        losses = [float(compiled(x, y).numpy()) for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_dropout_segments_not_correlated(self):
        """Two recomputed dropout blocks must not reuse the same mask."""
        paddle.seed(5)
        drop = nn.Dropout(0.5)
        x = paddle.to_tensor(np.ones((4, 64), np.float32))
        a = recompute(drop, x)
        b = recompute(drop, x)
        assert not np.array_equal(a.numpy(), b.numpy())

    def test_recompute_sequential(self):
        paddle.seed(11)
        seq = nn.Sequential(*[_block() for _ in range(4)])
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 32).astype(np.float32))
        ref = seq(x)
        out = recompute_sequential({"segments": 2}, seq, x)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-6)
        loss = out.sum()
        loss.backward()
        assert seq[0][0].weight.grad is not None

    def test_kwargs_and_multi_arg(self):
        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, a, b, scale=1.0):
                return self.fc(a) * scale + b

        paddle.seed(0)
        m = TwoIn()
        a = paddle.to_tensor(np.random.RandomState(0).randn(2, 8).astype(np.float32))
        a.stop_gradient = False
        b = paddle.to_tensor(np.random.RandomState(1).randn(2, 8).astype(np.float32))
        out = recompute(m, a, b, scale=2.0)
        ref = m(a, b, scale=2.0)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
        out.sum().backward()
        assert a.grad is not None and m.fc.weight.grad is not None


class TestSavedTensorsHooks:
    def test_pylayer_pack_unpack_roundtrip(self):
        from paddle_tpu.autograd import PyLayer
        from paddle_tpu.autograd.saved_tensors_hooks import saved_tensors_hooks

        events = []

        def pack(t):
            events.append("pack")
            return np.asarray(t.numpy())  # e.g. offload to host

        def unpack(h):
            events.append("unpack")
            return paddle.to_tensor(h)

        class Square(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                return dy * 2.0 * x

        x = paddle.to_tensor(np.array([3.0], np.float32))
        x.stop_gradient = False
        with saved_tensors_hooks(pack, unpack):
            y = Square.apply(x)
        y.backward()
        assert events == ["pack", "unpack"]
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_hooks_passthrough(self):
        from paddle_tpu.autograd import PyLayer

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                return dy * 3.0 * x * x

        x = paddle.to_tensor(np.array([2.0], np.float32))
        x.stop_gradient = False
        Cube.apply(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])
