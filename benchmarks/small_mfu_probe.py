"""Small-shape MFU decomposition (BASELINE.md round-3 weak #2): why do
21M (h=512) and 168M (h=1024) sit at 0.485 / 0.548 MFU while 542M
reaches 0.774? Measures, per config and batch size:

- full AdamW step (the recorded row),
- SGD step (optimizer-pass cost by substitution: AdamW - SGD isolates
  the moment math; SGD - fwd/bwd bounds the write+infra cost),
- "none" (grads computed then discarded): NOTE XLA dead-code-eliminates
  the unused backward, so this row is effectively FORWARD-ONLY — treat
  it as a lower bound, not a fwd+bwd measurement,

and reports the analytic lm-head (CE) FLOP fraction — at h=512 the
2*h*V head matmul is the largest single GEMM and the vocab-32k softmax
is bandwidth-heavy relative to the tiny model body.

Run: PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/small_mfu_probe.py
"""
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as popt
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.tensor import manipulation as M

PEAK = 197e12  # v5e bf16


def probe(name, config, batch, seq, steps=96,
          variants=("adamw", "sgd", "none")):
    import jax

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    model.bfloat16()
    rows = {}
    for opt_name in variants:
        if opt_name == "adamw":
            opt = popt.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True, moment_dtype="bfloat16")
        elif opt_name == "sgd":
            opt = popt.SGD(learning_rate=1e-5,
                           parameters=model.parameters())
        else:
            opt = None

        def step(ids, labels):
            logits = model(ids)
            b, s, v = logits.shape
            loss = F.cross_entropy(
                M.reshape(logits, [b * s, v]), M.reshape(labels, [b * s]))
            loss.backward()
            if opt is not None:
                opt.step()
                opt.clear_grad()
            else:
                for p in model.parameters():
                    p.clear_grad()
            return loss

        compiled = paddle.jit.to_static(
            step, layers=[model],
            optimizers=[opt] if opt is not None else [])
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, config.vocab_size, (batch, seq))
        ids = paddle.to_tensor(ids_np.astype("int32"))
        labels = paddle.to_tensor(ids_np.astype("int32"))
        compiled(ids, labels)
        k1, k2 = 4, steps
        np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
        np.asarray(compiled.multi_step(ids, labels, steps=k2)._data)
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(compiled.multi_step(ids, labels, steps=k2)._data)
            t2 = time.perf_counter() - t0
            t0 = time.perf_counter()
            np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
            t1 = time.perf_counter() - t0
            best = min(best, (t2 - t1) / (k2 - k1))
        rows[opt_name] = best * 1e3

    fpt = model.flops_per_token(seq)
    tok = batch * seq
    mfu = tok * fpt / (rows["adamw"] / 1e3) / PEAK
    head_frac = 6 * config.hidden_size * config.vocab_size / fpt
    extra = "".join(
        f" | {k} {v:.2f} ms" for k, v in rows.items() if k != "adamw")
    print(f"{name} B={batch} S={seq}: adamw {rows['adamw']:.2f} ms"
          f"{extra} | MFU {mfu:.3f} | head(CE) flop frac {head_frac:.2f}",
          flush=True)
    return rows, mfu


tiny = LlamaConfig(vocab_size=32000, hidden_size=512, intermediate_size=2048,
                   num_hidden_layers=4, num_attention_heads=8,
                   num_key_value_heads=8, max_position_embeddings=2048)
small = LlamaConfig(vocab_size=32000, hidden_size=1024, intermediate_size=4096,
                    num_hidden_layers=8, num_attention_heads=8,
                    num_key_value_heads=8, max_position_embeddings=2048)

tiny256 = LlamaConfig(vocab_size=256, hidden_size=512,
                      intermediate_size=2048, num_hidden_layers=4,
                      num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=2048)

if __name__ == "__main__":
    probe("21M-v32k", tiny, 8, 512)
    probe("21M-v32k", tiny, 32, 512)
    probe("168M", small, 8, 1024)
    # the ORIGINAL 21M row's config (v256): the true bandwidth-ceiling
    # shape; adamw-only keeps the run short
    probe("21M-v256", tiny256, 8, 512, steps=64, variants=("adamw",))
