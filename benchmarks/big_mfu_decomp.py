"""Big-regime MFU decomposition (round-4 verdict Next #6): why does
1.59B sit at ~0.726 and S=8192 at ~0.719 while the 542M flagship
reaches 0.774-0.778? Per config, by substitution (the flagship's
methodology, BASELINE.md "Flagship step decomposition"):

- adamw            — the recorded row (bf16 moments; masterless for
                     1.59B where fp32 masters don't fit),
- adamw+interleave — the fused-optimizer-into-backward schedule
                     (optimizer.interleave_updates),
- fused_adamw      — interleave + the single-pass Pallas AdamW kernel
                     (AdamW(fused=True): one HBM read of p/g/m/v, one
                     write of p/m/v per layer, SR in-register),
- fp8              — every Linear except the lm_head swapped for
                     Fp8Linear (delayed-scaling e4m3/e5m2 GEMMs),
- sgd              — optimizer-pass cost by substitution,
- mean-loss        — cross_entropy replaced by logits.mean(): isolates
                     the 32k-vocab logsumexp/gather CE epilogue (the
                     lm-head GEMM stays),
- analytic fractions — attention and lm-head FLOP shares, since at
  S=8192 attention is ~1/3 of FLOPs at LOWER arithmetic intensity
  than the h=2048 GEMMs, capping achievable MFU below the dense-GEMM
  ceiling (~0.85 of peak on v5e, measured for the flagship).

Rows also land in the BENCH_LEDGER via obs.regress.bench_record, so
``obs regress`` tracks round-over-round movement.

Run (real chip):
    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/big_mfu_decomp.py
    BIG_ONLY=long|big limits to one config; BIG_STEPS overrides K.
    --smoke runs a tiny config few-step pass (CPU-safe: the fused
    kernel interprets, fp8 GEMMs run on XLA CPU) so CI exercises every
    variant's full compile+step path without a chip.
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _timing  # noqa: E402  (shared K-differencing timer)

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as popt
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.tensor import manipulation as M

PEAK = 197e12  # v5e bf16


VARIANTS = ("adamw", "interleave", "fused_adamw", "fp8", "sgd", "meanloss")


def probe(name, config, batch, seq, steps, multi_precision,
          variants=VARIANTS, record=True):
    paddle.seed(0)
    model = LlamaForCausalLM(config)
    model.bfloat16()
    rows = {}
    for variant in variants:
        model_v = model
        if variant == "fp8":
            # conversion swaps sublayers in place — give fp8 its own
            # identically-seeded model so later variants stay bf16
            from paddle_tpu.amp import convert_to_fp8

            paddle.seed(0)
            model_v = LlamaForCausalLM(config)
            model_v.bfloat16()
            convert_to_fp8(model_v, exclude=lambda n: "lm_head" in n)
        opt = None
        if variant in ("adamw", "interleave", "fused_adamw", "fp8",
                       "meanloss"):
            opt = popt.AdamW(
                learning_rate=1e-4, parameters=model_v.parameters(),
                multi_precision=multi_precision,
                use_stochastic_rounding=not multi_precision,
                moment_dtype="bfloat16",
                interleave_updates=(variant in ("interleave",
                                                "fused_adamw")),
                fused=(variant == "fused_adamw"))
        elif variant == "sgd":
            opt = popt.SGD(learning_rate=1e-5,
                           parameters=model_v.parameters())

        mean_loss = variant == "meanloss"

        def step(ids, labels):
            logits = model_v(ids)
            if mean_loss:
                loss = logits.mean()
            else:
                b, s, v = logits.shape
                loss = F.cross_entropy(
                    M.reshape(logits, [b * s, v]),
                    M.reshape(labels, [b * s]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, layers=[model_v],
                                        optimizers=[opt])
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, config.vocab_size, (batch, seq))
        ids = paddle.to_tensor(ids_np.astype("int32"))
        labels = paddle.to_tensor(ids_np.astype("int32"))
        compiled(ids, labels)
        rows[variant] = round(
            _timing.diff_time_ms(compiled, ids, labels, steps), 2)
        del opt, compiled, model_v

    fpt = model.flops_per_token(seq)
    tok = batch * seq
    mfu = {k: round(tok * fpt / (v / 1e3) / PEAK, 4)
           for k, v in rows.items()}
    c = config
    attn_frac = 12 * c.num_hidden_layers * c.hidden_size * seq / fpt
    head_frac = 6 * c.hidden_size * c.vocab_size / fpt
    print(json.dumps({
        "config": name, "batch": batch, "seq": seq,
        "step_ms": rows, "mfu": mfu,
        "attn_flop_frac": round(attn_frac, 3),
        "head_flop_frac": round(head_frac, 3),
        "params": model.num_params(),
    }), flush=True)
    if record:
        from paddle_tpu.obs.regress import bench_record

        cfg = {"config": name, "batch": batch, "seq": seq,
               "multi_precision": multi_precision}
        for variant, ms in rows.items():
            bench_record("big_mfu_decomp", f"step_ms_{variant}", ms,
                         "ms", config=cfg, mfu=mfu[variant])
    return rows, mfu


LONG = LlamaConfig(vocab_size=32000, hidden_size=2048,
                   intermediate_size=5632, num_hidden_layers=8,
                   num_attention_heads=16, num_key_value_heads=16,
                   max_position_embeddings=8192)
BIG = LlamaConfig(vocab_size=32000, hidden_size=2560,
                  intermediate_size=6912, num_hidden_layers=18,
                  num_attention_heads=20, num_key_value_heads=20,
                  max_position_embeddings=2048)

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config, 2 differencing steps — CPU-safe "
                         "compile+step coverage of every variant")
    args = ap.parse_args()
    if args.smoke:
        tiny = LlamaConfig.tiny()
        probe("smoke-tiny", tiny, 2, 32, 3, multi_precision=False)
        sys.exit(0)
    only = os.environ.get("BIG_ONLY")
    steps = int(os.environ.get("BIG_STEPS", 24))
    if only in (None, "long"):
        probe("long-S8192", LONG, 1, 8192, steps, multi_precision=True)
    if only in (None, "big"):
        # fp32 masters don't fit at 1.59B — masterless + SR (the
        # recorded BASELINE.md configuration)
        probe("big-1.59B", BIG, 1, 2048, steps, multi_precision=False)
