"""ResNet convergence on a procedurally generated, HELD-OUT-able image
task (BASELINE config #1 was "blocked on data (no egress)" — this
replaces it with synthetic-but-learnable data requiring real feature
learning, evaluated on a disjoint test set).

Task: 10-class texture classification. Class k's images are oriented
sinusoidal gratings with class-specific (frequency, orientation) plus
per-image random phase, offset, and Gaussian noise (SNR < 1) — a
linear probe on raw pixels fails (random phase decorrelates pixels
from the class), a convnet learns the spectral signature. Train and
eval sets are generated from different seeds.

Run on the real chip:

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/convergence_resnet.py

CI-short variant: tests/test_convergence.py (fewer classes/steps,
smaller CNN, looser target).
"""
import json
import time

import numpy as np


def make_images(n: int, num_classes: int, size: int, seed: int):
    """[n, 3, size, size] float32 textures + [n] labels."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n)
    yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
    imgs = np.empty((n, 3, size, size), np.float32)
    for i in range(n):
        k = labels[i]
        freq = 0.6 + 0.35 * k          # class-specific frequency
        theta = (k * np.pi / num_classes) + rng.randn() * 0.05
        phase = rng.rand() * 2 * np.pi  # random phase: no fixed pixel cue
        wave = np.sin(freq * (np.cos(theta) * xx + np.sin(theta) * yy) + phase)
        base = wave[None] * np.array([1.0, 0.8, 0.6])[:, None, None]
        imgs[i] = base + rng.randn(3, size, size) * 1.2 + rng.randn() * 0.3
    return imgs.astype(np.float32), labels.astype(np.int64)


def run(num_classes=10, size=32, train_n=8000, eval_n=1000, batch=128,
        steps=600, eval_every=100, lr=1e-3, target_acc=0.95,
        model_fn=None, log=print):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt

    xs, ys = make_images(train_n, num_classes, size, seed=1)
    xe, ye = make_images(eval_n, num_classes, size, seed=2)

    paddle.seed(0)
    if model_fn is None:
        from paddle_tpu.vision.models import resnet18

        model = resnet18(num_classes=num_classes)
    else:
        model = model_fn(num_classes)
    opt = popt.AdamW(learning_rate=lr, parameters=model.parameters(),
                     weight_decay=1e-4)

    def step_fn(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    import paddle_tpu.jit as pjit

    train_step = pjit.to_static(step_fn, layers=[model], optimizers=[opt])

    def eval_acc():
        from paddle_tpu.base.tape import no_grad

        model.eval()
        hits = 0
        with no_grad():
            for i in range(0, eval_n, batch):
                logits = model(paddle.to_tensor(xs_e[i:i + batch]))
                hits += int(
                    (np.asarray(logits._data).argmax(-1)
                     == ye[i:i + batch]).sum())
        model.train()
        return hits / eval_n

    xs_e = xe
    rng = np.random.RandomState(7)
    curve = []
    t0 = time.time()
    for step in range(1, steps + 1):
        idx = rng.randint(0, train_n, batch)
        loss = train_step(paddle.to_tensor(xs[idx]),
                          paddle.to_tensor(ys[idx]))
        if step % eval_every == 0 or step == steps:
            acc = eval_acc()
            curve.append({"step": step, "train_loss": round(float(loss), 4),
                          "eval_acc": round(acc, 4)})
            log(f"step {step:5d}  train {float(loss):.4f}  eval_acc "
                f"{acc:.4f}  {time.time()-t0:.0f}s")
    final = curve[-1]["eval_acc"]
    result = {
        "metric": "heldout_accuracy", "value": final,
        "target": target_acc, "reached": bool(final >= target_acc),
        "curve": curve,
    }
    log(json.dumps(result))
    return result


if __name__ == "__main__":
    run()
