"""Fault-tolerant-training recovery bench (BASELINE.md row): how long a
killed-and-relaunched rank takes to get back to training, RAM tier vs
disk tier.

Three measured columns over the same model state:

- **snapshot overhead** — what one in-RAM snapshot costs the train
  thread (reference capture; no serialization) and what one peer
  publish costs end to end (serialize + CRC frame + store put);
- **RAM-tier recovery** — a fresh process-equivalent rig restoring
  from the peer-replicated snapshot: ``resume()`` fetch + verify +
  deserialize + rebind;
- **disk-tier recovery** — the same rig restoring from the newest
  ``AutoCheckpoint`` directory (scan + CRC verify + unpickle + rebind).

The point of the two-tier design is the ratio: peer RAM must be
decisively cheaper than disk for the Gemini-style architecture to pay
its replication cost. On this CPU harness the store is in-process
(MemKVStore) so the RAM column is an upper bound on protocol overhead,
not a network measurement — the TPU/multi-host column (TCP store,
real pod) lands with the tunnel (ROADMAP item 1).

``--model`` picks mlp (default, instant) or llama (LlamaConfig.tiny —
a transformer-shaped state dict). ``--steps``/``--interval`` shape the
run. Emits one JSON line per row plus a summary table.

Run: PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/trainfault_bench.py
"""
import argparse
import json
import os
import shutil
import tempfile
import time

import numpy as np

# the sharded column needs a 2-way mesh; force host vdevs before the
# first jax backend query (no-op when a harness already set the flag)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as popt
from paddle_tpu.distributed.store import MemKVStore
from paddle_tpu.incubate.checkpoint.auto_checkpoint import AutoCheckpoint
from paddle_tpu.training import PeerReplicator, TrainingSupervisor

ap = argparse.ArgumentParser()
ap.add_argument("--model", choices=["mlp", "llama"], default="mlp")
ap.add_argument("--steps", type=int, default=20)
ap.add_argument("--interval", type=int, default=5)
ap.add_argument("--repeat", type=int, default=5,
                help="recovery timing repetitions (median reported)")
args = ap.parse_args()


def build(ckpt_dir=None, store=None, tag="bench"):
    paddle.seed(0)
    if args.model == "llama":
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        model = LlamaForCausalLM(LlamaConfig.tiny())
        vocab = model.config.vocab_size

        def step_fn(batch):
            x = paddle.to_tensor(batch)
            logits = model(x)
            loss = F.cross_entropy(
                logits[:, :-1].reshape([-1, vocab]),
                paddle.to_tensor(batch[:, 1:].reshape(-1)))
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.RandomState(7)
        data = [rng.randint(0, vocab, (2, 32)).astype(np.int64)
                for _ in range(64)]
    else:
        model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                              nn.Linear(256, 64))

        def step_fn(batch):
            x, y = paddle.to_tensor(batch[0]), paddle.to_tensor(batch[1])
            loss = F.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.RandomState(7)
        data = [(rng.randn(16, 64).astype(np.float32),
                 rng.randn(16, 64).astype(np.float32))
                for _ in range(64)]
    opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def batch_fn(i):
        return data[(i - 1) % len(data)]

    ac = None
    if ckpt_dir is not None:
        ac = AutoCheckpoint(ckpt_dir, layers=[model], optimizers=[opt],
                            save_interval_steps=args.interval,
                            async_save=False)
    peer = PeerReplicator(store, 0, 1, tag=tag) if store is not None \
        else None
    return TrainingSupervisor(
        step_fn, batch_fn, layers=[model], optimizers=[opt],
        snapshot_interval=args.interval, peer=peer, auto_checkpoint=ac)


def build_sharded(ckpt_dir=None, store=None, tag="bench_sh"):
    """The pod-scale rig (ISSUE 16): stage-``os`` group-sharded
    optimizer state over a ("sharding", 2) mesh, supervisor in
    ``sharded_state`` mode — the peer tier ships per-rank SHARD
    payloads through ``distributed/checkpoint/reshard`` (gather +
    coverage-checked combine on resume) instead of one whole-state
    pickle, while the disk tier stays whole-state AutoCheckpoint."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.distributed.collective import Group
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, 64))
    opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    mesh = Mesh(np.array(jax.devices()[:2]), ("sharding",))
    model, opt, _ = group_sharded_parallel(
        model, opt, "os", group=Group([0, 1], "sharding", mesh=mesh))

    def step_fn(batch):
        x, y = paddle.to_tensor(batch[0]), paddle.to_tensor(batch[1])
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(7)
    data = [(rng.randn(16, 64).astype(np.float32),
             rng.randn(16, 64).astype(np.float32))
            for _ in range(64)]

    def batch_fn(i):
        return data[(i - 1) % len(data)]

    ac = None
    if ckpt_dir is not None:
        ac = AutoCheckpoint(ckpt_dir, layers=[model], optimizers=[opt],
                            save_interval_steps=args.interval,
                            async_save=False)
    peer = PeerReplicator(store, 0, 1, tag=tag) if store is not None \
        else None
    return TrainingSupervisor(
        step_fn, batch_fn, layers=[model], optimizers=[opt],
        snapshot_interval=args.interval, peer=peer, auto_checkpoint=ac,
        sharded_state=True,
        state_layout={"world": 1, "mesh": {"sharding": 2}})


# headline value per row kind — what the regression sentinel grades
# (all are latencies: down-is-good polarity from the _s suffix)
_ROW_HEADLINE = {"overhead": "step_s", "recovery": "ram_tier_s",
                 "sharded_recovery": "ram_tier_s"}


def emit(row):
    """One framed row through the shared obs ledger writer (ISSUE 15):
    the ``BENCH_ROW {json}`` stdout contract is unchanged (every row
    key stays top-level); the record also lands in BENCH_LEDGER."""
    from paddle_tpu.obs.regress import bench_record

    kind = row.get("row", "row")
    headline = _ROW_HEADLINE.get(kind)
    bench_record(row.get("bench", "trainfault"),
                 f"trainfault_{kind}_{headline}" if headline else
                 f"trainfault_{kind}",
                 row.get(headline) if headline else None,
                 "s", line_prefix="BENCH_ROW ",
                 **{k: v for k, v in row.items() if k != "bench"})


def main():
    scratch = tempfile.mkdtemp(prefix="trainfault_bench_")
    store = MemKVStore()
    try:
        sup = build(ckpt_dir=scratch, store=store)
        n_params = sum(
            int(np.prod(p.shape)) for p in sup.layers[0].parameters())

        # steady-state step time (for context) + snapshot overheads
        t0 = time.perf_counter()
        sup.run(args.steps)
        step_s = (time.perf_counter() - t0) / args.steps
        t0 = time.perf_counter()
        sup._take_snapshot(args.steps)
        sup.peer.drain()
        snap_plus_publish_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = sup._capture(args.steps)
        capture_s = time.perf_counter() - t0
        payload = sup._serialize(state)
        emit({"bench": "trainfault", "row": "overhead",
              "model": args.model, "params": n_params,
              "step_s": round(step_s, 6),
              "ram_capture_s": round(capture_s, 6),
              "snapshot_plus_peer_publish_s":
                  round(snap_plus_publish_s, 6),
              "payload_bytes": len(payload)})

        # recovery timings: fresh rig each repetition, like a relaunch
        def timed_resume(**kw):
            rig = build(**kw)
            t0 = time.perf_counter()
            start = rig.resume()
            dt = time.perf_counter() - t0
            assert start == args.steps + 1, (start, kw)
            return dt

        ram = sorted(timed_resume(store=store) for _ in range(args.repeat))
        disk = sorted(timed_resume(ckpt_dir=scratch)
                      for _ in range(args.repeat))
        ram_s = ram[len(ram) // 2]
        disk_s = disk[len(disk) // 2]
        emit({"bench": "trainfault", "row": "recovery",
              "model": args.model, "params": n_params,
              "ram_tier_s": round(ram_s, 6),
              "disk_tier_s": round(disk_s, 6),
              "disk_over_ram": round(disk_s / max(ram_s, 1e-9), 2)})
        print(f"\n{args.model} ({n_params:,} params): "
              f"step {step_s * 1e3:.2f} ms | RAM capture "
              f"{capture_s * 1e6:.0f} us | peer publish (sync) "
              f"{snap_plus_publish_s * 1e3:.2f} ms | payload "
              f"{len(payload) / 1e6:.2f} MB")
        print(f"recovery: RAM tier {ram_s * 1e3:.2f} ms vs disk tier "
              f"{disk_s * 1e3:.2f} ms ({disk_s / max(ram_s, 1e-9):.1f}x)")
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    # sharded kill-and-resume column (ISSUE 16): same two tiers, but
    # the state is group-sharded and the RAM tier restores through the
    # reshard gather/combine path — the shape the pod-scale elastic
    # resume (tests/test_elastic_shard.py) exercises across real
    # process boundaries
    sh_scratch = tempfile.mkdtemp(prefix="trainfault_sh_")
    sh_store = MemKVStore()
    try:
        from paddle_tpu.distributed.checkpoint import reshard

        sup = build_sharded(ckpt_dir=sh_scratch, store=sh_store)
        sup.run(args.steps)
        sup._take_snapshot(args.steps)
        sup.peer.drain()
        payload = sup._serialize(sup._capture(args.steps))
        n_sharded = reshard.sharded_leaf_count(payload)

        def timed_sharded(**kw):
            rig = build_sharded(**kw)
            t0 = time.perf_counter()
            start = rig.resume()
            dt = time.perf_counter() - t0
            assert start == args.steps + 1, (start, kw)
            return dt

        ram = sorted(timed_sharded(store=sh_store)
                     for _ in range(args.repeat))
        disk = sorted(timed_sharded(ckpt_dir=sh_scratch)
                      for _ in range(args.repeat))
        ram_s = ram[len(ram) // 2]
        disk_s = disk[len(disk) // 2]
        emit({"bench": "trainfault", "row": "sharded_recovery",
              "model": "mlp", "shard_degree": 2,
              "sharded_leaves": n_sharded,
              "payload_bytes": len(payload),
              "ram_tier_s": round(ram_s, 6),
              "disk_tier_s": round(disk_s, 6),
              "disk_over_ram": round(disk_s / max(ram_s, 1e-9), 2)})
        print(f"sharded recovery (os over 2-way mesh, {n_sharded} "
              f"sharded leaves): RAM tier {ram_s * 1e3:.2f} ms vs disk "
              f"tier {disk_s * 1e3:.2f} ms "
              f"({disk_s / max(ram_s, 1e-9):.1f}x)")
    finally:
        shutil.rmtree(sh_scratch, ignore_errors=True)


if __name__ == "__main__":
    main()
