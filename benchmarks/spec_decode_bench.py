"""Speculative-decoding serving row (BASELINE.md): acceptance rate x
decode tokens/s at draft depth k in {2, 4, 8} vs the k=None baseline,
same engine, same session.

Methodology (RTT-free by subtraction, decode_bench.py style): each
row times TWO full engine drains of the same warm engine config —
max_new_tokens = NEW_BIG and NEW_SMALL — and reports
(t_big - t_small) / (tokens_big - tokens_small): prefill, admission
and any residual compile cancel, leaving pure steady-state decode.
Speculation's win is TOKENS PER DISPATCH: a verify round emits
1 + accepted tokens per slot where plain decode emits exactly 1, so
at host-RTT-bound serving sizes tok/s scales with the acceptance
rate. The workload is REPETITIVE prompts (shared n-gram structure,
the prompt-lookup proposer's habitat — retrieval/code/boilerplate
traffic in production terms).

Runs under the ``BENCH_TOTAL_BUDGET`` supervisor deadline (default
600 s; rows emit incrementally so a timeout still lands partial
JSON). CPU smoke mode engages automatically off-TPU (tiny model,
small budgets) — it validates the harness and the acceptance-rate
plumbing, not absolute throughput.

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/spec_decode_bench.py

ref: Leviathan et al. 2023 (speculative sampling), Saxena 2023
(prompt lookup decoding), vLLM ngram speculative config.
"""
import argparse
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.utils.retries import Deadline


def build_engine(model, on_tpu, spec_k, max_len):
    if on_tpu:
        B, BS, PAD = 8, 64, 2048
    else:
        B, BS, PAD = 4, 8, 64
    return ContinuousBatchingEngine(
        model, max_batch=B, max_len=max_len, block_size=BS,
        num_blocks=B * (-(-max_len // BS)) + 2, prompt_pad=PAD,
        spec_decode_k=spec_k)


def timed_drain(eng, prompts, new_tokens, tag):
    """One full drain on an ALREADY-WARM engine (the engine's compiled
    phases persist across drains, so the big-minus-small subtraction
    cancels prefill + host scheduling, leaving steady-state decode)."""
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        eng.add_request(f"{tag}{i}", p, max_new_tokens=new_tokens)
    done = eng.run()
    wall = time.perf_counter() - t0
    toks = sum(len(done[f"{tag}{i}"].out) for i in range(len(prompts)))
    return wall, toks


def spec_row(model, on_tpu, spec_k, prompts, big, small, max_len):
    eng = build_engine(model, on_tpu, spec_k, max_len)
    # warm every phase outside the measured window (incl. the spec
    # verify program: a repetitive warm prompt guarantees a draft)
    warm = np.tile(np.arange(4, dtype=np.int32), 6)
    eng.add_request("warm", warm, max_new_tokens=8)
    eng.run()
    st0, rounds0 = eng.spec_stats(), eng.spec_slot_rounds
    w_big, t_big = timed_drain(eng, prompts, big, "b")
    st1, rounds1 = eng.spec_stats(), eng.spec_slot_rounds
    w_small, t_small = timed_drain(eng, prompts, small, "s")
    tps = (t_big - t_small) / max(w_big - w_small, 1e-9)
    # every quality stat is a BIG-WINDOW delta, matching the tok/s
    # methodology (the warm request's rounds must not contaminate)
    proposed = st1["proposed"] - st0["proposed"]
    accepted = st1["accepted"] - st0["accepted"]
    emitted = st1["emitted"] - st0["emitted"]
    rounds = rounds1 - rounds0
    return tps, {
        "acceptance_rate": (accepted / proposed) if proposed else 0.0,
        "tokens_per_slot_round": (emitted / rounds) if rounds else 0.0,
        "proposed_big_window": proposed,
        "emitted_big_window": emitted,
    }


def main():
    argparse.ArgumentParser().parse_args()
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.9)

    if on_tpu:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048)
        P, NEW_BIG, NEW_SMALL, MAX_LEN, NPROMPT = 512, 256, 16, 1024, 8
    else:
        config = LlamaConfig.tiny()
        P, NEW_BIG, NEW_SMALL, MAX_LEN, NPROMPT = 16, 24, 6, 64, 4

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()

    rng = np.random.RandomState(0)
    # repetitive prompts: a short base phrase tiled to length P
    prompts = []
    for i in range(NPROMPT):
        base = rng.randint(0, config.vocab_size, (P // 4,))
        prompts.append(np.tile(base, 5)[:P].astype(np.int32))

    rows = {}
    baseline_tps = None
    for k in (None, 2, 4, 8):
        if dl.expired():
            from paddle_tpu.obs.regress import bench_record
            bench_record("spec_decode", "spec_decode_best_speedup",
                         None, "", error="budget exhausted",
                         partial=rows)
            return
        tps, st = spec_row(model, on_tpu, k, prompts, NEW_BIG,
                           NEW_SMALL, MAX_LEN)
        label = "off" if k is None else f"k{k}"
        rows[label] = {
            "tok_s": round(tps, 1),
            "acceptance_rate": round(st["acceptance_rate"], 4),
            "tokens_per_slot_round": round(st["tokens_per_slot_round"], 3),
        }
        if k is None:
            baseline_tps = tps
        else:
            rows[label]["speedup"] = round(tps / baseline_tps, 3)
        print(f"[spec] {label}: {tps:.0f} tok/s  "
              f"accept={st['acceptance_rate']:.3f}  "
              f"tok/slot-round={st['tokens_per_slot_round']:.2f}",
              flush=True)

    best = max((r["speedup"] for r in rows.values() if "speedup" in r),
               default=None)
    from paddle_tpu.obs.regress import bench_record
    bench_record(
        "spec_decode", "spec_decode_best_speedup", best,
        "x decode tok/s vs spec-off (best k)",
        extra={
            "rows": rows,
            "prompt_len": P,
            "new_tokens_big_small": [NEW_BIG, NEW_SMALL],
            "device": getattr(dev, "device_kind", str(dev)),
            "cpu_smoke": not on_tpu,
        })


if __name__ == "__main__":
    main()
