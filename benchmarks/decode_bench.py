"""Decode-throughput bench: dense KV cache vs the paged paths, measured
two ways — the BASELINE.md decode rows. Run on the real chip:

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/decode_bench.py

1. **multi_step scan rows** (primary): per-step cost of the compiled
   decode scanned K steps in ONE dispatch (decode_chunk machinery),
   differenced between K=16 and K=256 — the tunnel/host RTT appears
   once per dispatch and cancels, so rows are stable across sessions.
2. **per-token dispatch rows** (context): the classic one-dispatch-per-
   token loop; dominated by tunnel RTT (±2x between sessions), only
   same-session rows compare.

Variants: dense cache; paged contiguous (reshape-view path); paged
kernel (Pallas paged-attention forced, the ragged-table path); paged
gather (fancy-index fallback, forced). Set GQA=1 in the env to use
num_key_value_heads=2 (the kernel's winning regime)."""
import os
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.ops.paged_attention as PA
from paddle_tpu import to_tensor
from paddle_tpu.base.tape import no_grad
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import _get_compiled, generate

KVH = 2 if os.getenv("GQA") else 16
config = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=8, num_attention_heads=16,
                     num_key_value_heads=KVH, max_position_embeddings=2048)
paddle.seed(0)
model = LlamaForCausalLM(config)
model.bfloat16()
B, P, NEW = 8, int(os.getenv("PROMPT", 512)), 300
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, 32000, (B, P)).astype(np.int64))

orig = PA.paged_decode_attention


def force_kernel(q, kp, vp, tbl, cl, contiguous=False):
    return orig(q, kp, vp, tbl, cl, contiguous=False)


def force_gather(q, kp, vp, tbl, cl, contiguous=False):
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _naive_attention

    kc, vc = PA.paged_gather_kv(kp, vp, tbl)
    max_len = kc.shape[1]
    mask = (jnp.arange(max_len)[None, :]
            <= jnp.asarray(cl).reshape(-1, 1))[:, None, None, :]
    return _naive_attention(q, kc, vc, mask, 0.0, False, None, None)


def scan_row(label, block_size):
    with no_grad():
        model._generation_programs = {}
        state, prefill, decode = _get_compiled(
            model, B, P, P + NEW, 0.0, 0, True,
            block_size=block_size, chunked=True, eos_token_id=None)

        def fresh():
            state.reset()
            prefill(ids, to_tensor(np.asarray(0, np.int32)))
            decode(to_tensor(np.asarray(P, np.int32)))

        def curs(k):
            return to_tensor(np.arange(P + 1, P + 1 + k, dtype=np.int32))

        for k in (16, 256):  # compile both scan lengths
            fresh()
            np.asarray(decode.multi_step(curs(k))._data)
        best = 1e9
        for _ in range(3):
            fresh()
            t0 = time.perf_counter()
            np.asarray(decode.multi_step(curs(256))._data)
            t256 = time.perf_counter() - t0
            fresh()
            t0 = time.perf_counter()
            np.asarray(decode.multi_step(curs(16))._data)
            t16 = time.perf_counter() - t0
            best = min(best, (t256 - t16) / 240)
    print(f"[scan] {label}: {best*1e3:.3f} ms/step = {B/best:.0f} tok/s",
          flush=True)


def per_token_row(label, kw):
    model._generation_programs = {}
    for n in (32, 96):
        generate(model, ids, max_new_tokens=n, temperature=0.0, **kw)
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        np.asarray(generate(model, ids, max_new_tokens=96,
                            temperature=0.0, **kw)._data)
        t96 = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(generate(model, ids, max_new_tokens=32,
                            temperature=0.0, **kw)._data)
        t32 = time.perf_counter() - t0
        best = min(best, t96 - t32)
    print(f"[per-token] {label}: {B*64/best:.0f} tok/s "
          f"({best/64*1e3:.2f} ms/token)", flush=True)


print(f"config: 542M-class, B={B}, P={P}, kv_heads={KVH}")
scan_row("dense", None)
scan_row("paged contiguous", 64)
PA.paged_decode_attention = force_kernel
scan_row("paged kernel (forced)", 64)
PA.paged_decode_attention = force_gather
scan_row("paged gather (forced)", 64)
PA.paged_decode_attention = orig

per_token_row("dense", {})
per_token_row("paged contiguous", {"block_size": 64})
per_token_row("dense chunked(32)", {"decode_chunk": 32})
per_token_row("paged chunked(32)", {"decode_chunk": 32, "block_size": 64})
