"""Decode-throughput bench: dense KV cache vs paged (Pallas kernel)
vs paged (gather fallback, monkeypatched) — the BASELINE.md decode
rows. Run on the real chip:

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/decode_bench.py

Tunnel RTT varies +-2x between sessions; only same-session rows
compare. Set P below for the long-prompt regime."""
import time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
import paddle_tpu.ops.paged_attention as PA

config = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=16,
                     max_position_embeddings=2048)
paddle.seed(0)
model = LlamaForCausalLM(config)
model.bfloat16()
B, P = 8, 1792
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, 32000, (B, P)).astype(np.int64))

orig = PA.paged_decode_attention

def measure(label, kw):
    model._generation_programs = {}
    for n in (32, 96):
        generate(model, ids, max_new_tokens=n, temperature=0.0, **kw)
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        np.asarray(generate(model, ids, max_new_tokens=96, temperature=0.0, **kw)._data)
        t96 = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(generate(model, ids, max_new_tokens=32, temperature=0.0, **kw)._data)
        t32 = time.perf_counter() - t0
        best = min(best, t96 - t32)
    print(f"{label}: {B*64/best:.0f} tok/s ({best/64*1e3:.2f} ms/token)")

measure("dense", {})
measure("paged+kernel", {"block_size": 64})

# gather fallback: force the non-kernel path
def no_kernel(q, k_pool, v_pool, tables, cache_len):
    import jax, jax.numpy as jnp
    kc, vc = PA.paged_gather_kv(k_pool, v_pool, tables)
    max_len = kc.shape[1]
    valid = (jnp.arange(max_len)[None, :] <= cache_len)
    h = q.shape[2]
    rep = h // kc.shape[2]
    ks = jnp.repeat(kc, rep, axis=2); vs = jnp.repeat(vc, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, ks) / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32)).astype(q.dtype)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vs)

# the llama paged-decode branch does `from ..ops.paged_attention import
# paged_decode_attention` inside the traced step, so rebinding the
# module attribute here DOES take effect for the fresh trace below
PA.paged_decode_attention = no_kernel
measure("paged+gather", {"block_size": 64})
PA.paged_decode_attention = orig
