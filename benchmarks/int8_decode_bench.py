"""int8 serving row (BASELINE.md): decode throughput + quality delta of
convert(execute_dtype="int8") vs bf16 on the 542M-class model, same
session (ref: the reference's llm.int8 deploy path,
paddle/phi/kernels/impl/llm_int8_matmul_kernel_impl.h).

Quantization: every nn.Linear (q/k/v/o, MLP, lm_head) swaps to
Int8InferenceLinear — per-out-channel int8 weights + dynamic activation
quantization, int8 x int8 -> int32 MXU dot (nn/quant). Memory: weights
drop 2 bytes -> 1 byte/param; decode at small batch is weight-streaming
bound, so int8 should WIN tokens/s, not just match.

``--kv int8`` (default) appends the KV-CACHE quantization column:
paged bf16 pools vs paged int8 pools + per-block scale pools
(``kv_dtype="int8"``, ops/paged_attention.py) under the same scan
methodology, plus the paged-prefill last-logit rel-err quality gate.
KV bytes halve; at serving batch the decode roofline is KV-bandwidth
bound, so int8 KV should WIN tok/s like int8 weights did.
``--smoke`` runs the whole bench on a tiny config (CPU harness
validation; absolute numbers meaningless).

Run: PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/int8_decode_bench.py
"""
import argparse
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import to_tensor
from paddle_tpu.base.tape import no_grad
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import _get_compiled, generate
from paddle_tpu.quantization import QAT, QuantConfig, quanter

ap = argparse.ArgumentParser()
ap.add_argument("--kv", choices=["none", "int8"], default="int8",
                help="append the int8 KV-cache column (paged pools)")
ap.add_argument("--smoke", action="store_true",
                help="tiny config for a CPU harness-validation run")
args = ap.parse_args()

if args.smoke:
    config = LlamaConfig.tiny()
    B, P, NEW, KV_BS = 2, 16, 24, 8
else:
    config = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=8, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=2048)
    B, P, NEW, KV_BS = 8, 512, 300, 64
paddle.seed(0)
model = LlamaForCausalLM(config)
if not args.smoke:
    model.bfloat16()
rng = np.random.RandomState(0)
ids = paddle.to_tensor(
    rng.randint(0, config.vocab_size, (B, P)).astype(np.int64))


def scan_row(m, label, block_size=None, kv_dtype=None):
    with no_grad():
        m._generation_programs = {}
        state, prefill, decode = _get_compiled(
            m, B, P, P + NEW, 0.0, 0, True, chunked=True,
            eos_token_id=None, block_size=block_size, kv_dtype=kv_dtype)

        k_big = min(256, NEW - 4)
        k_small = max(k_big // 16, 1)

        def fresh():
            state.reset()
            prefill(ids, to_tensor(np.asarray(0, np.int32)))
            decode(to_tensor(np.asarray(P, np.int32)))

        def curs(k):
            return to_tensor(np.arange(P + 1, P + 1 + k, dtype=np.int32))

        for k in (k_small, k_big):
            fresh()
            np.asarray(decode.multi_step(curs(k))._data)
        best = 1e9
        for _ in range(3):
            fresh()
            t0 = time.perf_counter()
            np.asarray(decode.multi_step(curs(k_big))._data)
            t256 = time.perf_counter() - t0
            fresh()
            t0 = time.perf_counter()
            np.asarray(decode.multi_step(curs(k_small))._data)
            t16 = time.perf_counter() - t0
            best = min(best, (t256 - t16) / (k_big - k_small))
    print(f"[scan] {label}: {best*1e3:.3f} ms/step = {B/best:.0f} tok/s",
          flush=True)
    return best


def greedy_tokens(m, n=None):
    n = min(64, NEW) if n is None else n
    out = generate(m, ids, max_new_tokens=n, temperature=0.0,
                   decode_chunk=min(32, n))
    return np.asarray(out._data)[:, P:]


def last_logits(m):
    with no_grad():
        caches = m.init_cache(B, P + 4)
        logits, _ = m.forward_with_cache(
            ids, caches, to_tensor(np.asarray(0, np.int32)))
    return np.asarray(logits._data[:, -1].astype("float32"))


# ---- bf16 reference ------------------------------------------------------
bf16_ms = scan_row(model, "bf16")
ref_tokens = greedy_tokens(model)
ref_logits = last_logits(model)

# ---- int8 conversion -----------------------------------------------------
# weight-only int8 deploy: no fake-quant projection — Int8InferenceLinear
# encodes each layer's weight with its TRUE per-out-channel absmax scale
cfg = QuantConfig(activation=None, weight=None)
qat = QAT(cfg)
model = qat.quantize(model)
model = qat.convert(model, execute_dtype="int8")
n_int8 = sum(1 for _, s in model.named_sublayers()
             if type(s).__name__ == "Int8InferenceLinear")
print(f"converted {n_int8} Linear layers to int8 execution")

int8_ms = scan_row(model, "int8")
int8_tokens = greedy_tokens(model)
int8_logits = last_logits(model)

match = float((ref_tokens == int8_tokens).mean())
rel = float(np.abs(int8_logits - ref_logits).mean()
            / (np.abs(ref_logits).mean() + 1e-9))
# top-5 containment: random-weight logits have near-tie argmaxes, so
# exact greedy match understates quality — check the int8 argmax lands
# in the bf16 top-5
top5 = np.argsort(ref_logits, axis=-1)[:, -5:]
in_top5 = float(np.mean([
    int8_logits[i].argmax() in top5[i] for i in range(B)]))
print(f"quality: greedy token match {match:.3f} over {ref_tokens.shape[1]} "
      f"tokens x {B} seqs; prefill last-logit rel err {rel:.4f}; "
      f"int8 argmax in bf16 top-5: {in_top5:.2f}")
print(f"speedup int8 vs bf16: {bf16_ms/int8_ms:.2f}x")


# ---- int4 weight-only conversion -----------------------------------------
# packed two-per-byte weights (0.5 B/param streamed) + group-64 scales;
# compute dequantizes into the bf16 MXU feed (nn/quant WeightOnlyLinear)
from paddle_tpu.nn.quant import convert_to_weight_only

paddle.seed(0)
model4 = LlamaForCausalLM(config)
if not args.smoke:
    model4.bfloat16()
n_int4 = convert_to_weight_only(model4, weight_dtype="int4", group_size=64)
print(f"converted {n_int4} Linear layers to packed-int4 weight-only")

int4_ms = scan_row(model4, "int4")
int4_tokens = greedy_tokens(model4)
int4_logits = last_logits(model4)
match4 = float((ref_tokens == int4_tokens).mean())
rel4 = float(np.abs(int4_logits - ref_logits).mean()
             / (np.abs(ref_logits).mean() + 1e-9))
in_top5_4 = float(np.mean([
    int4_logits[i].argmax() in top5[i] for i in range(B)]))
print(f"int4 quality: greedy match {match4:.3f}; prefill last-logit rel "
      f"err {rel4:.4f}; int4 argmax in bf16 top-5: {in_top5_4:.2f}")
print(f"SUMMARY ms/step: bf16 {bf16_ms*1e3:.3f} | int8 {int8_ms*1e3:.3f} "
      f"| int4 {int4_ms*1e3:.3f}  (same session)")


# ---- int8 KV-cache column (--kv int8) ------------------------------------
# the OTHER int8 lever: weight-only int8 halves weight bytes; paged
# kv_dtype="int8" halves KV bytes (pools + per-block scale pools,
# ops/paged_attention.py) — the lever that scales with BATCH and
# context, and doubles serving capacity on top of paged's block win
if args.kv == "int8":
    def last_logits_paged(m, kv_dtype=None):
        with no_grad():
            caches = m.init_cache(B, P + 4, block_size=KV_BS,
                                  kv_dtype=kv_dtype)
            logits, _ = m.forward_with_cache(
                ids, caches, to_tensor(np.asarray(0, np.int32)))
        return np.asarray(logits._data[:, -1].astype("float32"))

    paddle.seed(0)
    mkv = LlamaForCausalLM(config)
    if not args.smoke:
        mkv.bfloat16()
    paged_ms = scan_row(mkv, "paged-kv-bf16", block_size=KV_BS)
    kv8_ms = scan_row(mkv, "paged-kv-int8", block_size=KV_BS,
                      kv_dtype="int8")
    ref_kv_logits = last_logits_paged(mkv)
    kv8_logits = last_logits_paged(mkv, kv_dtype="int8")
    rel_kv = float(np.abs(kv8_logits - ref_kv_logits).mean()
                   / (np.abs(ref_kv_logits).mean() + 1e-9))
    top5_kv = np.argsort(ref_kv_logits, axis=-1)[:, -5:]
    in_top5_kv = float(np.mean([
        kv8_logits[i].argmax() in top5_kv[i] for i in range(B)]))
    print(f"int8-KV quality: prefill last-logit rel err {rel_kv:.4f}; "
          f"int8-KV argmax in bf16-KV top-5: {in_top5_kv:.2f}")
    print(f"KV column ms/step: paged-bf16 {paged_ms*1e3:.3f} | "
          f"paged-int8KV {kv8_ms*1e3:.3f}  "
          f"(speedup {paged_ms/kv8_ms:.2f}x; KV bytes halved)")
