"""Sustained continuous-batching throughput at fixed HBM.

The workload paged KV exists for (BASELINE.md serving-capacity row
proved the memory win; this measures the serving LOOP): requests with
mixed prompt lengths arrive continuously, finish at different times,
and the engine recycles their blocks into new admissions — report
sustained decode tokens/s and slot occupancy.

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/serving_throughput.py

ref: python/paddle/incubate/nn/functional/block_multihead_attention.py
(the reference's serving kernel; no published numbers in-tree).
"""
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048)
        B, MAX_LEN, BS, PAD = 64, 2048, 64, 512
        NUM_BLOCKS = B * (640 // BS) + 16  # ~640 live tokens/seq budget
        N_REQ, GEN = 192, 128
        prompt_lens = (256, 384, 512)
    else:  # mechanics check
        config = LlamaConfig.tiny()
        B, MAX_LEN, BS, PAD = 4, 64, 8, 16
        NUM_BLOCKS = 4 * 4 + 2
        N_REQ, GEN = 12, 8
        prompt_lens = (5, 9, 14)

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()

    rng = np.random.RandomState(0)
    eng = ContinuousBatchingEngine(
        model, max_batch=B, max_len=MAX_LEN, block_size=BS,
        num_blocks=NUM_BLOCKS, prompt_pad=PAD,
        decode_chunk=16 if on_tpu else 4)
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)

    # warm both compiled phases outside the timed region; throughput
    # counts only tokens produced inside the timed window
    eng.step()
    warm_toks = eng.decode_tokens
    t0 = time.perf_counter()
    occupancy = []
    while eng._queue or eng.num_active:
        eng.step()
        occupancy.append(eng.num_active)
    dt = time.perf_counter() - t0
    done = eng._completed
    assert len(done) == N_REQ, (len(done), N_REQ)
    toks = eng.decode_tokens - warm_toks
    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec",
        "value": round(toks / dt, 1),
        "unit": "tokens/s",
        "extra": {
            "requests": N_REQ, "gen_per_req": GEN, "max_batch": B,
            "num_blocks": NUM_BLOCKS, "block_size": BS,
            "decode_chunk": eng.decode_chunk,
            "mean_occupancy": round(float(np.mean(occupancy)), 2),
            "steps": eng.steps, "wall_s": round(dt, 2),
            "device": getattr(dev, "device_kind", str(dev)),
        },
    }))


if __name__ == "__main__":
    main()
