"""Continuous-batching serving benchmarks: sustained throughput at
fixed HBM, and the mixed-prompt-length latency comparison chunked
prefill exists for.

Part 1 (sustained): requests with mixed prompt lengths arrive
continuously, finish at different times, and the engine recycles their
blocks into new admissions — report sustained decode tokens/s and slot
occupancy (the workload paged KV exists for; BASELINE.md
serving-capacity row proved the memory win, this measures the LOOP).

Part 2 (mixed 128–4096): the same engine serves a workload whose
prompt lengths span 128–4096 under BOTH prefill policies —
whole-prompt (one padded prefill stalls every in-flight decode for the
full prompt) and chunked (``prefill_chunk`` tokens per step under
``max_num_batched_tokens``, decode-priority). Reports time-to-first-
token and p50/p99 inter-token latency per mode; the acceptance claim
is chunked p99 ITL strictly better than whole-prompt.

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/serving_throughput.py
    # --sustained-only / --mixed-only to run one part

ref: python/paddle/incubate/nn/functional/block_multihead_attention.py
(the reference's serving kernel; no published numbers in-tree),
Yu et al. OSDI'22 (Orca), Agrawal et al. OSDI'24 (Sarathi-Serve).
"""
import argparse
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _pct(xs, p):
    return round(float(np.percentile(xs, p)) * 1000, 2) if xs else None


def sustained(model, config, on_tpu, dev):
    if on_tpu:
        B, MAX_LEN, BS, PAD = 64, 2048, 64, 512
        NUM_BLOCKS = B * (640 // BS) + 16  # ~640 live tokens/seq budget
        N_REQ, GEN = 192, 128
        prompt_lens = (256, 384, 512)
    else:  # mechanics check
        B, MAX_LEN, BS, PAD = 4, 64, 8, 16
        NUM_BLOCKS = 4 * 4 + 2
        N_REQ, GEN = 12, 8
        prompt_lens = (5, 9, 14)

    rng = np.random.RandomState(0)
    eng = ContinuousBatchingEngine(
        model, max_batch=B, max_len=MAX_LEN, block_size=BS,
        num_blocks=NUM_BLOCKS, prompt_pad=PAD,
        decode_chunk=16 if on_tpu else 4)
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)

    # warm both compiled phases outside the timed region; throughput
    # counts only tokens produced inside the timed window
    eng.step()
    warm_toks = eng.decode_tokens
    t0 = time.perf_counter()
    occupancy = []
    while eng._queue or eng.num_active:
        eng.step()
        occupancy.append(eng.num_active)
    dt = time.perf_counter() - t0
    done = eng._completed
    assert len(done) == N_REQ, (len(done), N_REQ)
    toks = eng.decode_tokens - warm_toks
    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec",
        "value": round(toks / dt, 1),
        "unit": "tokens/s",
        "extra": {
            "requests": N_REQ, "gen_per_req": GEN, "max_batch": B,
            "num_blocks": NUM_BLOCKS, "block_size": BS,
            "decode_chunk": eng.decode_chunk,
            "mean_occupancy": round(float(np.mean(occupancy)), 2),
            "steps": eng.steps, "wall_s": round(dt, 2),
            "device": getattr(dev, "device_kind", str(dev)),
        },
    }), flush=True)


def _run_mixed_mode(model, config, *, chunked, B, MAX_LEN, BS, PAD, CHUNK,
                    N_REQ, GEN, prompt_lens):
    kw = dict(max_batch=B, max_len=MAX_LEN, block_size=BS,
              num_blocks=B * (-(-MAX_LEN // BS)) + 4, decode_chunk=1)
    if chunked:
        kw.update(prefill_chunk=CHUNK)  # budget defaults to B + CHUNK
    else:
        kw.update(prompt_pad=PAD)
    eng = ContinuousBatchingEngine(model, **kw)
    # compile both phases outside the measured workload
    eng.add_request("warm", np.ones(1, np.int32), max_new_tokens=2)
    eng.run()

    rng = np.random.RandomState(1)
    t0 = time.perf_counter()
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)
    done = eng.run()
    wall = time.perf_counter() - t0
    reqs = [done[i] for i in range(N_REQ)]
    assert all(r.status == "ok" for r in reqs)
    ttfts = [r.ttft() for r in reqs]
    itls = [d for r in reqs for d in r.inter_token_latencies()]
    toks = sum(len(r.out) for r in reqs)
    return {
        "mode": "chunked" if chunked else "whole_prompt",
        "ttft_ms_p50": _pct(ttfts, 50), "ttft_ms_p99": _pct(ttfts, 99),
        "itl_ms_p50": _pct(itls, 50), "itl_ms_p99": _pct(itls, 99),
        "tokens_per_sec": round(toks / wall, 1),
        "wall_s": round(wall, 2), "steps": eng.steps,
        "max_step_tokens": eng.max_step_tokens,
        "prefill_chunk": CHUNK if chunked else None,
        "max_num_batched_tokens": eng.max_num_batched_tokens,
        "prompt_pad": None if chunked else PAD,
    }


def mixed(model, config, on_tpu, dev):
    """Mixed 128–4096 prompt lengths, whole-prompt vs chunked."""
    if on_tpu:
        B, MAX_LEN, BS, PAD, CHUNK = 16, 4352, 64, 4096, 512
        N_REQ, GEN = 48, 64
    else:
        B, MAX_LEN, BS, PAD, CHUNK = 2, 4160, 64, 4096, 256
        N_REQ, GEN = 6, 12
    prompt_lens = (128, 4096, 512, 2048)

    rows = []
    for chunked in (False, True):
        row = _run_mixed_mode(
            model, config, chunked=chunked, B=B, MAX_LEN=MAX_LEN, BS=BS,
            PAD=PAD, CHUNK=CHUNK, N_REQ=N_REQ, GEN=GEN,
            prompt_lens=prompt_lens)
        rows.append(row)
        print(json.dumps({
            "metric": "serving_mixed_prefill_latency",
            "value": row["itl_ms_p99"], "unit": "ms (p99 ITL)",
            "extra": {**row, "requests": N_REQ, "gen_per_req": GEN,
                      "max_batch": B, "prompt_lens": list(prompt_lens),
                      "device": getattr(dev, "device_kind", str(dev))},
        }), flush=True)
    whole, chunk = rows
    print(json.dumps({
        "metric": "serving_mixed_itl_p99_speedup",
        "value": round(whole["itl_ms_p99"] / chunk["itl_ms_p99"], 2),
        "unit": "x (whole-prompt p99 ITL / chunked p99 ITL)",
        "extra": {
            "chunked_p99_better":
                chunk["itl_ms_p99"] < whole["itl_ms_p99"],
            "ttft_ms_p50_whole": whole["ttft_ms_p50"],
            "ttft_ms_p50_chunked": chunk["ttft_ms_p50"],
        },
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sustained-only", action="store_true")
    ap.add_argument("--mixed-only", action="store_true")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=4608)
    else:
        config = LlamaConfig.tiny(max_position_embeddings=4608)

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()

    if not args.mixed_only:
        sustained(model, config, on_tpu, dev)
    if not args.sustained_only:
        mixed(model, config, on_tpu, dev)


if __name__ == "__main__":
    main()
