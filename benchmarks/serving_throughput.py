"""Continuous-batching serving benchmarks: sustained throughput at
fixed HBM, and the mixed-prompt-length latency comparison chunked
prefill exists for.

Part 1 (sustained): requests with mixed prompt lengths arrive
continuously, finish at different times, and the engine recycles their
blocks into new admissions — report sustained decode tokens/s and slot
occupancy (the workload paged KV exists for; BASELINE.md
serving-capacity row proved the memory win, this measures the LOOP).

Part 2 (mixed 128–4096): the same engine serves a workload whose
prompt lengths span 128–4096 under BOTH prefill policies —
whole-prompt (one padded prefill stalls every in-flight decode for the
full prompt) and chunked (``prefill_chunk`` tokens per step under
``max_num_batched_tokens``, decode-priority). Reports time-to-first-
token and p50/p99 inter-token latency per mode; the acceptance claim
is chunked p99 ITL strictly better than whole-prompt.

Part 4 (``--router``, ISSUE 6): a 2-replica ClusterRouter serving a
shared-prefix mixed-priority workload twice — engine prefix cache ON
vs OFF — with prefix-affinity placement. Reports the measured cluster
prefix-hit-rate, TTFT p50/p99 per mode (chunked prefill inside each
replica, so cached tokens are chunks never scheduled), and per-replica
routed/shed/expired counters. The acceptance claim: hit-rate > 0 and
cache-on TTFT p50 strictly better than cache-off.

Part 5 (``--disagg``, ISSUE 8): decode p99 inter-token latency under
concurrent 4096-token prefills — disaggregated prefill/decode (one
prefill + one decode worker PROCESS over a TCPKVStore with crash-safe
KV-block handoff) vs the unified chunked engine — plus a measured
graceful-degradation phase (prefill worker killed; new prompts must
complete via colocated fallback with zero shed). NB the CPU row
measures MECHANISM (zero loss, fallback, ITL distribution): at tiny-
model scale the base64/TCP transport dominates and a 256-token chunk
costs single-digit ms, so unified chunked wins on CPU; the latency-
independence claim is the TPU column, where a real model's chunk
stalls decode for tens of ms and transfers ride ICI/DMA.

Part 6 (``--overlap``, ISSUE 10): the async host/device pipelining
A/B — the SAME decode-heavy chunked workload served by the sync engine
(blocking D2H fetch + full table/cache_len re-upload every step) and
the ``overlap=True`` engine (device-resident step state, lag-1 copy
ring, dirty-slot uploads). Reports per mode: decode tokens/s, the
decode-phase host-blocked fraction (blocked-in-fetch seconds / step
seconds, steady-state delta), and H2D upload bytes per decode token —
the two quantities the pipeline exists to shrink — plus a BITWISE
output-stream equality check (the token-exactness acceptance gate).
On CPU the dispatch itself is cheap, so the blocked-fraction drop is
the mechanism proof; the tok/s win is the TPU column (dispatch/RTT
dominates serving-size decode there — BASELINE.md decode rows).

Part 7 (``--obs``, ISSUE 12): the observability-overhead A/B — the
SAME sustained decode workload with trace recording ON vs OFF
(``obs.set_enabled``; the metrics registry stays live in both modes —
it backs the engine's own counters). Whole-run A/B cannot resolve a
sub-2% effect (run-to-run drift is ±5-8%), so recording is toggled
per STEP inside one engine run: adjacent steady decode steps sample
identical machine conditions, paired (on − off) diffs are
trimmed-mean'd against the off-step time, reporting tok/s for both
columns and asserting the obs-on overhead stays under 2% — the budget
that lets tracing default to on in production.

Part 3 (``--overload``, ISSUE 4): offered load ≈ 2x measured capacity,
mixed interactive/batch priorities with per-class deadlines, admission
control ON. The overload-control claim: every rejection happens at
admission (``status="shed"``, zero accepted-then-expired), batch
traffic absorbs the shedding, and admitted interactive p99 TTFT stays
inside the interactive deadline. The whole scenario runs under a
``Deadline`` carved from ``BENCH_TOTAL_BUDGET`` (default 600 s) and
always emits its JSON line inside that window.

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/serving_throughput.py
    # --sustained-only / --mixed-only to run one part; --overload for
    # the overload-control scenario alone

ref: python/paddle/incubate/nn/functional/block_multihead_attention.py
(the reference's serving kernel; no published numbers in-tree),
Yu et al. OSDI'22 (Orca), Agrawal et al. OSDI'24 (Sarathi-Serve),
Zhou et al. SOSP'19 (DAGOR overload control).
"""
import argparse
import dataclasses
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.admission import AdmissionConfig
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.utils.retries import Deadline


def _emit(doc: dict) -> None:
    """One metric line through the shared obs ledger writer (ISSUE 15):
    same stdout contract as the old hand-rolled ``print(json.dumps(...))``
    lines, plus the schema'd append to ``BENCH_LEDGER`` when set."""
    from paddle_tpu.obs.regress import bench_record

    bench_record("serving_throughput", doc["metric"], doc.get("value"),
                 doc.get("unit", ""), extra=doc.get("extra"))


def _pct(xs, p):
    return round(float(np.percentile(xs, p)) * 1000, 2) if xs else None


def sustained(model, config, on_tpu, dev):
    if on_tpu:
        B, MAX_LEN, BS, PAD = 64, 2048, 64, 512
        NUM_BLOCKS = B * (640 // BS) + 16  # ~640 live tokens/seq budget
        N_REQ, GEN = 192, 128
        prompt_lens = (256, 384, 512)
    else:  # mechanics check
        B, MAX_LEN, BS, PAD = 4, 64, 8, 16
        NUM_BLOCKS = 4 * 4 + 2
        N_REQ, GEN = 12, 8
        prompt_lens = (5, 9, 14)

    rng = np.random.RandomState(0)
    eng = ContinuousBatchingEngine(
        model, max_batch=B, max_len=MAX_LEN, block_size=BS,
        num_blocks=NUM_BLOCKS, prompt_pad=PAD,
        decode_chunk=16 if on_tpu else 4)
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)

    # warm both compiled phases outside the timed region; throughput
    # counts only tokens produced inside the timed window
    eng.step()
    warm_toks = eng.decode_tokens
    t0 = time.perf_counter()
    occupancy = []
    while eng._queue or eng.num_active:
        eng.step()
        occupancy.append(eng.num_active)
    dt = time.perf_counter() - t0
    done = eng._completed
    assert len(done) == N_REQ, (len(done), N_REQ)
    toks = eng.decode_tokens - warm_toks
    _emit({
        "metric": "serving_decode_tokens_per_sec",
        "value": round(toks / dt, 1),
        "unit": "tokens/s",
        "extra": {
            "requests": N_REQ, "gen_per_req": GEN, "max_batch": B,
            "num_blocks": NUM_BLOCKS, "block_size": BS,
            "decode_chunk": eng.decode_chunk,
            "mean_occupancy": round(float(np.mean(occupancy)), 2),
            "steps": eng.steps, "wall_s": round(dt, 2),
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })


def _run_mixed_mode(model, config, *, chunked, B, MAX_LEN, BS, PAD, CHUNK,
                    N_REQ, GEN, prompt_lens):
    kw = dict(max_batch=B, max_len=MAX_LEN, block_size=BS,
              num_blocks=B * (-(-MAX_LEN // BS)) + 4, decode_chunk=1)
    if chunked:
        kw.update(prefill_chunk=CHUNK)  # budget defaults to B + CHUNK
    else:
        kw.update(prompt_pad=PAD)
    eng = ContinuousBatchingEngine(model, **kw)
    # compile both phases outside the measured workload
    eng.add_request("warm", np.ones(1, np.int32), max_new_tokens=2)
    eng.run()

    rng = np.random.RandomState(1)
    t0 = time.perf_counter()
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)
    done = eng.run()
    wall = time.perf_counter() - t0
    reqs = [done[i] for i in range(N_REQ)]
    assert all(r.status == "ok" for r in reqs)
    ttfts = [r.ttft() for r in reqs]
    itls = [d for r in reqs for d in r.inter_token_latencies()]
    toks = sum(len(r.out) for r in reqs)
    return {
        "mode": "chunked" if chunked else "whole_prompt",
        "ttft_ms_p50": _pct(ttfts, 50), "ttft_ms_p99": _pct(ttfts, 99),
        "itl_ms_p50": _pct(itls, 50), "itl_ms_p99": _pct(itls, 99),
        "tokens_per_sec": round(toks / wall, 1),
        "wall_s": round(wall, 2), "steps": eng.steps,
        "max_step_tokens": eng.max_step_tokens,
        "prefill_chunk": CHUNK if chunked else None,
        "max_num_batched_tokens": eng.max_num_batched_tokens,
        "prompt_pad": None if chunked else PAD,
    }


def mixed(model, config, on_tpu, dev):
    """Mixed 128–4096 prompt lengths, whole-prompt vs chunked."""
    if on_tpu:
        B, MAX_LEN, BS, PAD, CHUNK = 16, 4352, 64, 4096, 512
        N_REQ, GEN = 48, 64
    else:
        B, MAX_LEN, BS, PAD, CHUNK = 2, 4160, 64, 4096, 256
        N_REQ, GEN = 6, 12
    prompt_lens = (128, 4096, 512, 2048)

    rows = []
    for chunked in (False, True):
        row = _run_mixed_mode(
            model, config, chunked=chunked, B=B, MAX_LEN=MAX_LEN, BS=BS,
            PAD=PAD, CHUNK=CHUNK, N_REQ=N_REQ, GEN=GEN,
            prompt_lens=prompt_lens)
        rows.append(row)
        _emit({
            "metric": "serving_mixed_prefill_latency",
            "value": row["itl_ms_p99"], "unit": "ms (p99 ITL)",
            "extra": {**row, "requests": N_REQ, "gen_per_req": GEN,
                      "max_batch": B, "prompt_lens": list(prompt_lens),
                      "device": getattr(dev, "device_kind", str(dev))},
        })
    whole, chunk = rows
    _emit({
        "metric": "serving_mixed_itl_p99_speedup",
        "value": round(whole["itl_ms_p99"] / chunk["itl_ms_p99"], 2),
        "unit": "x (whole-prompt p99 ITL / chunked p99 ITL)",
        "extra": {
            "chunked_p99_better":
                chunk["itl_ms_p99"] < whole["itl_ms_p99"],
            "ttft_ms_p50_whole": whole["ttft_ms_p50"],
            "ttft_ms_p50_chunked": chunk["ttft_ms_p50"],
        },
    })


def overload(model, config, on_tpu, dev):
    """~2x offered load with admission control: shed at the front door,
    keep interactive latency flat, never accept-then-expire."""
    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)  # reserve tail for the JSON emit
    if on_tpu:
        B, MAX_LEN, BS, PAD, GEN = 16, 1024, 64, 512, 48
        prompt_lens, n_req = (128, 256, 384), 192
    else:
        B, MAX_LEN, BS, PAD, GEN = 2, 64, 8, 16, 6
        prompt_lens, n_req = (5, 9, 14), 48

    def make_engine(admission=None):
        return ContinuousBatchingEngine(
            model, max_batch=B, max_len=MAX_LEN, block_size=BS,
            num_blocks=B * (-(-MAX_LEN // BS)) + 2, prompt_pad=PAD,
            admission=admission)

    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, config.vocab_size,
                           (int(prompt_lens[i % len(prompt_lens)]),))
               for i in range(n_req)]

    # calibration: closed-loop saturation measures the service capacity
    # (real tokens/s) and a per-request latency scale; both compiled
    # phases are warmed first so compile time cannot deflate capacity
    calib = make_engine()
    calib.add_request("warm", np.ones(1, np.int32), max_new_tokens=2)
    calib.run()
    n_cal = min(3 * B, n_req)
    t0 = time.perf_counter()
    for i in range(n_cal):
        calib.add_request(i, prompts[i], max_new_tokens=GEN)
    calib.run()
    cal_wall = time.perf_counter() - t0
    capacity_tps = (calib.prefill_tokens + calib.decode_tokens) / cal_wall
    lat_scale = cal_wall / max(n_cal / B, 1)  # ~ one admission wave

    interactive_ddl = max(8 * lat_scale, 1.0)
    batch_ddl = max(24 * lat_scale, 3.0)
    per_req_tokens = float(np.mean([p.size for p in prompts])) + GEN
    arrival_dt = per_req_tokens / (2.0 * capacity_tps)  # 2x offered load

    eng = make_engine(AdmissionConfig(
        max_queue=B, high_watermark=0.75,
        target_delay_s=interactive_ddl / 2))
    # each engine instance compiles its own phase programs: warm them
    # outside the measured window so compile latency cannot expire the
    # first admitted arrivals
    eng.add_request("warm", np.ones(1, np.int32), max_new_tokens=2)
    eng.run()
    del eng._completed["warm"]
    # the warm steps carried compile latency — drop them from the
    # service-rate EWMAs so feasibility reasons from steady-state speed
    eng.ewma_step_s = eng.ewma_step_tokens = None
    submitted = 0
    t0 = time.perf_counter()
    while ((submitted < n_req or eng._queue or eng.num_active)
           and not dl.expired()):
        now = time.perf_counter() - t0
        while submitted < n_req and now >= submitted * arrival_dt:
            i = submitted
            pri = "interactive" if i % 3 == 0 else "batch"
            eng.add_request(
                i, prompts[i], max_new_tokens=GEN, priority=pri,
                deadline=interactive_ddl if pri == "interactive"
                else batch_ddl)
            submitted += 1
        eng.step()
    wall = time.perf_counter() - t0

    done = eng._completed
    ok = [r for r in done.values() if r.status == "ok"]
    ok_inter = [r for r in ok if r.priority == "interactive"]
    ttfts = [r.ttft() for r in ok_inter if r.ttft() is not None]
    goodput = sum(len(r.out) for r in ok) / wall
    shed_total = eng.n_shed["interactive"] + eng.n_shed["batch"]
    _emit({
        "metric": "serving_overload_goodput",
        "value": round(goodput, 1),
        "unit": "ok tokens/s at ~2x offered load",
        "extra": {
            "submitted": submitted, "completed_ok": len(ok),
            "capacity_tokens_per_sec": round(capacity_tps, 1),
            "offered_x": 2.0,
            "shed_rate": round(shed_total / max(submitted, 1), 3),
            "shed_interactive": eng.n_shed["interactive"],
            "shed_batch": eng.n_shed["batch"],
            "accepted_then_expired": eng.n_expired,
            "ttft_ms_p99_interactive": _pct(ttfts, 99),
            "interactive_deadline_ms": round(interactive_ddl * 1000, 1),
            "batch_deadline_ms": round(batch_ddl * 1000, 1),
            "admission_level": eng.admission.level,
            "max_queue": B, "max_batch": B, "gen_per_req": GEN,
            "wall_s": round(wall, 2),
            "budget_s": budget_s,
            "stopped_early": dl.expired(),
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })


def router(model, config, on_tpu, dev):
    """2-replica cluster, shared-prefix traffic, prefix cache on/off."""
    from paddle_tpu.inference.cluster import ClusterRouter, InProcessReplica
    from paddle_tpu.inference.serving import ContinuousBatchingEngine as CBE

    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)  # reserve tail for the JSON emit
    if on_tpu:
        B, MAX_LEN, BS, CHUNK, GEN = 8, 1024, 64, 256, 32
        n_req, plen_prefix, tail_lens = 64, 512, (64, 128)
        n_families = 4
    else:
        B, MAX_LEN, BS, CHUNK, GEN = 2, 128, 8, 16, 6
        n_req, plen_prefix, tail_lens = 24, 32, (5, 9)
        n_families = 2

    rng = np.random.RandomState(3)
    families = [rng.randint(0, config.vocab_size, (plen_prefix,))
                for _ in range(n_families)]
    workload = []
    for i in range(n_req):
        tail = rng.randint(0, config.vocab_size,
                           (int(tail_lens[i % len(tail_lens)]),))
        pri = "interactive" if i % 3 == 0 else "batch"
        workload.append(
            (i, np.concatenate([families[i % n_families], tail]), pri))

    def run_mode(prefix_cache):
        def factory():
            return CBE(model, max_batch=B, max_len=MAX_LEN, block_size=BS,
                       num_blocks=B * (-(-MAX_LEN // BS)) + 8,
                       prefill_chunk=CHUNK, prefix_cache=prefix_cache)

        reps = [InProcessReplica(f"r{i}", factory) for i in range(2)]
        # warm both replicas' compiled phases outside the timed window
        for rep in reps:
            rep.supervisor.submit(f"warm-{rep.replica_id}",
                                  np.ones(1, np.int32), max_new_tokens=2)
            while rep.supervisor.pending:
                rep.supervisor.step()
        rt = ClusterRouter(reps, block_size=BS)
        t0 = time.perf_counter()
        for rid, prompt, pri in workload:
            rt.submit(rid, prompt, max_new_tokens=GEN, priority=pri)
        res = rt.run(deadline=dl.sub(fraction=0.45))
        wall = time.perf_counter() - t0
        assert all(res[rid]["status"] == "ok"
                   for rid, _, _ in workload), "router workload lost work"
        reqs = [r for rep in reps
                for rid, r in rep.supervisor.results.items()
                if not str(rid).startswith("warm")]
        ttfts = [r.ttft() for r in reqs if r.ttft() is not None]
        toks = sum(len(r.out) for r in reqs)
        per_replica = []
        for i, rep in enumerate(reps):
            load = rep.load()
            per_replica.append({
                "replica": rep.replica_id,
                "routed": rt.n_routed[i],
                "shed": load["n_shed_interactive"] + load["n_shed_batch"],
                "expired": load["n_expired"],
                "prefix_hit_tokens": load["prefix"]["hit_tokens"],
            })
        return {
            "prefix_cache": prefix_cache,
            "prefix_hit_rate": round(rt.prefix_hit_rate(), 3),
            "ttft_ms_p50": _pct(ttfts, 50), "ttft_ms_p99": _pct(ttfts, 99),
            "tokens_per_sec": round(toks / wall, 1),
            "wall_s": round(wall, 2),
            "per_replica": per_replica,
        }

    off = run_mode(False)
    on = run_mode(True)
    _emit({
        "metric": "cluster_router_prefix_hit_rate",
        "value": on["prefix_hit_rate"],
        "unit": "cached/prompt tokens over 2 replicas",
        "extra": {
            "cache_on": on, "cache_off": off,
            "ttft_p50_speedup": round(
                off["ttft_ms_p50"] / on["ttft_ms_p50"], 2)
            if on["ttft_ms_p50"] else None,
            "ttft_p50_improved":
                (on["ttft_ms_p50"] or 0) < (off["ttft_ms_p50"] or 0),
            "requests": n_req, "replicas": 2,
            "prefix_len": plen_prefix, "families": n_families,
            "prefill_chunk": CHUNK, "gen_per_req": GEN,
            "budget_s": budget_s,
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })


def disagg(model, config, on_tpu, dev):
    """Part 5 (``--disagg``, ISSUE 8): decode p99 ITL under concurrent
    4096-token prefills — disaggregated prefill/decode (one prefill +
    one decode worker PROCESS over a TCPKVStore, KV-block handoff) vs
    the unified chunked-prefill engine. The ROADMAP item-3 claim:
    disaggregation makes decode inter-token latency independent of
    concurrent long prefills, because the prefill pool runs them in a
    different process/chip entirely. Ends with a measured graceful-
    degradation phase: the prefill worker is KILLED and new prompts
    must complete via the decode worker's colocated fallback (no shed
    storm)."""
    import subprocess
    import sys

    from paddle_tpu.distributed.store import TCPKVStore, TCPStoreServer
    from paddle_tpu.inference.cluster import ProcessReplica
    from paddle_tpu.inference.disagg import DisaggRouter

    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)  # reserve tail for the JSON emit
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if on_tpu:
        B, MAX_LEN, CHUNK, LONG, SHORT = 8, 4352, 512, 4096, 128
        N_SHORT, N_LONG, GEN_S, GEN_L = 12, 4, 48, 16
    else:
        B, MAX_LEN, CHUNK, LONG, SHORT = 2, 4160, 256, 4096, 128
        N_SHORT, N_LONG, GEN_S, GEN_L = 4, 2, 24, 8
    BS = 8  # _disagg_worker.py's engine block size
    blocks = B * (-(-MAX_LEN // BS)) + 8

    rng = np.random.RandomState(4)
    shorts = [(f"s{i}", rng.randint(0, config.vocab_size, (SHORT,)))
              for i in range(N_SHORT)]
    longs = [(f"l{i}", rng.randint(0, config.vocab_size, (LONG,)))
             for i in range(N_LONG)]

    def itls_of(times_by_rid):
        return [b - a for ts in times_by_rid for a, b in zip(ts, ts[1:])]

    # -- unified chunked baseline (one engine time-slices both) --------
    eng = ContinuousBatchingEngine(
        model, max_batch=B, max_len=MAX_LEN, block_size=BS,
        num_blocks=blocks, prefill_chunk=CHUNK)
    eng.add_request("warm", np.ones(1, np.int32), max_new_tokens=2)
    eng.run()
    for rid, p in shorts:
        eng.add_request(rid, p, max_new_tokens=GEN_S)
    for rid, p in longs:
        eng.add_request(rid, p, max_new_tokens=GEN_L)
    t0 = time.perf_counter()
    done = eng.run()
    uni_wall = time.perf_counter() - t0
    assert all(done[rid].status == "ok" for rid, _ in shorts + longs)
    uni_itls = itls_of([done[rid].times for rid, _ in shorts])
    unified = {
        "mode": "unified_chunked",
        "decode_itl_ms_p50": _pct(uni_itls, 50),
        "decode_itl_ms_p99": _pct(uni_itls, 99),
        "wall_s": round(uni_wall, 2),
    }

    # -- disaggregated: 1 prefill + 1 decode worker process ------------
    server = TCPStoreServer("127.0.0.1", 0)
    procs = []
    try:
        reps = []
        for rid, role in (("pf0", "prefill"), ("dx0", "decode")):
            jdir = os.path.join(
                "/tmp", f"disagg_bench_{os.getpid()}", rid)
            env = dict(os.environ)
            env.pop("PADDLE_CHAOS", None)
            env.pop("XLA_FLAGS", None)
            env.update({
                "DISAGG_ROLE": role,
                "DISAGG_STORE_PORT": str(server.port),
                "DISAGG_WORKER_ID": rid,
                "DISAGG_JOURNAL_DIR": jdir,
                "DISAGG_DECODE_IDS": "dx0",
                "DISAGG_BUDGET": str(max(dl.remaining() - 5, 30)),
                "DISAGG_CHUNK": str(CHUNK),
                "DISAGG_MAX_LEN": str(MAX_LEN),
                "DISAGG_BLOCKS": str(blocks),
                "DISAGG_BATCH": str(B),
                "DISAGG_STEPS_PER_PUMP": "8",
                # the workers must run the SAME model/platform as the
                # unified baseline or the comparison is meaningless
                "DISAGG_MODEL_JSON": json.dumps(dataclasses.asdict(config)),
                "DISAGG_BF16": "1" if on_tpu else "",
                "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")
                if not on_tpu else "tpu",
                "PYTHONPATH": repo + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            })
            p = subprocess.Popen(
                [sys.executable,
                 os.path.join(repo, "tests", "_disagg_worker.py")],
                env=env, cwd=repo)
            procs.append(p)
            store = TCPKVStore("127.0.0.1", server.port)
            # journal_dir: a mid-run death recovers via journal-replay
            # ∪ routing table, not the routing table alone
            reps.append(ProcessReplica(store, rid, journal_dir=jdir,
                                       proc=p))
        router = DisaggRouter([reps[0]], [reps[1]])
        store = TCPKVStore("127.0.0.1", server.port)
        while not dl.expired():
            if all(store.get(f"cluster/{r}/hb")
                   for r in ("pf0", "dx0")):
                break
            time.sleep(0.25)
        # warm both workers' compiled phases outside the timed window
        router.submit("warm", np.ones(1, np.int32), max_new_tokens=2)
        router.run(deadline=dl.sub(fraction=0.3))

        for rid, p in shorts:
            router.submit(rid, p, max_new_tokens=GEN_S)
        for rid, p in longs:
            router.submit(rid, p, max_new_tokens=GEN_L)
        t0 = time.perf_counter()
        res = router.run(deadline=dl.sub(fraction=0.8))
        dis_wall = time.perf_counter() - t0
        assert all(res[rid]["status"] == "ok"
                   for rid, _ in shorts + longs), "disagg lost work"
        dis_itls = itls_of([res[rid].get("times", [])
                            for rid, _ in shorts])
        disagg_row = {
            "mode": "disagg_1pf_1dx",
            "decode_itl_ms_p50": _pct(dis_itls, 50),
            "decode_itl_ms_p99": _pct(dis_itls, 99),
            "wall_s": round(dis_wall, 2),
            "fallback": router.n_fallback,
            "handoff_failed": router.n_handoff_failed,
        }

        # -- graceful degradation: kill the prefill pool, keep serving
        procs[0].kill()
        fb_ids = []
        for i in range(3):
            rid = f"fb{i}"
            fb_ids.append(rid)
            router.submit(
                rid, rng.randint(0, config.vocab_size, (SHORT,)),
                max_new_tokens=8)
        fb_res = router.run(deadline=dl.sub(fraction=0.9))
        fb_ok = sum(fb_res.get(r, {}).get("status") == "ok"
                    for r in fb_ids)
        dx_load = reps[1].load() or {}
        degradation = {
            "prefill_killed": True,
            "fallback_submitted": len(fb_ids),
            "fallback_ok": fb_ok,
            "shed": (dx_load.get("n_shed_interactive", 0)
                     + dx_load.get("n_shed_batch", 0)),
            "router_fallback_total": router.n_fallback,
        }
        router.stop(deadline=10.0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()

    _emit({
        "metric": "serving_disagg_decode_itl_p99",
        "value": disagg_row["decode_itl_ms_p99"],
        "unit": "ms (decode p99 ITL under concurrent 4096-tok prefills)",
        "extra": {
            "disagg": disagg_row, "unified": unified,
            "itl_p99_speedup": round(
                unified["decode_itl_ms_p99"]
                / disagg_row["decode_itl_ms_p99"], 2)
            if disagg_row["decode_itl_ms_p99"] else None,
            "degradation": degradation,
            "short_requests": N_SHORT, "long_requests": N_LONG,
            "short_len": SHORT, "long_len": LONG,
            "gen_short": GEN_S, "gen_long": GEN_L,
            "prefill_chunk": CHUNK, "max_batch": B,
            "budget_s": budget_s,
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })


def overlap_ab(model, config, on_tpu, dev):
    """Part 6 (``--overlap``, ISSUE 10): sync vs async-pipelined engine
    over one decode-heavy workload — host-blocked fraction, H2D bytes
    per decode token, tok/s, and the bitwise stream-equality gate."""
    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)  # reserve tail for the JSON emit
    if on_tpu:
        B, MAX_LEN, BS, CHUNK, GEN = 16, 1024, 64, 256, 128
        n_req, plens = 48, (128, 256)
    else:
        B, MAX_LEN, BS, CHUNK, GEN = 4, 128, 8, 16, 24
        n_req, plens = 12, (5, 9, 14)

    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, config.vocab_size,
                           (int(plens[i % len(plens)]),))
               for i in range(n_req)]

    def run_mode(overlap):
        eng = ContinuousBatchingEngine(
            model, max_batch=B, max_len=MAX_LEN, block_size=BS,
            num_blocks=B * (-(-MAX_LEN // BS)) + 4, prefill_chunk=CHUNK,
            overlap=overlap)
        # warm every compiled phase (prefill, decode, update_slot)
        # outside the measured window, then DELTA the transfer/blocked
        # counters so the row is steady-state, not warmup
        eng.add_request("warm", np.ones(1, np.int32), max_new_tokens=4)
        eng.run()
        base = eng.overlap_stats()
        dec0, steps0 = eng.decode_tokens, eng.steps
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            eng.add_request(i, p, max_new_tokens=GEN)
        done = eng.run()
        wall = time.perf_counter() - t0
        st = eng.overlap_stats()
        streams = {i: list(done[i].out) for i in range(n_req) if i in done}
        assert all(done[i].status == "ok" for i in range(n_req))
        dec = eng.decode_tokens - dec0
        busy = st["busy_s"] - base["busy_s"]
        blocked = st["host_blocked_s"] - base["host_blocked_s"]
        row = {
            "mode": "overlap" if overlap else "sync",
            "decode_tokens_per_sec": round(dec / wall, 1),
            "host_blocked_frac": round(blocked / busy, 4) if busy else None,
            "host_blocked_s": round(blocked, 4),
            "h2d_decode_bytes_per_token": round(
                (st["h2d_decode_bytes"] - base["h2d_decode_bytes"])
                / max(dec, 1), 1),
            "dispatches": st["dispatches"] - base["dispatches"],
            "tokens_per_dispatch": round(
                dec / max(st["dispatches"] - base["dispatches"], 1), 2),
            "wall_s": round(wall, 2), "steps": eng.steps - steps0,
        }
        return streams, row

    sync_streams, sync_row = run_mode(False)
    # honor the budget between modes: a blown-out sync half (slow TPU
    # compile, wedged tunnel) still emits its JSON row inside the
    # window instead of dying mid-A/B with no output at all
    ovl_streams, ovl_row = (None, None)
    if not dl.expired():
        ovl_streams, ovl_row = run_mode(True)
    identical = ovl_streams is not None and sync_streams == ovl_streams
    _emit({
        "metric": "serving_overlap_host_blocked_frac",
        "value": ovl_row["host_blocked_frac"] if ovl_row else None,
        "unit": "blocked/busy (overlap mode; sync row beside)",
        "extra": {
            "overlap": ovl_row, "sync": sync_row,
            "identical_streams": identical,
            "stopped_early": ovl_row is None,
            "blocked_frac_drop_x": round(
                sync_row["host_blocked_frac"]
                / ovl_row["host_blocked_frac"], 2)
            if ovl_row and ovl_row["host_blocked_frac"] else None,
            "h2d_bytes_drop_x": round(
                sync_row["h2d_decode_bytes_per_token"]
                / ovl_row["h2d_decode_bytes_per_token"], 2)
            if ovl_row and ovl_row["h2d_decode_bytes_per_token"]
            else None,
            "requests": n_req, "gen_per_req": GEN, "max_batch": B,
            "prefill_chunk": CHUNK, "budget_s": budget_s,
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })
    assert ovl_row is None or identical, \
        "overlap output streams diverged from sync"


def obs_ab(model, config, on_tpu, dev):
    """Trace-recording overhead A/B: ONE sustained decode workload with
    recording toggled every step, comparing median steady-state decode
    step times. Whole-run A/B pairs are useless here: run-to-run noise
    on a shared box is ±5-8% while the effect under test is <2%, but
    adjacent steps of the same run sample identical conditions, so
    per-step alternation pairs the modes tightly. The CPU row uses a
    mid-size model on purpose: the recording cost is a fixed ~10-20us
    per step, so the ratio is only meaningful against a serving-
    representative (millisecond-plus) step, not a toy-model one."""
    from paddle_tpu import obs

    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)
    if on_tpu:
        B, MAX_LEN, BS, PAD = 16, 1024, 64, 256
        N_REQ, GEN = 64, 64
        prompt_lens = (128, 192, 256)
    else:
        B, MAX_LEN, BS, PAD = 4, 64, 8, 16
        N_REQ, GEN = 64, 40
        prompt_lens = (5, 9, 14)
        config = LlamaConfig(
            vocab_size=2048, hidden_size=256, intermediate_size=688,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=256)
        paddle.seed(0)
        model = LlamaForCausalLM(config)
    rng = np.random.RandomState(3)

    eng = ContinuousBatchingEngine(
        model, max_batch=B, max_len=MAX_LEN, block_size=BS,
        num_blocks=B * (-(-MAX_LEN // BS)) + 2, prompt_pad=PAD,
        # the sustained row's decode_chunk: spans are per DISPATCH, so
        # the A/B must amortize them over a dispatch's worth of tokens
        # exactly like the serving configuration does
        decode_chunk=16 if on_tpu else 4)
    # compile both phases outside the timed loop
    eng.add_request("warm", np.ones(5, np.int32), max_new_tokens=2)
    eng.run()
    for i in range(N_REQ):
        plen = int(prompt_lens[i % len(prompt_lens)])
        eng.add_request(i, rng.randint(0, config.vocab_size, (plen,)),
                        max_new_tokens=GEN)

    # paired estimator: adjacent steps alternate modes and sample the
    # same machine conditions, so the per-pair (on - off) difference
    # cancels drift/noise that swamps unpaired medians at this scale
    diffs, offs = [], []
    last = None  # (step index, mode, seconds) of the last steady step
    prev, i = obs.enabled(), 0
    try:
        while (eng._queue or eng.num_active) and not dl.expired():
            on = i % 2 == 0
            obs.set_enabled(on)
            # pair only pure steady-state decode steps: full batch,
            # nothing mid-prefill, no admission possible, and a full
            # decode_chunk emitted per row — a homogeneous population
            # (prefill/admission steps land in both modes anyway)
            steady = (eng.num_active == B
                      and eng.num_prefilling == 0)
            d0 = eng.decode_tokens
            t0 = time.perf_counter()
            eng.step()
            dt = time.perf_counter() - t0
            if steady and eng.decode_tokens - d0 == B * eng.decode_chunk:
                if last is not None and last[0] == i - 1:
                    li, lon, ldt = last
                    diffs.append(dt - ldt if on else ldt - dt)
                    offs.append(ldt if on else dt)
                last = (i, on, dt)
            i += 1
    finally:
        obs.set_enabled(prev)
    assert not eng._queue and not eng.num_active, "budget too small"
    assert len(diffs) >= 40, len(diffs)

    def _trimmed(xs, frac=0.2):  # robust + lower-variance than median
        xs = np.sort(np.asarray(xs))
        k = int(len(xs) * frac)
        return float(np.mean(xs[k:len(xs) - k]))

    off_med = _trimmed(offs)
    on_med = off_med + _trimmed(diffs)
    overhead = _trimmed(diffs) / off_med
    _emit({
        "metric": "serving_obs_overhead_pct",
        "value": round(100 * overhead, 2),
        "unit": "% steady-state decode step time added by recording",
        "extra": {
            "tokens_per_sec_obs_off": round(
                B * eng.decode_chunk / off_med, 1),
            "tokens_per_sec_obs_on": round(
                B * eng.decode_chunk / on_med, 1),
            "decode_chunk": eng.decode_chunk,
            "step_ms_obs_off": round(off_med * 1000, 3),
            "step_ms_obs_on": round(on_med * 1000, 3),
            "paired_steps": len(diffs),
            "requests": N_REQ, "gen_per_req": GEN, "max_batch": B,
            "ring_len": len(obs.ring()),
            "device": getattr(dev, "device_kind", str(dev)),
        },
    })
    assert overhead < 0.02, \
        f"obs-on overhead {100 * overhead:.2f}% exceeds the 2% budget"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sustained-only", action="store_true")
    ap.add_argument("--mixed-only", action="store_true")
    ap.add_argument("--overload", action="store_true",
                    help="run only the 2x-offered-load admission-control "
                         "scenario (under BENCH_TOTAL_BUDGET)")
    ap.add_argument("--router", action="store_true",
                    help="run only the 2-replica cluster-router shared-"
                         "prefix scenario, prefix cache on vs off "
                         "(under BENCH_TOTAL_BUDGET)")
    ap.add_argument("--disagg", action="store_true",
                    help="run only the disaggregated prefill/decode "
                         "scenario: decode p99 ITL under concurrent "
                         "4096-token prefills, 2-process KV handoff vs "
                         "unified chunked, plus the kill-the-prefill-"
                         "pool fallback phase (under BENCH_TOTAL_BUDGET)")
    ap.add_argument("--overlap", action="store_true",
                    help="run only the async host/device pipelining "
                         "A/B: sync vs overlap=True engine over the "
                         "same decode-heavy workload — host-blocked "
                         "fraction, H2D bytes/token, tok/s, bitwise "
                         "stream equality (under BENCH_TOTAL_BUDGET)")
    ap.add_argument("--obs", action="store_true",
                    help="run only the observability-overhead A/B: one "
                         "sustained decode run with trace recording "
                         "toggled per step, paired adjacent-step "
                         "diffs; asserts obs-on costs < 2%% per step")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=4608)
    else:
        config = LlamaConfig.tiny(max_position_embeddings=4608)

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()

    if args.overload:
        overload(model, config, on_tpu, dev)
        return
    if args.router:
        router(model, config, on_tpu, dev)
        return
    if args.disagg:
        disagg(model, config, on_tpu, dev)
        return
    if args.overlap:
        overlap_ab(model, config, on_tpu, dev)
        return
    if args.obs:
        obs_ab(model, config, on_tpu, dev)
        return
    if not args.mixed_only:
        sustained(model, config, on_tpu, dev)
    if not args.sustained_only:
        mixed(model, config, on_tpu, dev)


if __name__ == "__main__":
    main()
