"""Measured pipeline bubble fraction: V-sweep and microbatch sweep
(the BASELINE.md "Pipeline bubble" table). Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/pipeline_bubble_sweep.py

Model: utilization = M*V / T ticks where T = ((M-1)//S)*S*V + (V-1)*S
+ ((M-1)%S) + S; measured wall time per step vs the M*V useful ticks
gives the empirical bubble. (VERDICT #8: attach numbers to the
ZeroBubble refusal.)"""
import time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer, PipelineParallel


class Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc1 = nn.Linear(h, 4 * h)
        self.fc2 = nn.Linear(4 * h, h)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


S, H, MB = 4, 256, 8
rows = []
for V in (1, 2, 4):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"pp_degree": S}
    for M in (4, 8, 16, 32):
        strategy.pipeline_configs = {"accumulate_steps": M}
        hcg = fleet.init(strategy=strategy)
        paddle.seed(0)
        pipe = PipelineLayer(
            layers=[LayerDesc(Block, H) for _ in range(S * V)] + [nn.Linear(H, 8)],
            num_stages=S, num_virtual_pipeline_stages=V,
            loss_fn=lambda lo, y: F.cross_entropy(lo, y),
        )
        pp = PipelineParallel(pipe, hcg, strategy)
        opt = popt.SGD(learning_rate=0.01, parameters=pipe.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(M * MB, H).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 8, (M * MB,)).astype(np.int64))
        pp.train_batch((x, y), opt)  # compile
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            loss = pp.train_batch((x, y), opt)
            float(loss)
            best = min(best, time.perf_counter() - t0)
        T = ((M - 1) // S) * S * V + (V - 1) * S + ((M - 1) % S) + S
        sched_bubble = 1 - (M * V) / T
        rows.append((V, M, T, best * 1e3, best * 1e3 / (M * V), sched_bubble))
        import paddle_tpu.distributed as dist
        dist.destroy_process_group()
        fleet.set_hybrid_communicate_group(None)

print(f"{'V':>2} {'M':>3} {'ticks':>5} {'step_ms':>8} {'ms/chunk':>9} {'sched_bubble':>12}")
for V, M, T, ms, mpc, bub in rows:
    print(f"{V:>2} {M:>3} {T:>5} {ms:>8.1f} {mpc:>9.2f} {bub:>12.3f}")

# empirical bubble: per-useful-chunk time inflation vs the V,M -> inf limit
base = {V: min(r[4] for r in rows if r[0] == V) for V in (1, 2, 4)}
print("\nempirical bubble (1 - best_ms_per_chunk / ms_per_chunk):")
for V, M, T, ms, mpc, bub in rows:
    print(f"V={V} M={M}: measured {1 - base[V]/mpc:.3f} vs schedule model {bub - min(rr[5] for rr in rows if rr[0]==V):.3f} (rel)")
