"""Real convergence run, in-tree: train a small Llama on a procedurally
generated char-level corpus with a KNOWN entropy floor, and evaluate on
HELD-OUT data (ref methodology: test/legacy_test/test_dist_base.py:952
loss-curve checks; this run replaces "overfit one batch" evidence with
train/eval curves against an analytic target).

The source is an order-2 Markov chain over a 32-symbol alphabet with a
fixed seeded Dirichlet(0.3) transition table. Its conditional entropy
H = -sum_s pi(s) sum_c P(c|s) log P(c|s) is computable exactly, so the
eval target is principled: a model that reaches eval cross-entropy
<= 1.05 * H has LEARNED the source (the unigram floor is ~log 32 =
3.47 nats; memorization cannot help on the held-out stream).

Run on the real chip:

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/convergence_lm.py

The CI-short variant lives in tests/test_convergence.py (same
generator, smaller model/steps, looser target).
"""
import json
import time

import numpy as np

VOCAB = 32


def make_chain(seed: int = 0, concentration: float = 0.3, order: int = 2):
    """[VOCAB^order, VOCAB] transition table + its stationary entropy.

    ``order=1`` (32-state table) learns in a couple hundred steps — the
    CI-short test's regime; ``order=2`` (1024 states) needs real data
    efficiency and is the benchmark regime."""
    rng = np.random.RandomState(seed)
    n_states = VOCAB ** order
    trans = rng.dirichlet(np.full(VOCAB, concentration), size=n_states)
    pi = np.full(n_states, 1.0 / n_states)
    for _ in range(400):
        if order == 1:
            nxt = pi @ trans
        else:
            # mass of state (a,b) flows to states (b, :)
            flow = pi[:, None] * trans  # [ab, c]
            nxt = flow.reshape(VOCAB, VOCAB, VOCAB).sum(0).reshape(-1)
        if np.abs(nxt - pi).max() < 1e-12:
            pi = nxt
            break
        pi = nxt
    h = float(-(pi[:, None] * trans * np.log(trans + 1e-30)).sum())
    return trans, h


def sample_stream(trans, n: int, seed: int, order: int = 2) -> np.ndarray:
    """Sample n tokens from the chain (its own RNG — train seed 1,
    eval seed 2 give DISJOINT streams)."""
    rng = np.random.RandomState(seed)
    out = np.empty(n, np.int32)
    a, b = rng.randint(0, VOCAB), rng.randint(0, VOCAB)
    # cumulative tables once; inverse-CDF sampling per step
    cum = np.cumsum(trans, axis=1)
    u = rng.rand(n)
    for i in range(n):
        state = (a * VOCAB + b) if order == 2 else b
        c = int(np.searchsorted(cum[state], u[i]))
        c = min(c, VOCAB - 1)
        out[i] = c
        a, b = b, c
    return out


def batches(stream: np.ndarray, batch: int, seq: int, rng: np.random.RandomState):
    """Random [batch, seq+1] windows -> (inputs, labels)."""
    starts = rng.randint(0, len(stream) - seq - 1, size=batch)
    wins = np.stack([stream[s:s + seq + 1] for s in starts])
    return wins[:, :-1].astype(np.int64), wins[:, 1:].astype(np.int64)


def run(hidden=256, layers=4, heads=4, batch=32, seq=128,
        steps=600, eval_every=100, lr=3e-3, train_tokens=400_000,
        eval_tokens=50_000, target_ratio=1.05, order=2, log=print,
        bf16_sr=False):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.tensor import manipulation as M

    trans, h_floor = make_chain(order=order)
    train = sample_stream(trans, train_tokens, seed=1, order=order)
    heldout = sample_stream(trans, eval_tokens, seed=2, order=order)
    log(f"source entropy floor H = {h_floor:.4f} nats "
        f"(unigram ~{np.log(VOCAB):.4f}); target eval CE <= "
        f"{target_ratio:.2f}*H = {target_ratio * h_floor:.4f}")

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=VOCAB, hidden_size=hidden,
        intermediate_size=int(hidden * 8 / 3) // 64 * 64 or 128,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=max(seq, 256),
    )
    model = LlamaForCausalLM(cfg)
    if bf16_sr:
        # masterless bf16 with stochastic-rounded writes: the full-lr
        # trajectory without fp32 masters (validated against the f32
        # run's eval target)
        model.bfloat16()
    opt = popt.AdamW(learning_rate=lr, parameters=model.parameters(),
                     weight_decay=0.01, use_stochastic_rounding=bf16_sr)

    def step_fn(x, y):
        logits = model(x)
        b, s, v = logits.shape
        loss = F.cross_entropy(
            M.reshape(logits, [b * s, v]), M.reshape(y, [b * s]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    import paddle_tpu.jit as pjit

    train_step = pjit.to_static(step_fn, layers=[model], optimizers=[opt])

    def eval_loss():
        from paddle_tpu.base.tape import no_grad

        rng = np.random.RandomState(99)
        tot, n = 0.0, 0
        with no_grad():
            for _ in range(8):
                x, y = batches(heldout, batch, seq, rng)
                logits = model(paddle.to_tensor(x))
                b, s, v = logits.shape
                ce = F.cross_entropy(
                    M.reshape(logits, [b * s, v]),
                    M.reshape(paddle.to_tensor(y), [b * s]))
                tot += float(ce)
                n += 1
        return tot / n

    rng = np.random.RandomState(7)
    curve = []
    t0 = time.time()
    for step in range(1, steps + 1):
        x, y = batches(train, batch, seq, rng)
        loss = train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        if step % eval_every == 0 or step == steps:
            ev = eval_loss()
            curve.append({"step": step, "train": round(float(loss), 4),
                          "eval": round(ev, 4)})
            log(f"step {step:5d}  train {float(loss):.4f}  eval {ev:.4f}  "
                f"(floor {h_floor:.4f})  {time.time()-t0:.0f}s")
    final_eval = curve[-1]["eval"]
    ok = final_eval <= target_ratio * h_floor
    result = {
        "metric": "eval_ce_over_entropy_floor",
        "value": round(final_eval / h_floor, 4),
        "floor_nats": round(h_floor, 4),
        "final_eval_ce": round(final_eval, 4),
        "target": target_ratio,
        "reached": bool(ok),
        "curve": curve,
    }
    log(json.dumps(result))
    return result


if __name__ == "__main__":
    import os

    # the BASELINE.md row's config (reached 1.027x floor on v5e,
    # 2026-07-31; lr 1e-2 DIVERGES at this width — sits at unigram).
    # CONV_BF16_SR=1 reruns it in masterless-bf16 stochastic-rounding
    # mode (same lr/steps — the point is trajectory parity).
    run(hidden=256, layers=4, heads=4, batch=64, seq=128,
        steps=3000, eval_every=500, lr=3e-3,
        train_tokens=2_000_000, eval_tokens=100_000,
        target_ratio=1.05, order=2,
        bf16_sr=os.environ.get("CONV_BF16_SR") == "1")
