"""Open-loop trace-driven load harness (ISSUE 14).

Closed-loop drivers (submit, wait, submit) let a slow server throttle
its own workload — the measured "latency" is then a function of the
harness, not the scheduler (the coordinated-omission trap the serving
papers this stack follows call out; Sarathi-Serve, DistServe). This
harness is OPEN-LOOP: a seeded schedule fixes every arrival instant
up front, and the driver submits at those instants regardless of what
has completed. Queues build when the server falls behind — that
build-up IS the signal the SLO report grades.

The schedule generator composes four effects, all from one
``random.Random(seed)`` stream (pure python — byte-reproducible
across platforms, unlike numpy's generators across versions):

- **Poisson arrivals** via exponential gaps at the envelope's peak
  rate, thinned against the instantaneous rate (Lewis-Shedler): a
  candidate at ``t`` survives with probability ``rate(t)/rate_max``.
- **Burst episodes** — seeded windows covering ``burst_frac`` of the
  horizon multiply the rate by ``burst_factor`` (the flash-crowd
  shape single-rate Poisson can't produce).
- **Diurnal ramp** — one sinusoid period compressed into the horizon
  (amplitude ``diurnal_amp``), so a short run still sweeps through
  trough and peak load.
- **Heavy-tailed lengths** — lognormal prompt/output token counts
  (clamped), the observed production shape: most requests short, a
  fat tail of long ones.
- **Zipf tenant mix** — tenant ``k`` drawn with weight
  ``1/(k+1)^zipf_s``: one dominant tenant, a long tail of small ones,
  the shape per-tenant attainment accounting exists for.

``generate_schedule`` is pure and deterministic: same spec -> the
same ``schedule_json`` bytes (the acceptance gate). The driver layer
(:class:`EngineFront` / :class:`RouterFront`) adapts any front door —
``ContinuousBatchingEngine``, ``ClusterRouter``, ``DisaggRouter`` —
behind submit/pump/harvest, and the report is
``paddle_tpu.obs.slo.attainment_report`` over the harvested
per-token timestamps, plus a stitched Chrome trace of the run.

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/loadgen.py --smoke

``--smoke`` runs the CPU mechanics check: a seeded schedule over a
2-replica in-process ClusterRouter (tiny Llama, 3 zipf tenants) under
``BENCH_TOTAL_BUDGET``, bench.py's preflight device probe included,
and emits one JSON metric line with the per-tenant attainment table.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import time
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # direct `python benchmarks/loadgen.py` runs
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# schedule generation (pure, deterministic — no framework imports)
# ---------------------------------------------------------------------------

@dataclass
class TraceSpec:
    """The seeded workload shape. ``n_requests`` arrivals over roughly
    ``duration_s`` schedule-seconds (the thinned process runs past the
    horizon if the tail needs it; the driver can compress real time
    with ``time_scale``)."""

    seed: int = 0
    n_requests: int = 48
    duration_s: float = 8.0
    burst_factor: float = 3.0     # rate multiplier inside burst windows
    burst_frac: float = 0.15      # fraction of horizon under bursts
    diurnal_amp: float = 0.5      # sinusoid amplitude, 0 <= amp < 1
    tenants: int = 3
    zipf_s: float = 1.2           # tenant-mix skew
    batch_frac: float = 0.25      # P(priority == "batch")
    prompt_len_median: float = 10.0
    prompt_len_sigma: float = 0.5
    prompt_len_max: int = 24
    output_len_median: float = 6.0
    output_len_sigma: float = 0.5
    output_len_max: int = 12

    def to_dict(self) -> dict:
        return asdict(self)


def _zipf_cdf(n: int, s: float) -> List[float]:
    w = [1.0 / (k + 1) ** s for k in range(n)]
    tot = sum(w)
    acc, out = 0.0, []
    for x in w:
        acc += x / tot
        out.append(acc)
    return out


def _burst_windows(rng: random.Random,
                   spec: TraceSpec) -> List[Tuple[float, float]]:
    """Seeded burst episodes covering ~burst_frac of the horizon."""
    windows: List[Tuple[float, float]] = []
    covered, target = 0.0, spec.burst_frac * spec.duration_s
    while covered < target:
        width = rng.uniform(0.03, 0.10) * spec.duration_s
        start = rng.uniform(0.0, spec.duration_s - width)
        windows.append((start, start + width))
        covered += width
    return windows


def generate_schedule(spec: TraceSpec) -> List[dict]:
    """The open-loop arrival trace: ``n_requests`` entries sorted by
    arrival time ``t`` (seconds from run start), each with tenant,
    priority, lengths, and a per-request prompt seed. Deterministic in
    ``spec`` alone."""
    if not 0.0 <= spec.diurnal_amp < 1.0:
        raise ValueError("diurnal_amp must be in [0, 1)")
    rng = random.Random(spec.seed)
    bursts = _burst_windows(rng, spec)
    cdf = _zipf_cdf(spec.tenants, spec.zipf_s)
    base_rate = spec.n_requests / spec.duration_s
    rate_max = base_rate * (1.0 + spec.diurnal_amp) * spec.burst_factor

    def rate(t: float) -> float:
        r = base_rate * (1.0 + spec.diurnal_amp
                         * math.sin(2.0 * math.pi * t / spec.duration_s))
        if any(a <= (t % spec.duration_s) < b for a, b in bursts):
            r *= spec.burst_factor
        return r

    def _length(median: float, sigma: float, cap: int) -> int:
        v = rng.lognormvariate(math.log(median), sigma)
        return max(1, min(int(cap), int(round(v))))

    out: List[dict] = []
    t = 0.0
    while len(out) < spec.n_requests:
        # Lewis-Shedler thinning: candidates at the envelope's peak
        # rate, kept with probability rate(t)/rate_max
        t += rng.expovariate(rate_max)
        if rng.random() * rate_max > rate(t):
            continue
        u = rng.random()
        tenant = next(k for k, c in enumerate(cdf) if u <= c)
        out.append({
            "i": len(out),
            "req_id": f"lg-{spec.seed}-{len(out):04d}",
            "t": round(t, 6),
            "tenant": f"tenant{tenant}",
            "priority": ("batch" if rng.random() < spec.batch_frac
                         else "interactive"),
            "prompt_len": _length(spec.prompt_len_median,
                                  spec.prompt_len_sigma,
                                  spec.prompt_len_max),
            "max_new_tokens": _length(spec.output_len_median,
                                      spec.output_len_sigma,
                                      spec.output_len_max),
            "prompt_seed": rng.getrandbits(32),
        })
    return out


def schedule_json(spec: TraceSpec, schedule: List[dict]) -> str:
    """Canonical bytes for the schedule — the reproducibility gate:
    equal specs must serialize byte-identically."""
    return json.dumps({"schema": "paddle_tpu.loadgen/1",
                       "spec": spec.to_dict(), "schedule": schedule},
                      sort_keys=True, indent=2)


def feedforward_from_spec(spec: TraceSpec):
    """The trace's rate envelope as an autoscaler feed-forward hint:
    ``f(t_schedule_seconds) -> expected-rate-multiple`` (1.0 = the base
    rate). Re-derives the seeded burst windows exactly as
    :func:`generate_schedule` does (they are the FIRST draw from
    ``random.Random(seed)``), so the hint and the trace agree on when
    the flash crowds land — the feed-forward raises the replica floor
    BEFORE a predictable peak instead of paying one SLO breach per
    ramp. Pure: no clocks; the caller maps wall time onto schedule
    time (``(now - t0) / time_scale``)."""
    rng = random.Random(spec.seed)
    bursts = _burst_windows(rng, spec)

    def multiple(t: float) -> float:
        m = 1.0 + spec.diurnal_amp * math.sin(
            2.0 * math.pi * t / spec.duration_s)
        if any(a <= (t % spec.duration_s) < b for a, b in bursts):
            m *= spec.burst_factor
        return m

    return multiple


# ---------------------------------------------------------------------------
# front-door adapters
# ---------------------------------------------------------------------------

class EngineFront:
    """Drive a bare ``ContinuousBatchingEngine``."""

    def __init__(self, engine):
        self.engine = engine

    def submit(self, item: dict, prompt) -> None:
        self.engine.add_request(
            item["req_id"], prompt, item["max_new_tokens"],
            priority=item["priority"], tenant=item["tenant"])

    def pump(self) -> None:
        self.engine.step()

    def unfinished(self, ids) -> int:
        return sum(1 for r in ids if r not in self.engine._completed)

    def harvest(self, ids) -> List[object]:
        return [self.engine._completed.get(r) for r in ids]


class RouterFront:
    """Drive a ``ClusterRouter`` or ``DisaggRouter`` (both expose
    ``submit(req_id, prompt, n, *, priority, tenant)`` and
    ``step() -> [result dicts]``). Per-token timestamps are harvested
    from the worker supervisors' GenRequests; a request only the
    router-level result dict knows about (e.g. finished on a replica
    that later died) degrades to status-only accounting."""

    def __init__(self, router):
        self.router = router
        self.results: Dict[object, dict] = {}

    def submit(self, item: dict, prompt) -> None:
        self.router.submit(
            item["req_id"], prompt, item["max_new_tokens"],
            priority=item["priority"], tenant=item["tenant"])

    def pump(self) -> None:
        for d in self.router.step():
            self.results[d["req_id"]] = d

    def unfinished(self, ids) -> int:
        return sum(1 for r in ids if r not in self.results)

    def _workers(self):
        for attr in ("replicas", "prefill", "decode"):
            for w in getattr(self.router, attr, ()):
                yield w

    def harvest(self, ids) -> List[object]:
        by_id: Dict[object, object] = {}
        for w in self._workers():
            sup = getattr(w, "supervisor", None)
            if sup is not None:
                by_id.update(sup.results)
        out: List[object] = []
        for rid in ids:
            if rid in by_id:
                out.append(by_id[rid])
            elif rid in self.results:
                d = dict(self.results[rid])
                d.setdefault("times", [])
                out.append(d)
            else:
                out.append(None)
        return out


# ---------------------------------------------------------------------------
# the open-loop driver
# ---------------------------------------------------------------------------

def run_schedule(front, schedule: List[dict], *, vocab_size: int,
                 time_scale: float = 1.0, deadline=None,
                 drain_s: float = 60.0,
                 on_tick=None) -> Tuple[List[object], float]:
    """Submit every schedule entry at its arrival instant (scaled by
    ``time_scale``), pumping the front door between arrivals but NEVER
    gating a submission on completions; then drain. ``on_tick`` (a
    zero-arg callable) runs alongside every pump — the seam a control
    loop (the fleet autoscaler) rides to observe and act while the
    open-loop trace plays. Returns ``(per-request records, wall_s)`` —
    records are GenRequest-shaped (or ``None`` for requests the
    deadline abandoned)."""
    import numpy as np

    ids = [item["req_id"] for item in schedule]
    prompts = {
        item["req_id"]: np.random.RandomState(
            item["prompt_seed"] % (2 ** 32)).randint(
                0, vocab_size, (item["prompt_len"],)).astype(np.int32)
        for item in schedule
    }
    t0 = time.perf_counter()
    for item in schedule:
        due = t0 + item["t"] * time_scale
        while time.perf_counter() < due:
            front.pump()
            if on_tick is not None:
                on_tick()
        front.submit(item, prompts[item["req_id"]])
    t_drain = time.perf_counter()
    while front.unfinished(ids):
        if time.perf_counter() - t_drain > drain_s:
            break
        if deadline is not None and deadline.remaining() <= 0:
            break
        front.pump()
        if on_tick is not None:
            on_tick()
    wall = time.perf_counter() - t0
    return front.harvest(ids), wall


def _lost(rid: str, item: dict) -> dict:
    return {"req_id": rid, "tenant": item["tenant"],
            "priority": item["priority"], "status": "lost",
            "t_submit": 0.0, "times": [], "out": []}


def run_report(front, spec: TraceSpec, slo_spec, *, vocab_size: int,
               time_scale: float = 1.0, deadline=None,
               drain_s: float = 60.0, on_tick=None) -> dict:
    """generate + drive + grade: the one-call harness."""
    from paddle_tpu.obs import slo as _slo

    schedule = generate_schedule(spec)
    recs, wall = run_schedule(front, schedule, vocab_size=vocab_size,
                              time_scale=time_scale, deadline=deadline,
                              drain_s=drain_s, on_tick=on_tick)
    recs = [r if r is not None else _lost(item["req_id"], item)
            for r, item in zip(recs, schedule)]
    return _slo.attainment_report(
        recs, slo_spec, wall,
        extra={"trace_spec": spec.to_dict(), "time_scale": time_scale})


# ---------------------------------------------------------------------------
# the --smoke scenario (CPU mechanics check; the TPU row reuses it)
# ---------------------------------------------------------------------------

def _probe_child() -> None:
    """Preflight child (bench.py's idiom): enumerate devices, print one
    JSON line. A hung tunnel hangs HERE under a ~90 s kill instead of
    inside the load run."""
    import jax

    devs = jax.devices()
    print(json.dumps({"probe": "ok", "n_devices": len(devs),
                      "platform": devs[0].platform}))


def _preflight(deadline) -> Optional[dict]:
    """Two device probes before the run; both hanging means the backend
    is down — return the structured failure instead of burning the
    budget. None = proceed."""
    if os.environ.get("BENCH_PREFLIGHT", "1") != "1":
        return None
    import subprocess

    cap = float(os.environ.get("BENCH_PROBE_TIMEOUT", "90"))
    history = []
    for i in (1, 2):
        timeout_s = min(cap, max(deadline.remaining(), 1.0))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=dict(os.environ, BENCH_PROBE="1"),
                capture_output=True, text=True, timeout=timeout_s)
            ok, hung = proc.returncode == 0 and proc.stdout.strip(), False
        except subprocess.TimeoutExpired:
            ok, hung = False, True
        if ok:
            return None
        history.append({"probe": i, "hung": hung,
                        "timeout_s": round(timeout_s, 2)})
    return {"metric": "loadgen_smoke", "error": "preflight_failed",
            "probes": history}


def burn_columns(table: dict, objective: float = 0.99) -> dict:
    """Burn-rate / remaining-error-budget columns for one attainment
    table row (overall or per-tenant) — computed by the ALERT ENGINE's
    own arithmetic (:func:`paddle_tpu.obs.alerts.burn_rate` /
    :func:`~paddle_tpu.obs.alerts.budget_remaining_frac`), so the
    open-loop harness and the alert rules grade from the same math; a
    parity test pins the two surfaces against each other."""
    from paddle_tpu.obs import alerts as _alerts

    n = int(table["requests"])
    att = table["attainment"]["all"]
    # the table stores met/n rounded to 6 digits; the round-trip back
    # to the integer met count is exact for any realistic n
    bad = 0 if att is None else n - int(round(att * n))
    return {
        "slo_objective": objective,
        "burn_rate": round(_alerts.burn_rate(bad, n, objective), 6),
        "budget_remaining_frac": round(
            _alerts.budget_remaining_frac(bad, n, objective), 6),
    }


def smoke(args) -> dict:
    from paddle_tpu.utils.retries import Deadline

    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)  # reserve tail for the JSON emit
    fail = _preflight(dl)
    if fail is not None:
        return fail

    import paddle_tpu as paddle
    from paddle_tpu import obs as _obs
    from paddle_tpu.inference.cluster import ClusterRouter, InProcessReplica
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.obs.slo import SLOClass, SLOSpec

    paddle.seed(0)
    config = LlamaConfig.tiny()
    model = LlamaForCausalLM(config)

    def factory():
        return ContinuousBatchingEngine(
            model, max_batch=4, max_len=48, block_size=8, num_blocks=28,
            prompt_pad=24)

    replicas = [InProcessReplica(f"rep{i}", factory) for i in range(2)]
    router = ClusterRouter(replicas, block_size=8)
    front = RouterFront(router)

    spec = TraceSpec(seed=args.seed, n_requests=args.requests,
                     duration_s=args.duration, tenants=args.tenants)
    # CPU targets: generous enough that a healthy tiny-model run meets
    # most of them, tight enough that the attainment fractions are not
    # trivially 1.0 for the dominant tenant under its own bursts
    slo_spec = SLOSpec(
        default=SLOClass(ttft_s=8.0, itl_p95_s=2.0, e2e_s=20.0),
        per_priority={"batch": SLOClass(ttft_s=15.0, e2e_s=30.0)},
        per_tenant={"tenant0": SLOClass(ttft_s=6.0)})

    report = run_report(front, spec, slo_spec,
                        vocab_size=config.vocab_size,
                        time_scale=args.time_scale, deadline=dl,
                        drain_s=min(60.0, max(5.0, dl.remaining())))
    if args.report_out:
        from paddle_tpu.obs.slo import report_json
        with open(args.report_out, "w", encoding="utf-8") as fh:
            fh.write(report_json(report))
    if args.trace_out:
        from paddle_tpu.obs.trace import export_chrome_trace, ring, \
            stitch_traces
        export_chrome_trace(stitch_traces([ring().dump()]),
                            path=args.trace_out)
    ov = report["overall"]
    return {
        "metric": "loadgen_goodput_under_slo",
        "value": ov["goodput_tokens_per_s"],
        "unit": "tok/s",
        "extra": {
            "requests": ov["requests"],
            "attainment_all": ov["attainment"]["all"],
            "ttft_p99_s": ov["ttft"]["p99"],
            "itl_p95_p99_s": ov["itl_p95"]["p99"],
            # burn-rate / error-budget columns (ISSUE 15): same
            # arithmetic as the alert engine's burn-rate rules
            **burn_columns(ov),
            "tenants": {
                t: {"requests": row["requests"],
                    "attainment_all": row["attainment"]["all"],
                    "ttft_p50_s": row["ttft"]["p50"],
                    "ttft_p99_s": row["ttft"]["p99"],
                    "goodput_tokens_per_s": row["goodput_tokens_per_s"],
                    **burn_columns(row)}
                for t, row in report["tenants"].items()},
            "fleet_snapshot_series": len(
                _obs.registry().snapshot().get("metrics", {})),
        },
    }


# ---------------------------------------------------------------------------
# the --autoscale scenario (ISSUE 19: closed-loop fleet control)
# ---------------------------------------------------------------------------

def _rec_status(rec) -> str:
    if rec is None:
        return "lost"
    if isinstance(rec, dict):
        return str(rec.get("status", "lost"))
    return str(getattr(rec, "status", "lost"))


def autoscale_smoke(args) -> dict:
    """Closed-loop fleet control under the bursty trace (CPU):

    a 1-replica ClusterRouter grows/shrinks under a FleetAutoscaler
    driven by a short-window TTFT burn-rate rule (internal target
    DELIBERATELY tighter than the graded SLO — the SRE-workbook move:
    page before the user-facing objective is gone) plus the trace's own
    diurnal/burst envelope as feed-forward. Chaos SIGKILLs the first
    drain victim MID-DRAIN; journal-∪-table recovery must lose zero
    accepted requests. Side runs grade WFQ fairness under a hot-tenant
    flood and the host-RAM cache tier with a working set bigger than
    HBM. Emits one bench row per claim, each with explicit polarity."""
    from paddle_tpu.utils.retries import Deadline

    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET", "600"))
    dl = Deadline(budget_s * 0.85)
    fail = _preflight(dl)
    if fail is not None:
        return fail

    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.admission import AdmissionConfig, TenantPolicy
    from paddle_tpu.inference.autoscale import (AutoscalerConfig,
                                                FleetAutoscaler)
    from paddle_tpu.inference.cache_tier import HostTier
    from paddle_tpu.inference.cluster import ClusterRouter, InProcessReplica
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.obs import slo as _slo
    from paddle_tpu.obs.alerts import AlertManager, BurnRateRule
    from paddle_tpu.obs.slo import SLOClass, SLOSpec
    from paddle_tpu.testing import chaos

    ts = max(float(args.time_scale), 1e-9)
    paddle.seed(0)
    config = LlamaConfig.tiny()
    model = LlamaForCausalLM(config)

    def make_engine(**over):
        kw = dict(max_batch=4, max_len=48, block_size=8, num_blocks=28,
                  prompt_pad=24)
        kw.update(over)
        return ContinuousBatchingEngine(model, **kw)

    # Every engine jits its own phase closures, so a replica spawned
    # mid-burst would pay a cold XLA compile on its first prefill.
    # Point the persistent compilation cache at a scratch dir and warm
    # it once: spawned replicas then deserialize instead of compiling.
    jit_cache = tempfile.mkdtemp(prefix="ascale-jit-")
    import jax
    for key, val in (("jax_compilation_cache_dir", jit_cache),
                     ("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(key, val)
        except Exception:  # noqa: BLE001 — older jax: slower spawns only
            pass
    warm = make_engine()
    warm.add_request("warmup", np.arange(9, dtype=np.int32), 2)
    for _ in range(64):
        warm.step()
        if "warmup" in warm._completed:
            break
    del warm

    # --- the autoscaled fleet -------------------------------------------
    journals = tempfile.mkdtemp(prefix="ascale-journal-")

    def replica_factory(rid):
        return InProcessReplica(
            rid, make_engine,
            journal_dir=os.path.join(journals, str(rid)))

    router = ClusterRouter([replica_factory("seed0")], block_size=8)
    front = RouterFront(router)

    # graded SLO (the user-facing objective) vs the controller's rule:
    # an exact-bucket-bound 2.0 s TTFT target — tighter than the graded
    # 8 s so the controller pages BEFORE users hurt, but above a lone
    # CPU prefill's latency so a healthy fleet can actually recover its
    # budget (the scale-down gate). 50% objective, one short window —
    # fires within ~2 s of a backlog forming.
    slo_spec = SLOSpec(
        default=SLOClass(ttft_s=8.0, itl_p95_s=2.0, e2e_s=20.0),
        per_priority={"batch": SLOClass(ttft_s=15.0, e2e_s=30.0)})
    alerts = AlertManager([BurnRateRule(
        "ttft_burn_fast", "serving_ttft_seconds",
        objective=0.5, threshold_s=2.0,
        windows=((2.0 * ts, 1.0),), resolve_for_s=0.25 * ts)],
        emit_trace=False)

    spec = TraceSpec(seed=args.seed, n_requests=args.requests,
                     duration_s=args.duration, tenants=args.tenants,
                     burst_factor=4.0, burst_frac=0.2)
    envelope = feedforward_from_spec(spec)
    t0_cell: List[Optional[float]] = [None]

    def feedforward(now: float) -> float:
        if t0_cell[0] is None:
            return 1.0
        t = (now - t0_cell[0]) / ts
        if t >= spec.duration_s:  # past the horizon: no forecast — the
            return 1.0            # periodic envelope must not re-fire
        return envelope(t)

    cfg = AutoscalerConfig(
        min_replicas=1, max_replicas=3,
        scale_up_cooldown_s=0.75 * ts, scale_down_cooldown_s=1.0 * ts,
        recover_budget_frac=0.2, recover_hold_s=0.75 * ts,
        spawn_backoff_s=0.25, drain_timeout_s=8.0 * ts,
        # headroom 0.3: the 4x burst envelope pre-warms the floor to 2,
        # leaving the third replica to the burn signal — feed-forward
        # alone must not pin the fleet at peak (that IS static peak)
        feedforward_headroom=0.3, evaluate_interval_s=0.2 * ts)
    scaler = FleetAutoscaler(router, replica_factory, config=cfg,
                             alerts=alerts, feedforward=feedforward,
                             clock=time.perf_counter)

    # chaos: the FIRST drain victim is SIGKILLed mid-drain — the
    # zero-lost acceptance row covers the crash-only recovery path
    chaos.install(chaos.ChaosSchedule(seed=args.seed)
                  .at("scale.drain", 1, "drop"))

    peak = [1]
    last_tick = [0.0]

    def on_tick():
        now = time.perf_counter()
        if now - last_tick[0] < 0.05:
            return
        last_tick[0] = now
        rec = scaler.step(now)
        peak[0] = max(peak[0], int(rec["live"]))

    schedule = generate_schedule(spec)
    try:
        t_start = t0_cell[0] = time.perf_counter()
        recs, wall = run_schedule(
            front, schedule, vocab_size=config.vocab_size,
            time_scale=ts, deadline=dl,
            drain_s=min(60.0, max(5.0, dl.remaining())),
            on_tick=on_tick)
        t0_cell[0] = None  # trace over: feed-forward floor back to min
        # let in-progress drains finish so replica-seconds reflects the
        # controller's real footprint, not a snapshot mid-scale-down
        t_cool = time.perf_counter()
        while time.perf_counter() - t_cool < 6.0 and dl.remaining() > 0:
            router.step()
            rec = scaler.step()
            if not rec["draining"] and rec["live"] <= rec["floor"]:
                break
            time.sleep(0.01)
    finally:
        chaos.uninstall()

    wall_total = time.perf_counter() - t_start
    replica_seconds = scaler.replica_seconds
    static_rs = cfg.max_replicas * wall_total
    saving = 1.0 - replica_seconds / static_rs if static_rs > 0 else 0.0

    statuses: Dict[str, int] = {}
    for r in recs:
        st = _rec_status(r)
        statuses[st] = statuses.get(st, 0) + 1
    lost = sum(n for st, n in statuses.items() if st != "ok")
    actions: Dict[str, int] = {}
    for d in scaler.decisions:
        actions[d["action"]] = actions.get(d["action"], 0) + 1

    graded = [r if r is not None else _lost(item["req_id"], item)
              for r, item in zip(recs, schedule)]
    report = _slo.attainment_report(
        graded, slo_spec, wall,
        extra={"trace_spec": spec.to_dict(), "time_scale": ts})
    ov = report["overall"]

    try:
        router.stop()
    except Exception:  # noqa: BLE001 — teardown must not fail the bench
        pass

    # --- WFQ fairness under a hot-tenant flood --------------------------
    adm = AdmissionConfig(max_queue=512, wfq=True,
                          tenants={"*": TenantPolicy(weight=1.0)})
    feng = make_engine(admission=adm)
    fspec = TraceSpec(seed=args.seed + 1, n_requests=32, duration_s=3.0,
                      tenants=3, zipf_s=3.0, burst_factor=1.0,
                      burst_frac=0.0)
    freport = run_report(
        EngineFront(feng), fspec, slo_spec,
        vocab_size=config.vocab_size, time_scale=ts, deadline=dl,
        drain_s=min(60.0, max(5.0, dl.remaining())))
    fair = {t: row["attainment"]["all"]
            for t, row in freport["tenants"].items()
            if row["attainment"]["all"] is not None}
    fair_min = min(fair.values()) if fair else 0.0
    fair_max = max(fair.values()) if fair else 0.0
    fair_band = (fair_min / fair_max) if fair_max else 0.0
    wfq_snap = feng.admission.snapshot() if feng.admission else {}

    # --- host-RAM cache tier: working set > HBM budget ------------------
    def _cache_pass(eng, prompts, tag):
        for j, p in enumerate(prompts):
            rid = f"{tag}-{j}"
            eng.add_request(rid, p, 4)
            for _ in range(512):  # bounded: a stuck request must not
                if rid in eng._completed:  # burn the whole bench budget
                    break
                eng.step()

    rngp = np.random.RandomState(args.seed + 7)
    # 16 prompts x 2 full blocks = 32 cacheable blocks against a
    # 24-block HBM pool: HBM alone cannot hold the working set
    prompts = [rngp.randint(0, config.vocab_size, (17,)).astype(np.int32)
               for _ in range(16)]

    def _replay_hit_rate(tier):
        eng = make_engine(num_blocks=24, prefix_cache=True,
                          cache_tier=tier)
        _cache_pass(eng, prompts, "warm")
        s0 = eng.prefix_stats()
        _cache_pass(eng, prompts, "replay")
        s1 = eng.prefix_stats()
        hits = s1["hit_tokens"] - s0["hit_tokens"]
        pres = s1["prefill_tokens"] - s0["prefill_tokens"]
        rate = hits / (hits + pres) if hits + pres else 0.0
        return rate, s1

    tier = HostTier()
    tier_rate, tier_stats = _replay_hit_rate(tier)
    hbm_rate, _ = _replay_hit_rate(None)

    shutil.rmtree(journals, ignore_errors=True)
    shutil.rmtree(jit_cache, ignore_errors=True)

    rows = [
        {"metric": "autoscale_saving_frac_vs_static_peak",
         "value": round(saving, 6), "unit": "frac", "polarity": "up",
         "extra": {"replica_seconds": round(replica_seconds, 3),
                   "static_replica_seconds": round(static_rs, 3),
                   "wall_s": round(wall_total, 3),
                   "max_replicas": cfg.max_replicas,
                   "peak_live": peak[0],
                   "target_min_saving": 0.30}},
        {"metric": "autoscale_replica_seconds",
         "value": round(replica_seconds, 3), "unit": "replica*s",
         "polarity": "down",
         "extra": {"wall_s": round(wall_total, 3)}},
        {"metric": "autoscale_ttft_p99_s",
         "value": ov["ttft"]["p99"], "unit": "s", "polarity": "down",
         "extra": {"slo_ttft_s": 8.0,
                   "attainment_all": ov["attainment"]["all"],
                   "requests": ov["requests"],
                   **burn_columns(ov)}},
        {"metric": "autoscale_lost_requests",
         "value": lost, "unit": "requests", "polarity": "down",
         "extra": {"statuses": statuses,
                   "chaos_drain_kills": actions.get("drain-died", 0),
                   "router_recoveries": router.n_recoveries,
                   "poisoned": len(router.poisoned_ids)}},
        {"metric": "autoscale_decisions",
         "value": sum(actions.values()), "unit": "decisions",
         "polarity": "down",
         "extra": {"actions": actions,
                   "decisions": scaler.decisions[-64:]}},
        {"metric": "autoscale_tenant_attainment_min",
         "value": round(fair_min, 6), "unit": "frac", "polarity": "up",
         "extra": {"tenants": fair,
                   "fairness_band_min_over_max": round(fair_band, 6),
                   "wfq_vtime": wfq_snap.get("vtime"),
                   "quota_shed": wfq_snap.get("n_quota_shed")}},
        {"metric": "autoscale_cache_tier_hit_rate",
         "value": round(tier_rate, 6), "unit": "frac", "polarity": "up",
         "extra": {"hbm_only_hit_rate": round(hbm_rate, 6),
                   "working_set_blocks": 32, "hbm_blocks": 24,
                   "tier": tier_stats.get("tier")}},
    ]
    return {"rows": rows}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop trace-driven load harness")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU mechanics run: 2-replica in-process "
                         "router, 3 zipf tenants, under "
                         "BENCH_TOTAL_BUDGET")
    ap.add_argument("--autoscale", action="store_true",
                    help="closed-loop fleet-control run: burn-rate-"
                         "driven autoscaler over a 1..3-replica "
                         "router, chaos SIGKILL mid-drain, WFQ "
                         "fairness + host-RAM cache-tier side runs")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=None,
                    help="arrivals (default 24; 60 with --autoscale)")
    ap.add_argument("--duration", type=float, default=None,
                    help="schedule horizon in seconds (default 4; "
                         "10 with --autoscale)")
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="multiply schedule times (e.g. 0.5 = 2x "
                         "faster offered load)")
    ap.add_argument("--schedule-only", action="store_true",
                    help="print the canonical schedule JSON and exit "
                         "(no model, no framework import)")
    ap.add_argument("--report-out", default=None,
                    help="write the full attainment report JSON here")
    ap.add_argument("--trace-out", default=None,
                    help="write the stitched Chrome trace here")
    args = ap.parse_args(argv)
    if args.requests is None:
        args.requests = 60 if args.autoscale else 24
    if args.duration is None:
        args.duration = 10.0 if args.autoscale else 4.0

    if args.schedule_only:
        spec = TraceSpec(seed=args.seed, n_requests=args.requests,
                         duration_s=args.duration, tenants=args.tenants)
        print(schedule_json(spec, generate_schedule(spec)))
        return 0
    if not (args.smoke or args.autoscale):
        ap.error("pick a scenario: --smoke, --autoscale or "
                 "--schedule-only")
    from paddle_tpu.obs.regress import bench_record

    if args.autoscale:
        doc = autoscale_smoke(args)
        for row in doc.get("rows", ()):
            bench_record("loadgen_autoscale", row["metric"],
                         row["value"], row.get("unit", ""),
                         extra=row.get("extra"),
                         polarity=row.get("polarity"))
        if "rows" not in doc:  # preflight failure: keep the old contract
            bench_record("loadgen_autoscale",
                         doc.get("metric", "autoscale"), None, "",
                         **{k: v for k, v in doc.items()
                            if k not in ("metric", "value", "unit")})
        return 0

    doc = smoke(args)
    bench_record(
        "loadgen", doc.get("metric", "loadgen_goodput_under_slo"),
        doc.get("value"), doc.get("unit", ""), extra=doc.get("extra"),
        **{k: v for k, v in doc.items()
           if k not in ("metric", "value", "unit", "extra")})
    return 0


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE") == "1":
        _probe_child()
        raise SystemExit(0)
    raise SystemExit(main())
