"""Flagship step with SGD instead of AdamW: the delta vs the AdamW
step isolates the optimizer's HBM-roofline cost (BASELINE.md "step
decomposition"). Run on the real chip with PYTHONPATH set."""
import time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as popt
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.tensor import manipulation as M

config = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=16,
                     max_position_embeddings=2048)
paddle.seed(0)
model = LlamaForCausalLM(config)
model.bfloat16()
opt = popt.SGD(learning_rate=1e-4, parameters=model.parameters())

def step(ids, labels):
    logits = model(ids)
    b, s, v = logits.shape
    loss = F.cross_entropy(M.reshape(logits, [b*s, v]), M.reshape(labels, [b*s]))
    loss.backward(); opt.step(); opt.clear_grad()
    return loss

compiled = paddle.jit.to_static(step, layers=[model], optimizers=[opt])
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, config.vocab_size, (4, 2048)).astype("int32"))
compiled(ids, ids)
np.asarray(compiled.multi_step(ids, ids, steps=4)._data)
np.asarray(compiled.multi_step(ids, ids, steps=24)._data)
def t(k):
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(compiled.multi_step(ids, ids, steps=k)._data)
        best = min(best, time.perf_counter() - t0)
    return best
ms = (t(24) - t(4)) / 20 * 1e3
print("SGD step ms:", round(ms, 2), "-> AdamW tax ~", round(202.5 - ms, 1), "ms")
