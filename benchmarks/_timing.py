"""Shared K-differencing step timer for the benchmark scripts.

One dispatch runs K scanned train steps; differencing two run lengths
cancels the constant dispatch+fetch round trip (the tunnel RTT):
    per_step = (T(k2) - T(k1)) / (k2 - k1)
Used by bench.py-style scripts; see BASELINE.md "Timing methodology".
"""
import time

import numpy as np


def diff_time_ms(compiled, ids, labels, steps, k1=2, repeats=3):
    """Best-of-N per-step milliseconds for a jit.to_static function
    (already called once so optimizer state exists)."""
    if steps <= k1:
        raise ValueError(
            f"steps ({steps}) must exceed the short run k1 ({k1}) — "
            "the differencing denominator is steps - k1")
    np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
    np.asarray(compiled.multi_step(ids, labels, steps=steps)._data)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(compiled.multi_step(ids, labels, steps=steps)._data)
        t2 = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
        t1 = time.perf_counter() - t0
        best = min(best, (t2 - t1) / (steps - k1))
    return best * 1e3
