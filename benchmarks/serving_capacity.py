"""Serving memory-capacity row (BASELINE.md): B concurrent sequences
with a 2048-token position budget but only 640 live tokens each
(P=512 prompt + 128 generated). The dense cache must pre-allocate
B x 2048 x kvh x d x 2 x layers; the paged pool allocates blocks for
LIVE tokens only (BlockManager), so the same HBM serves ~3x the
sequences. Run on the real chip:

    PYTHONPATH="/root/repo:$PYTHONPATH" python benchmarks/serving_capacity.py

Measured 2026-07-31 (v5e 15.75 GiB, 542M bf16 model = 1.1 GiB):
- B=128: dense needs 16.0 GiB -> RESOURCE_EXHAUSTED; paged pool is
  5.0 GiB -> allocates AND decodes a real model step.
- the eager probe double-buffers pools (no donation), so its own
  ceiling is ~B=176; the compiled serving loop (generate/to_static)
  donates cache buffers and runs 1x-pool, headroom to ~B=300."""
import gc

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import to_tensor
from paddle_tpu.base.tape import no_grad
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

config = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=8, num_attention_heads=16,
                     num_key_value_heads=16, max_position_embeddings=2048)
paddle.seed(0)
model = LlamaForCausalLM(config)
model.bfloat16()
B, LIVE, CAP, BSZ = 128, 640, 2048, 64

bytes_seq_dense = CAP * 16 * 128 * 2 * 2 * 8
blocks_live = -(-LIVE // BSZ)
bytes_seq_paged = blocks_live * BSZ * 16 * 128 * 2 * 2 * 8
print(f"per-seq KV: dense {bytes_seq_dense/2**20:.0f} MiB (budget {CAP}) "
      f"vs paged {bytes_seq_paged/2**20:.0f} MiB ({blocks_live} live blocks)")
print(f"B={B}: dense {B*bytes_seq_dense/2**30:.1f} GiB vs paged "
      f"{B*bytes_seq_paged/2**30:.1f} GiB (+1.1 GiB model, 15.75 GiB HBM)")


def try_paged():
    from paddle_tpu.ops.paged_attention import BlockManager

    mgr = BlockManager(num_blocks=B * blocks_live + 8, block_size=BSZ)
    tables = np.zeros((B, -(-CAP // BSZ)), np.int32)
    for b in range(B):
        row = mgr.allocate(b, LIVE)
        tables[b, :len(row)] = row
    caches = model.init_cache(B, CAP, block_size=BSZ,
                              num_blocks=B * blocks_live + 8, tables=tables)
    tok = to_tensor(
        np.random.RandomState(0).randint(0, 32000, (B, 1)).astype(np.int64))
    with no_grad():
        logits, _ = model.forward_with_cache(
            tok, caches, to_tensor(np.asarray(LIVE - 1, np.int32)))
    return np.asarray(logits._data[:, -1].argmax(-1)).shape


def try_dense():
    caches = model.init_cache(B, CAP)
    return sum(float(k._data[0, 0, 0, 0]) for k, _ in caches)


try:
    shape = try_paged()
    print(f"paged: allocated AND decoded one step (argmax shape {shape})")
except Exception as e:  # noqa: BLE001 — OOM is the expected failure mode
    print(f"paged: FAILED -> {type(e).__name__}: {str(e)[:120]}")
gc.collect()

try:
    try_dense()
    print("dense: allocated OK (no OOM) — raise B for the boundary")
except Exception as e:  # noqa: BLE001
    print(f"dense: FAILED -> {type(e).__name__}: {str(e)[:120]}")
