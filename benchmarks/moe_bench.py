"""MoE dispatch benchmark: tokens/s for an FFN stack — dense vs MoE
(einsum vs sort dispatch), expert-count and capacity-factor sweeps.

Iso-FLOPs comparison: a top-2 MoE applies 2 experts per token, so a
dense FFN of width F and a top-2 MoE with per-expert width F/2 spend
the same matmul FLOPs per token; the measured gap is routing overhead
(gate + dispatch/combine). The dense [N, E, C] mask costs O(N*E*C*H)
bandwidth and grows with E at fixed capacity_factor; the sort path is
O(N*k*H) + an O(N*k log) sort (moe.py MoELayer.dispatch_mode).

Methodology: K train steps (fwd+bwd+SGD) in ONE lax.scan dispatch via
jit.to_static multi_step, run-length differencing to cancel tunnel RTT
(same as bench.py). Prints one JSON line per row.

``--cpu`` runs a TIMED sort-vs-einsum comparison at E=32 on the CPU
backend (sized up from the default off-TPU mechanics check, which is
too small to time): one measured point for the claim that sort
dispatch's O(N·k·H) traffic beats the dense mask's O(N·E·C·H) as E
grows — the TPU sweep stays the real evidence once the tunnel is back.

ref: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(the reference's NCCL all-to-all MoE layer; no published perf numbers).
"""
from __future__ import annotations

import json
import time

import numpy as np


def build_model(mode, h, f_dense, e, cf, layers, dispatch):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.meta_parallel.moe import MoELayer

    paddle.seed(0)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.LayerNorm(h)
            if mode == "dense":
                self.fc1 = nn.Linear(h, f_dense)
                self.fc2 = nn.Linear(f_dense, h)
                self.moe = None
            else:
                # iso-FLOPs: top-2 x (F/2)-wide experts == dense F
                self.moe = MoELayer(
                    d_model=h, d_hidden=f_dense // 2, num_experts=e,
                    top_k=2, capacity_factor=cf, dispatch_mode=dispatch)

        def forward(self, x):
            y = self.norm(x)
            if self.moe is None:
                import paddle_tpu.nn.functional as F

                y = self.fc2(F.gelu(self.fc1(y)))
            else:
                y = self.moe(y)
            return x + y

    class Stack(nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList([Block() for _ in range(layers)])

        def forward(self, x):
            aux = None
            for b in self.blocks:
                x = b(x)
                if b.moe is not None:
                    aux = b.moe.l_aux if aux is None else aux + b.moe.l_aux
            self._aux = aux
            return x

    return Stack()


def measure(model, batch_tokens, h, steps, on_tpu, ks=None):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt

    opt = popt.SGD(learning_rate=1e-3, parameters=model.parameters())

    def step(x):
        out = model(x)
        loss = (out * out).mean()
        if getattr(model, "_aux", None) is not None:
            loss = loss + 0.01 * model._aux
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, layers=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch_tokens, 1, h).astype(np.float32))
    if on_tpu:
        x = x.astype("bfloat16")
        model.bfloat16()

    np.asarray(compiled(x)._data)  # create opt state / carry structure
    k1, k2 = ks if ks is not None else ((4, steps) if on_tpu else (1, 3))
    np.asarray(compiled.multi_step(x, steps=k1)._data)
    np.asarray(compiled.multi_step(x, steps=k2)._data)

    def timed(k):
        best = float("inf")
        for _ in range(3 if on_tpu else 1):
            t0 = time.perf_counter()
            loss = compiled.multi_step(x, steps=k)
            np.asarray(loss._data)
            best = min(best, time.perf_counter() - t0)
        return best

    dt = max(timed(k2) - timed(k1), 1e-9)
    return batch_tokens * (k2 - k1) / dt, 1000 * dt / (k2 - k1)


def cpu_dispatch_point():
    """The measured CPU point for the O(N·k·H)-vs-O(N·E·C·H) dispatch
    claim (round-5 verdict Next #8): einsum vs sort at E=32, sized so
    the timed region is dominated by dispatch work, not noise."""
    import jax

    dev = jax.devices()[0]
    H, F, TOKENS, LAYERS = 128, 512, 4096, 2
    E, CF = 32, 1.25
    results = {}
    for dispatch in ("einsum", "sort"):
        model = build_model("moe", H, F, E, CF, LAYERS, dispatch)
        tps, step_ms = measure(model, TOKENS, H, 0, False, ks=(2, 8))
        results[dispatch] = (tps, step_ms)
        print(json.dumps({
            "row": "moe_cpu_point", "e": E, "cf": CF, "dispatch": dispatch,
            "tokens_per_sec": round(tps, 1), "step_ms": round(step_ms, 3),
            "h": H, "f_dense": F, "tokens": TOKENS, "layers": LAYERS,
            "device": getattr(dev, "device_kind", str(dev)),
        }), flush=True)
    print(json.dumps({
        "row": "moe_cpu_sort_vs_einsum_speedup", "e": E,
        "value": round(results["sort"][0] / results["einsum"][0], 3),
        "unit": "x (sort tokens/s / einsum tokens/s)",
        "sort_faster": results["sort"][0] > results["einsum"][0],
    }), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="timed sort vs einsum dispatch at E=32 on CPU")
    if ap.parse_args().cpu:
        cpu_dispatch_point()
        return

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        H, F, TOKENS, LAYERS, STEPS = 1024, 5632, 8192, 4, 48
    else:  # mechanics check
        H, F, TOKENS, LAYERS, STEPS = 32, 64, 256, 2, 3

    rows = [
        ("dense", dict(e=0, cf=0.0, dispatch="-")),
        ("moe", dict(e=8, cf=1.25, dispatch="einsum")),
        ("moe", dict(e=8, cf=1.25, dispatch="sort")),
        ("moe", dict(e=32, cf=1.25, dispatch="einsum")),
        ("moe", dict(e=32, cf=1.25, dispatch="sort")),
        ("moe", dict(e=8, cf=1.0, dispatch="sort")),
        ("moe", dict(e=8, cf=2.0, dispatch="sort")),
    ]
    for mode, cfg in rows:
        model = build_model(mode, H, F, cfg["e"], cfg["cf"], LAYERS,
                            cfg["dispatch"])
        tps, step_ms = measure(model, TOKENS, H, STEPS, on_tpu)
        print(json.dumps({
            "row": mode, **cfg, "tokens_per_sec": round(tps, 1),
            "step_ms": round(step_ms, 3), "h": H, "f_dense": F,
            "tokens": TOKENS, "layers": LAYERS,
            "device": getattr(dev, "device_kind", str(dev)),
        }), flush=True)


if __name__ == "__main__":
    main()
