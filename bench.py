"""Benchmark: transformer LM train step on the real chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Metric: tokens/sec/chip for a Llama-style decoder LM train step
(forward+backward+AdamW) compiled via paddle_tpu.jit.to_static, bf16
activations path. vs_baseline = achieved MFU / 0.55 (the conventional
A100-class MFU anchor for Llama-2 pretrain stacks, BASELINE.md north
star: MFU parity ⇒ vs_baseline ≥ 1.0).
"""
from __future__ import annotations

import json
import time

import numpy as np

# bf16 peak FLOP/s per chip by TPU generation (device_kind substring)
_PEAK = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,  # trillium
    "v3": 123e12,
    "v2": 45e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.tensor import manipulation as M

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    import os as _os

    variant = _os.environ.get("BENCH_CONFIG", "flagship")
    multi_precision = on_tpu
    if on_tpu:
        if variant == "long":
            # long-context row: attention-heavy regime, Pallas flash
            # kernel path (BASELINE.md S>=8192 row)
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=8192,
            )
            batch, seq = 1, 8192
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 48)), 2
        elif variant == "big":
            # largest-fits row: ~1.5B params; bf16 AdamW moments (fp32
            # masters would need 16 bytes/param and not fit 15.75G)
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2560, intermediate_size=6912,
                num_hidden_layers=18, num_attention_heads=20,
                num_key_value_heads=20, max_position_embeddings=2048,
            )
            batch, seq = int(_os.environ.get("BENCH_BATCH", 1)), 2048
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 24)), 2
            multi_precision = False
        else:
            # flagship: 542M-param Llama at seq 2048 — large enough to be
            # MXU-bound (v5e measures ~0.75 MFU), small enough to fit
            # params + fp32 master/moments in one chip's HBM
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
            )
            batch = int(_os.environ.get("BENCH_BATCH", 4))
            seq = int(_os.environ.get("BENCH_SEQ", 2048))
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 132)), 2
    else:  # CPU fallback so the bench is runnable anywhere
        config = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 64, 3, 1

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()  # bf16 params+activations; AdamW keeps fp32 masters
    # Default: masterless bf16 with stochastic-rounded writes — drops
    # the fp32 masters' 8 bytes/param of HBM traffic while keeping the
    # fp32-master loss trajectory (unbiased rounding carries sub-ulp
    # updates in expectation), so the full fp32-master lr applies.
    # Validated: same overfit loss (0.0011) and the bf16 convergence run
    # reaches the f32 entropy-floor target (tests/test_convergence.py).
    # BENCH_SR=0 restores the fp32-master configuration.
    use_sr = _os.environ.get("BENCH_SR", "1") == "1" and on_tpu
    if use_sr:
        multi_precision = False
    # the PLAIN masterless config (multi_precision=False, no SR: bf16
    # WEIGHTS carry the update, ~3 significant digits) needs a smaller
    # step to stay stable; bf16 moment STORAGE itself is safe at lr 1e-4
    # (update math is f32 and fp32 masters accumulate)
    lr = 1e-4 if multi_precision or use_sr or not on_tpu else 1e-5
    opt = popt.AdamW(
        learning_rate=lr, parameters=model.parameters(),
        multi_precision=multi_precision,
        use_stochastic_rounding=use_sr,
        # bf16 moment STORAGE (f32 update math, f32 masters): the AdamW
        # pass is HBM-bound; halving its moment traffic buys ~5 ms/step
        moment_dtype="bfloat16" if on_tpu else None,
    )

    def step(ids, labels):
        logits = model(ids)
        b, s, v = logits.shape
        loss = F.cross_entropy(
            M.reshape(logits, [b * s, v]), M.reshape(labels, [b * s])
        )
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, layers=[model], optimizers=[opt])

    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, config.vocab_size, (batch, seq))
    ids = paddle.to_tensor(ids_np.astype("int32"))
    labels = paddle.to_tensor(ids_np.astype("int32"))

    for _ in range(warmup):
        loss = compiled(ids, labels)
    np.asarray(loss._data)  # force full execution (block_until_ready may
    # be a no-op through remote-device tunnels)

    # Timing methodology for high-latency device links: run K steps in a
    # SINGLE dispatch (lax.scan inside jit, StaticFunction.multi_step),
    # fetch the result to force execution, and difference two run
    # lengths so the constant dispatch+fetch round-trip cancels:
    #   per_step = (T(K2) - T(K1)) / (K2 - K1)
    k1, k2 = (4, steps) if on_tpu else (1, steps)
    # warm/compile both scan lengths outside the timed region
    np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
    np.asarray(compiled.multi_step(ids, labels, steps=k2)._data)

    def timed(k):
        best = float("inf")
        for _ in range(3 if on_tpu else 1):
            t0 = time.perf_counter()
            loss = compiled.multi_step(ids, labels, steps=k)
            last = float(np.asarray(loss._data)[-1])
            best = min(best, time.perf_counter() - t0)
        return best, last

    t_k1, _ = timed(k1)
    t_k2, final_loss = timed(k2)
    dt = max(t_k2 - t_k1, 1e-9)

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * (k2 - k1) / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / _peak_flops(dev)
    vs_baseline = mfu / 0.55

    print(
        json.dumps(
            {
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 4),
                "extra": {
                    "mfu": round(mfu, 4),
                    "step_ms": round(1000 * dt / (k2 - k1), 2),
                    "loss": round(final_loss, 4),
                    "device": getattr(dev, "device_kind", str(dev)),
                    "params": model.num_params(),
                    "batch": batch,
                    "seq": seq,
                    "dtype": "bfloat16" if on_tpu else "float32",
                },
            }
        )
    )


if __name__ == "__main__":
    main()
