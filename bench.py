"""Benchmark: transformer LM train step on the real chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Metric: tokens/sec/chip for a Llama-style decoder LM train step
(forward+backward+AdamW) compiled via paddle_tpu.jit.to_static, bf16
activations path. vs_baseline = achieved MFU / 0.55 (the conventional
A100-class MFU anchor for Llama-2 pretrain stacks, BASELINE.md north
star: MFU parity ⇒ vs_baseline ≥ 1.0).

Hardening (round-4 verdict Next #1 — BENCH_r04 was lost to one
transient "Unable to initialize backend" with no second chance; round-5
verdict — BENCH_r05 was lost the OPPOSITE way, a single hung attempt's
1800s timeout outliving the driver's capture window): the top-level
invocation is a SUPERVISOR that runs the actual bench in a child
process under a TOTAL wall-clock budget (paddle_tpu.utils.retries
Deadline). Each attempt's timeout is the remaining budget minus a small
reserved slice per future retry — the current attempt gets the lion's
share (a healthy long run is never capped at budget/attempts), while a
hung attempt forfeits only its slice, never the whole window — so N
attempts plus backoff always fit inside BENCH_TOTAL_BUDGET and the
supervisor always emits a JSON line before the driver's capture window
closes. Before any attempt, a PREFLIGHT device probe (a child that only
enumerates devices, killed at ~90 s) answers "is the backend even
there?" cheaply: two consecutive probe hangs mean the tunnel is down
and the supervisor emits its structured failure within ~5 minutes
instead of forfeiting full attempt slices (round-5 Next #1a). Transient
backend failures (init errors, connection loss, hangs) retry with
exponential backoff; real errors (compile/shape/ import bugs) fail
fast; final failure prints a structured diagnostics JSON line instead
of a bare traceback. Knobs (env):
BENCH_PREFLIGHT=1 (0 skips the probe), BENCH_PROBE_TIMEOUT=90 s,
BENCH_TOTAL_BUDGET=3300 s (the whole supervisor run, retries included),
BENCH_ATTEMPTS=5, BENCH_ATTEMPT_TIMEOUT=1800 s (per-attempt cap; the
budget share may shrink it further), BENCH_RETRY_DELAY=5 s (doubles
each retry), BENCH_MAX_HANGS=2 (timeout-kills allowed before declaring
the backend down). BENCH_FORCE_FAIL=transient_until:N|fatal|hang_until:N
is the test hook (tests/test_bench_guard.py); PADDLE_CHAOS schedules
(paddle_tpu/testing/chaos.py, site "bench.attempt") inject the same
faults from a seeded plan.
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))


def _emit_metric(metric: str, value, unit: str, **fields) -> None:
    """One JSON metric line via the shared obs ledger writer
    (``paddle_tpu.obs.regress.bench_record``: same stdout contract,
    plus the schema'd append to BENCH_LEDGER). Falls back to a plain
    print when the package import is itself what's broken — the
    supervisor's structured-failure line must survive that."""
    try:
        from paddle_tpu.obs.regress import bench_record
    except Exception:
        print(json.dumps({"metric": metric, "value": value,
                          "unit": unit, **fields}), flush=True)
        return
    bench_record("bench", metric, value, unit, **fields)


def _load_by_path(name: str, rel: str):
    """Load a stdlib-only framework module WITHOUT importing paddle_tpu
    (the supervisor must stay alive even when the framework/backend
    import is what's broken)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses/typing resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


_retries = _load_by_path("_ptpu_retries", "paddle_tpu/utils/retries.py")
Deadline, RetryPolicy = _retries.Deadline, _retries.RetryPolicy

# re-exported for callers/tests that used bench.py as the taxonomy home
TRANSIENT_PATTERNS = _retries.TRANSIENT_PATTERNS
FATAL_OVERRIDES = _retries.FATAL_OVERRIDES


def _classify(stderr_text: str, rc: int) -> str:
    """timeout/kill and known backend-bring-up errors are transient;
    anything else (tracebacks from compile/shape/import bugs) is fatal
    and retrying would just burn the capture window."""
    if rc < 0 or rc == 124:  # killed (timeout) / shell timeout rc
        return "transient"
    return _retries.classify_text(stderr_text)


def _json_lines_from_end(stdout_text: str):
    """(line, parsed) for each JSON line of ``stdout_text``, last
    first — children emit ONE JSON line but log noise may surround it."""
    for line in reversed(stdout_text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            yield line, json.loads(line)
        except ValueError:
            continue


def _last_metric_line(stdout_text: str):
    for line, obj in _json_lines_from_end(stdout_text):
        if isinstance(obj, dict) and "metric" in obj:
            return line
    return None


def _probe_child() -> None:
    """Preflight child: enumerate devices and print one JSON line —
    nothing else. A hung tunnel hangs HERE, inside a ~90 s kill,
    instead of inside a full-bench attempt's slice."""
    if os.environ.get("PADDLE_CHAOS"):
        chaos = _load_by_path("_ptpu_chaos", "paddle_tpu/testing/chaos.py")
        if not chaos.inject("bench.probe",
                            index=int(os.environ.get(
                                "BENCH_PROBE_ATTEMPT", "1"))):
            sys.exit(0)  # dropped probe: vanishes with no JSON line
    spec = os.environ.get("BENCH_FORCE_FAIL", "")
    if spec.startswith("probe_hang"):
        _, _, n = spec.partition(":")
        if int(os.environ.get("BENCH_PROBE_ATTEMPT", "1")) < int(n or 99):
            time.sleep(10_000)
    import jax

    devs = jax.devices()
    print(json.dumps({
        "probe": "ok", "n_devices": len(devs),
        "platform": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", str(devs[0])),
    }))


def _preflight(deadline, subprocess):
    """Device-enumeration probe before any bench attempt (round-5
    verdict Next #1a: BENCH_r05 burned the whole driver window on one
    hung attempt). Two consecutive ~90 s hangs mean the backend is down
    — the supervisor can then emit its structured failure within ~5
    minutes instead of forfeiting full attempt slices. Returns
    (ok, probe_history, stop_reason)."""
    if os.environ.get("BENCH_PREFLIGHT", "1") != "1":
        return True, [], None
    probe_cap = float(os.environ.get("BENCH_PROBE_TIMEOUT", "90"))
    history = []
    for i in (1, 2):
        timeout_s = min(probe_cap, max(deadline.remaining(), 1.0))
        env = dict(os.environ, BENCH_PROBE="1", BENCH_PROBE_ATTEMPT=str(i))
        hung = False
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=timeout_s,
            )
            rc, err_s = proc.returncode, proc.stderr
            ok = rc == 0 and _last_json_line(proc.stdout) is not None
        except subprocess.TimeoutExpired:
            rc, ok, hung = -9, False, True
            err_s = (f"[bench supervisor] device probe {i}/2 killed after "
                     f"{timeout_s:.0f}s (backend hang)")
        if ok:
            if i > 1:
                sys.stderr.write(
                    f"[bench supervisor] device probe recovered on try {i}\n")
            return True, history, None
        history.append({
            "probe": i, "rc": rc, "hung": hung,
            "timeout_s": round(timeout_s, 2),
            "stderr_tail": err_s[-600:],
        })
        sys.stderr.write(
            f"[bench supervisor] device probe {i}/2 failed "
            f"(rc={rc}{', hang' if hung else ''})\n")
    if all(h["hung"] for h in history):
        return False, history, "preflight device probe hung twice"
    # two fast FAILURES (not hangs): the attempt loop's transient/fatal
    # classifier owns those — it fails fast and keeps the retry budget
    return True, history, None


def _last_json_line(stdout_text: str):
    for _, obj in _json_lines_from_end(stdout_text):
        return obj
    return None


def _supervise() -> int:
    import subprocess

    attempts = int(os.environ.get("BENCH_ATTEMPTS", "5"))
    attempt_cap = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "1800"))
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "3300"))
    policy = RetryPolicy(
        max_attempts=attempts,
        base_delay=float(os.environ.get("BENCH_RETRY_DELAY", "5")),
        multiplier=2.0, max_delay=total_budget,
    )
    deadline = Deadline(total_budget)
    # transient ERRORS fail fast and deserve the full retry budget; a
    # HANG burns its whole share, so a hung tunnel must not consume
    # every attempt's slice (2 hangs ~= the tunnel is down, not flaky)
    max_hangs = int(os.environ.get("BENCH_MAX_HANGS", "2"))
    hangs = 0
    vanished_count = 0  # exit-0-no-metric-line children, bounded like hangs:
    # two in a row means the output pipeline (not the backend) is broken
    history = []
    stop_reason = "attempts exhausted"
    probe_ok, probe_history, probe_stop = _preflight(deadline, subprocess)
    if not probe_ok:
        _emit_metric(
            "llama_train_tokens_per_sec_per_chip", None, "tokens/s",
            vs_baseline=None,
            error={
                "final_classification": "transient",
                "attempts": 0,
                "stop_reason": probe_stop,
                "total_budget_s": total_budget,
                "elapsed_s": round(deadline.elapsed(), 2),
                "history": [],
                "preflight": probe_history,
            })
        return 1
    # each FUTURE attempt keeps a small reserved slice (not an equal
    # share — an equal split would cap a healthy 700s run at
    # budget/attempts and kill captures the old 1800s knob allowed):
    # the current attempt gets everything else, so a hang forfeits a
    # big slice but the reserve guarantees the retries still run
    reserve = min(60.0, total_budget / (2.0 * attempts))
    for attempt in range(1, attempts + 1):
        candidate = deadline.remaining() - (attempts - attempt) * reserve
        timeout_s = min(attempt_cap, candidate)
        if timeout_s < 1.0:
            # a reserve-squeezed slice still gets a 1s floor while real
            # budget remains; below that, stop instead of spawning
            if deadline.remaining() >= 2.0:
                timeout_s = 1.0
            else:
                stop_reason = "budget exhausted"
                sys.stderr.write(
                    f"[bench supervisor] {deadline.remaining():.1f}s of "
                    f"{total_budget:.0f}s budget left — stopping\n")
                break
        env = dict(os.environ, BENCH_CHILD="1", BENCH_ATTEMPT=str(attempt))
        hung = False
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=timeout_s,
            )
            rc, out_s, err_s = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            def _txt(b):
                return b.decode("utf-8", "replace") if isinstance(b, bytes) \
                    else (b or "")
            rc, out_s = -9, _txt(e.stdout)
            hung = True  # OUR timeout kill — not an external SIGKILL
            err_s = _txt(e.stderr) + (
                f"\n[bench supervisor] attempt killed after {timeout_s:.0f}s"
                " (backend hang; forfeited its budget share)")
        vanished = False
        if rc == 0:
            line = _last_metric_line(out_s)
            if line is not None:
                print(line)
                sys.stderr.write(err_s[-2000:])
                return 0
            err_s += ("\n[bench supervisor] child exited 0 without a JSON"
                      " metric line (output lost/child vanished)")
            # exit 0 with no metric line is infrastructure-shaped (lost
            # output, silently reaped child — chaos 'drop' simulates
            # it); a real bench bug raises and exits nonzero
            vanished = True
        classification = "transient" if vanished else _classify(err_s, rc)
        history.append({
            "attempt": attempt,
            "rc": rc,
            "classification": classification,
            "timeout_s": round(timeout_s, 2),
            "stderr_tail": err_s[-600:],
        })
        sys.stderr.write(
            f"[bench supervisor] attempt {attempt}/{attempts} failed "
            f"(rc={rc}, {classification}, "
            f"{deadline.remaining():.0f}s budget left)\n")
        if classification == "fatal":
            stop_reason = "fatal error"
            break
        if vanished:
            vanished_count += 1
            if vanished_count >= 2:
                # a deterministic metric-emission defect would otherwise
                # burn EVERY attempt as a "transient" full bench run
                stop_reason = "children vanish without metric output"
                sys.stderr.write(
                    "[bench supervisor] 2 children exited 0 with no "
                    "metric line — output pipeline broken, stopping\n")
                break
        if hung:
            hangs += 1
            if hangs >= max_hangs:
                stop_reason = "hang budget exhausted"
                sys.stderr.write(
                    f"[bench supervisor] {hangs} attempts hung — "
                    "backend down, stopping\n")
                break
        if attempt < attempts:
            # backoff comes out of the same budget (never sleeps past it)
            deadline.sleep(policy.delay(attempt))
            if deadline.expired():
                stop_reason = "budget exhausted"
                break
    # final failure: one structured diagnostics line, not a traceback
    _emit_metric(
        "llama_train_tokens_per_sec_per_chip", None, "tokens/s",
        vs_baseline=None,
        error={
            "final_classification": history[-1]["classification"]
            if history else "unknown",
            "attempts": len(history),
            "stop_reason": stop_reason,
            "total_budget_s": total_budget,
            "elapsed_s": round(deadline.elapsed(), 2),
            "history": history,
            "preflight": probe_history,
        })
    return 1


def _maybe_force_fail():
    """Test hook: deterministic failures before any JAX import so the
    retry path is provable without a real backend outage. PADDLE_CHAOS
    schedules fire here too (site "bench.attempt") — same seam, seeded
    plans instead of the single-knob BENCH_FORCE_FAIL."""
    if os.environ.get("PADDLE_CHAOS"):
        chaos = _load_by_path("_ptpu_chaos", "paddle_tpu/testing/chaos.py")
        # fresh process per attempt: index by attempt number, not the
        # per-process counter, so multi-attempt schedules line up
        if not chaos.inject("bench.attempt",
                            index=int(os.environ.get("BENCH_ATTEMPT", "1"))):
            # dropped attempt: the child vanishes with no metric line
            # (the supervisor sees exit 0 + missing JSON and reacts)
            sys.exit(0)
    spec = os.environ.get("BENCH_FORCE_FAIL")
    if not spec:
        return
    attempt = int(os.environ.get("BENCH_ATTEMPT", "1"))
    kind, _, n = spec.partition(":")
    if kind == "transient_until" and attempt < int(n):
        raise RuntimeError(
            "Unable to initialize backend 'axon' (forced test failure)")
    if kind == "fatal":
        raise ValueError("forced fatal failure: simulated compile error")
    if kind == "unregistered":
        raise RuntimeError(
            "Unable to initialize backend 'axon': Backend 'axon' is not "
            "in the list of known backends (forced test failure)")
    if kind == "hang_until" and attempt < int(n):
        time.sleep(10_000)

# bf16 peak FLOP/s per chip by TPU generation (device_kind substring)
_PEAK = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,  # trillium
    "v3": 123e12,
    "v2": 45e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12


def main():
    _maybe_force_fail()
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as popt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.tensor import manipulation as M

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    import os as _os

    variant = _os.environ.get("BENCH_CONFIG", "flagship")
    multi_precision = on_tpu
    if on_tpu:
        if variant == "long":
            # long-context row: attention-heavy regime, Pallas flash
            # kernel path (BASELINE.md S>=8192 row)
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=8192,
            )
            batch, seq = 1, 8192
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 48)), 2
        elif variant == "big":
            # largest-fits row: ~1.5B params; bf16 AdamW moments (fp32
            # masters would need 16 bytes/param and not fit 15.75G)
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2560, intermediate_size=6912,
                num_hidden_layers=18, num_attention_heads=20,
                num_key_value_heads=20, max_position_embeddings=2048,
            )
            batch, seq = int(_os.environ.get("BENCH_BATCH", 1)), 2048
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 24)), 2
            multi_precision = False
        else:
            # flagship: 542M-param Llama at seq 2048 — large enough to be
            # MXU-bound (v5e measures ~0.75 MFU), small enough to fit
            # params + fp32 master/moments in one chip's HBM
            config = LlamaConfig(
                vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=8, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=2048,
            )
            batch = int(_os.environ.get("BENCH_BATCH", 4))
            seq = int(_os.environ.get("BENCH_SEQ", 2048))
            steps, warmup = int(_os.environ.get("BENCH_STEPS", 132)), 2
    else:  # CPU fallback so the bench is runnable anywhere
        config = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 64, 3, 1

    paddle.seed(0)
    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()  # bf16 params+activations; AdamW keeps fp32 masters
    # Default: masterless bf16 with stochastic-rounded writes — drops
    # the fp32 masters' 8 bytes/param of HBM traffic while keeping the
    # fp32-master loss trajectory (unbiased rounding carries sub-ulp
    # updates in expectation), so the full fp32-master lr applies.
    # Validated: same overfit loss (0.0011) and the bf16 convergence run
    # reaches the f32 entropy-floor target (tests/test_convergence.py).
    # BENCH_SR=0 restores the fp32-master configuration.
    use_sr = _os.environ.get("BENCH_SR", "1") == "1" and on_tpu
    if use_sr:
        multi_precision = False
    # the PLAIN masterless config (multi_precision=False, no SR: bf16
    # WEIGHTS carry the update, ~3 significant digits) needs a smaller
    # step to stay stable; bf16 moment STORAGE itself is safe at lr 1e-4
    # (update math is f32 and fp32 masters accumulate)
    lr = 1e-4 if multi_precision or use_sr or not on_tpu else 1e-5
    opt = popt.AdamW(
        learning_rate=lr, parameters=model.parameters(),
        multi_precision=multi_precision,
        use_stochastic_rounding=use_sr,
        # bf16 moment STORAGE (f32 update math, f32 masters): the AdamW
        # pass is HBM-bound; halving its moment traffic buys ~5 ms/step
        moment_dtype="bfloat16" if on_tpu else None,
        # BENCH_INTERLEAVE=1: apply each layer's AdamW update at its
        # grad-finalization point inside backward instead of a serial
        # tail — the >0.79-MFU experiment (BASELINE.md decomposition:
        # ~13-19 ms of the step is optimizer HBM traffic after backward)
        interleave_updates=os.environ.get("BENCH_INTERLEAVE", "0") == "1",
    )

    def step(ids, labels):
        logits = model(ids)
        b, s, v = logits.shape
        loss = F.cross_entropy(
            M.reshape(logits, [b * s, v]), M.reshape(labels, [b * s])
        )
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, layers=[model], optimizers=[opt])

    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, config.vocab_size, (batch, seq))
    ids = paddle.to_tensor(ids_np.astype("int32"))
    labels = paddle.to_tensor(ids_np.astype("int32"))

    for _ in range(warmup):
        loss = compiled(ids, labels)
    np.asarray(loss._data)  # force full execution (block_until_ready may
    # be a no-op through remote-device tunnels)

    # Timing methodology for high-latency device links: run K steps in a
    # SINGLE dispatch (lax.scan inside jit, StaticFunction.multi_step),
    # fetch the result to force execution, and difference two run
    # lengths so the constant dispatch+fetch round-trip cancels:
    #   per_step = (T(K2) - T(K1)) / (K2 - K1)
    k1, k2 = (4, steps) if on_tpu else (1, steps)
    # warm/compile both scan lengths outside the timed region
    np.asarray(compiled.multi_step(ids, labels, steps=k1)._data)
    np.asarray(compiled.multi_step(ids, labels, steps=k2)._data)

    def timed(k):
        best = float("inf")
        for _ in range(3 if on_tpu else 1):
            t0 = time.perf_counter()
            loss = compiled.multi_step(ids, labels, steps=k)
            last = float(np.asarray(loss._data)[-1])
            best = min(best, time.perf_counter() - t0)
        return best, last

    t_k1, _ = timed(k1)
    t_k2, final_loss = timed(k2)
    dt = max(t_k2 - t_k1, 1e-9)

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * (k2 - k1) / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / _peak_flops(dev)
    vs_baseline = mfu / 0.55

    _emit_metric(
        "llama_train_tokens_per_sec_per_chip",
        round(tokens_per_sec, 1), "tokens/s",
        vs_baseline=round(vs_baseline, 4),
        extra={
            "mfu": round(mfu, 4),
            "step_ms": round(1000 * dt / (k2 - k1), 2),
            "loss": round(final_loss, 4),
            "device": getattr(dev, "device_kind", str(dev)),
            "params": model.num_params(),
            "batch": batch,
            "seq": seq,
            "dtype": "bfloat16" if on_tpu else "float32",
        },
        config={"batch": batch, "seq": seq})


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE") == "1":
        _probe_child()
    elif os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        sys.exit(_supervise())
