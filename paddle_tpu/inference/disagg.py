"""Disaggregated prefill/decode serving: crash-safe KV-block handoff.

Chunked prefill (PR 2) time-slices ONE engine; the production
end-state (DistServe-style disaggregation, Mooncake's KV-centric
transfer) separates the phases into POOLS: prefill workers run
``role="prefill_only"`` engines and stream each finished prompt's KV
blocks to decode workers, so a 4096-token prefill never shares a
compiled program or a batch with latency-critical decode, and the two
pools scale independently. This module is the handoff layer between
them, engineered as a CRASH-ONLY protocol:

- **Idempotent** — a transfer is keyed by ``req_id``; a resend (nack,
  sender retry, router requeue) of an already-imported request is
  acked and dropped by the receiver, so at-least-once delivery serves
  exactly once.
- **Checksummed** — every store leg rides the KV store's
  length-prefixed CRC32 frame (``put_bytes``/``get_bytes``), the
  commit record carries a whole-payload CRC, and a corrupted or
  incomplete transfer is NACKED (transient) — the sender re-sends
  under its deadline; garbage is never imported.
- **Deadline-bounded** — every leg (export, part puts, commit, ack
  wait, import retry) runs under a :class:`Deadline` carved from the
  request's remaining budget, with :class:`RetryPolicy` backoff on
  transient failures.
- **Pipelining-transparent** (ISSUE 10) — workers inherit the
  engine's async host/device pipeline through their ``engine_factory``
  (``overlap=True``; serving.py module docstring): a decode worker's
  hot loop then recycles sampled tokens on device and harvests through
  the copy ring, an imported request's slot reaches the persistent
  device state via the ordinary dirty-slot upload, and a prefill
  worker's handoff-ready parking simply happens one harvest later —
  ``pending()``/``pump()`` need no changes because a slot stays bound
  until its tokens land.
- **Survivable** — a prefill worker killed MID-handoff leaves parts
  without a commit; the decode side simply never imports the partial
  transfer, and the router's recovery (supervisor journal replay ∪ its
  own routing table, exactly the cluster.py design) requeues the
  request token-exact onto a surviving prefill worker — or, when the
  prefill pool is down, FALLS BACK to submitting the prompt directly
  to a decode worker, whose engine serves it colocated (chunked
  prefill): graceful degradation to the proven unified path instead of
  an outage.

Store layout (any :class:`~paddle_tpu.distributed.store.KVStore`:
``TCPKVStore`` across hosts, ``MemKVStore`` in process) under
``disagg/<decode_id>/``::

    xfer/<sender>-<inc>-<seq>/part/<i>  CRC-framed payload slices
    xfer/<sender>-<inc>-<seq>/commit    JSON {req_id, parts, bytes, crc}
    ack/<sender>-<inc>-<seq>            "ok" | "corrupt:<reason>" (nack)

The commit record is written LAST: its absence is the partial-transfer
discard signal. Acks persist in the store, so a relaunched receiver
never re-imports what a previous incarnation verified. ``<inc>`` is a
random per-sender-INCARNATION nonce: seq counters restart at 0 in a
relaunched prefill worker, and without the nonce its first transfers
would collide with the previous incarnation's persisted acks — the
sender would read a stale "ok" for a payload the receiver never saw.

Chaos sites: ``handoff.export`` (engine export), ``handoff.transfer``
(every part/commit put — a byte site: ``corrupt`` flips a payload bit
the CRC framing must catch, ``kill`` mid-parts manufactures the
partial transfer), ``handoff.import`` (each committed transfer the
receiver verifies — ``drop`` defers it one poll).

Cross-role observability: each handoff leg is recorded in the
collective flight recorder (``handoff_send`` on the prefill side,
``handoff_recv`` on the decode side — rank-divergent by design, like
send/recv), and :class:`DisaggServer` attaches the flight-recorder
contract store, so a decode-worker hang dump names BOTH roles'
schedules, not just its own stacks.
"""
from __future__ import annotations

import json
import struct
import time
import uuid
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import agg as _obs_agg
from ..base.dtype import convert_dtype
from ..distributed.communication import flight_recorder as _fr
from ..distributed.store import CorruptBlobError
from ..ops.paged_attention import BlockImportError
from ..testing import chaos as _chaos
from ..utils import resources as _res
from ..utils.retries import Deadline, RetryPolicy
from .cluster import make_record, remaining_budget, result_record
from .serving import EngineFenced, GenRequest
from .supervisor import Journal, ServingSupervisor

__all__ = [
    "HandoffPayload",
    "KVHandoffSender",
    "KVHandoffReceiver",
    "PrefillWorker",
    "DecodeWorker",
    "DisaggRouter",
    "DisaggServer",
]


def _handoff_transient(exc: BaseException) -> bool:
    """Transient taxonomy for handoff legs: transport errors (OSError
    covers reset/refused/timeout), corrupted/truncated frames
    (ValueError incl. CorruptBlobError — re-read/re-send fixes
    in-transit damage), and a destination pool/slot that is full RIGHT
    NOW (BlockImportError — decode drains continuously)."""
    return isinstance(exc, (OSError, ValueError, BlockImportError))


# np.dtype by name (bfloat16 & friends included) — the framework's one
# resolver, so the wire format can never disagree with the rest of the
# codebase about what a dtype string means
_np_dtype = convert_dtype


@dataclass
class HandoffPayload:
    """One finished prefill, ready to resume decoding elsewhere: the
    request identity/budget, the FIRST generated token (it came from
    the prefill logits — decode starts by writing its KV), and the raw
    KV pages + int8 scale rows from
    :meth:`~paddle_tpu.inference.serving.ContinuousBatchingEngine.export_kv`."""

    req_id: object
    prompt: np.ndarray
    first_token: int
    max_new_tokens: int
    priority: str
    deadline_unix: Optional[float]
    retries: int
    pages: np.ndarray
    scales: Optional[np.ndarray]
    meta: dict
    # carryable trace context ({"trace_id", "span_id"} or None): rides
    # the CRC-framed header so the decode worker's spans parent under
    # the prefill-side trace across the process boundary (ISSUE 12)
    trace: Optional[dict] = None
    # tenant identity rides the handoff too (ISSUE 14): the decode-side
    # SLO histograms must land on the submitting tenant's series
    tenant: str = "default"

    @classmethod
    def from_request(cls, req: GenRequest, pages, scales,
                     meta) -> "HandoffPayload":
        expires = None
        if req.deadline is not None and req.deadline.budget is not None:
            expires = time.time() + req.deadline.remaining()
        return cls(
            req_id=req.req_id, prompt=np.asarray(req.prompt, np.int32),
            first_token=int(req.out[0]),
            max_new_tokens=int(req.max_new_tokens), priority=req.priority,
            deadline_unix=expires, retries=int(req.retries),
            pages=pages, scales=scales, meta=dict(meta),
            trace=_obs.trace_ctx(req), tenant=req.tenant)

    def remaining_budget(self) -> Optional[float]:
        return (None if self.deadline_unix is None
                else self.deadline_unix - time.time())

    def to_request(self) -> GenRequest:
        rem = self.remaining_budget()
        t = self.trace or {}
        return GenRequest(
            self.req_id, np.asarray(self.prompt, np.int32),
            int(self.max_new_tokens),
            deadline=None if rem is None else Deadline(max(rem, 0.0)),
            t_submit=time.perf_counter(), priority=self.priority,
            retries=int(self.retries), tenant=self.tenant,
            trace_id=t.get("trace_id"), span_id=t.get("span_id"))

    # -- wire format ----------------------------------------------------
    # !I header_len | header json | pages bytes | scales bytes
    # (each store leg is additionally CRC-framed by put_bytes; the
    # commit record carries a whole-payload CRC on top)

    def pack(self) -> bytes:
        header = {
            "req_id": self.req_id,
            "prompt": [int(t) for t in self.prompt],
            "first_token": int(self.first_token),
            "max_new_tokens": int(self.max_new_tokens),
            "priority": self.priority,
            "tenant": self.tenant,
            "deadline_unix": self.deadline_unix,
            "retries": int(self.retries),
            "trace": self.trace,
            "meta": self.meta,
            "pages": {"shape": list(self.pages.shape),
                      "dtype": str(self.pages.dtype)},
            "scales": None if self.scales is None else {
                "shape": list(self.scales.shape),
                "dtype": str(self.scales.dtype)},
        }
        hb = json.dumps(header).encode("utf-8")
        out = struct.pack("!I", len(hb)) + hb + self.pages.tobytes()
        if self.scales is not None:
            out += self.scales.tobytes()
        return out

    @classmethod
    def unpack(cls, data: bytes) -> "HandoffPayload":
        if len(data) < 4:
            raise ValueError("handoff payload truncated (no header)")
        (hlen,) = struct.unpack("!I", data[:4])
        if len(data) < 4 + hlen:
            raise ValueError("handoff payload truncated (torn header)")
        header = json.loads(data[4:4 + hlen].decode("utf-8"))
        pdt = _np_dtype(header["pages"]["dtype"])
        pshape = tuple(header["pages"]["shape"])
        psize = int(np.prod(pshape)) * pdt.itemsize
        body = data[4 + hlen:]
        want = psize
        sdt = sshape = None
        if header["scales"] is not None:
            sdt = _np_dtype(header["scales"]["dtype"])
            sshape = tuple(header["scales"]["shape"])
            want += int(np.prod(sshape)) * sdt.itemsize
        if len(body) != want:
            raise ValueError(
                f"handoff payload body is {len(body)} bytes, header "
                f"promises {want}")
        pages = np.frombuffer(body[:psize], dtype=pdt).reshape(pshape)
        scales = None
        if sshape is not None:
            scales = np.frombuffer(body[psize:], dtype=sdt).reshape(sshape)
        return cls(
            req_id=header["req_id"],
            prompt=np.asarray(header["prompt"], np.int32),
            first_token=int(header["first_token"]),
            max_new_tokens=int(header["max_new_tokens"]),
            priority=header.get("priority", "interactive"),
            deadline_unix=header.get("deadline_unix"),
            retries=int(header.get("retries", 0)),
            pages=pages, scales=scales, meta=dict(header["meta"]),
            trace=header.get("trace"),
            tenant=header.get("tenant", "default"))


# ---------------------------------------------------------------------------
# Transfer legs


class KVHandoffSender:
    """Prefill-side transfer leg: split the packed payload into
    CRC-framed parts, write the commit record LAST, wait for the
    receiver's ack — every put retried under the leg's deadline, a
    nack re-sent as a fresh transfer (idempotent by req_id)."""

    def __init__(self, store, channel: str, *, sender_id: str = "pf",
                 part_bytes: int = 1 << 20,
                 n_parts: Optional[int] = None,
                 max_resends: int = 3,
                 retry: Optional[RetryPolicy] = None):
        self.store = store
        self.channel = str(channel)
        self.ns = f"disagg/{self.channel}"
        self.sender_id = str(sender_id)
        self.part_bytes = int(part_bytes)
        self.n_parts = None if n_parts is None else max(1, int(n_parts))
        self.max_resends = int(max_resends)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay=0.05, max_delay=1.0,
            transient=_handoff_transient)
        # per-incarnation nonce: a relaunched sender's seq counter
        # restarts at 0, and acks persist in the store by design — a
        # bare sender_id-seq would alias the previous incarnation's
        # settled transfers and falsely settle a fresh one off a stale
        # "ok" (the receiver having skipped it as already-acked)
        self.incarnation = uuid.uuid4().hex[:8]
        self._seq = 0
        self.n_sent = 0
        self.n_nacked = 0

    def _split(self, data: bytes) -> List[bytes]:
        if self.n_parts is not None:
            per = -(-len(data) // self.n_parts)
        else:
            per = self.part_bytes
        per = max(per, 1)
        return [data[i:i + per] for i in range(0, len(data), per)] or [b""]

    def send_handoff(self, payload: HandoffPayload,
                     deadline=None) -> str:
        """Post one payload (parts first, commit LAST — a crash in
        between leaves a partial transfer the receiver never imports)
        and return its transfer id. NON-BLOCKING past the store puts:
        the ack arrives asynchronously via :meth:`poll_ack` — in-
        process deployments pump sender and receiver from one thread,
        so a synchronous ack wait would deadlock by construction.
        Raises transient transport errors (already retried under
        ``deadline``) for the caller's policy to handle."""
        dl = Deadline.coerce(deadline if deadline is not None else 30.0)
        data = payload.pack()
        _fr.record("handoff_send", shape=tuple(payload.pages.shape),
                   dtype=str(payload.pages.dtype),
                   group=f"disagg/{self.channel}",
                   detail=f"req={payload.req_id}")
        self._seq += 1
        seq = f"{self.sender_id}-{self.incarnation}-{self._seq:08d}"
        with _obs.span("handoff_send",
                       parent=_obs.trace_ctx(payload.trace),
                       tid="handoff", channel=self.channel, seq=seq,
                       req=str(payload.req_id), bytes=len(data)):
            self._put_transfer(seq, payload.req_id, data, dl)
        self.n_sent += 1
        return seq

    def poll_ack(self, seq: str) -> Optional[str]:
        """The receiver's verdict on a posted transfer: "ok",
        "corrupt:..." (nack — resend), or None while unsettled."""
        raw = self.store.get(f"{self.ns}/ack/{seq}")
        if raw and raw != "ok":
            self.n_nacked += 1
        return raw or None

    def _put_transfer(self, seq: str, req_id, data: bytes,
                      dl: Deadline) -> None:
        parts = self._split(data)
        for i, part in enumerate(parts):
            # chaos byte site: corrupt flips a bit (the CRC frame must
            # catch it downstream), drop loses this leg (the commit's
            # whole-payload check turns that into a nack), kill
            # mid-parts leaves the partial transfer
            mutated = _chaos.inject_bytes("handoff.transfer", part)
            if mutated is None:
                continue
            key = f"{self.ns}/xfer/{seq}/part/{i:04d}"
            self.retry.call(self.store.put_bytes, key, mutated,
                            deadline=dl, describe="handoff part put")
        commit = json.dumps({
            "req_id": req_id, "parts": len(parts), "bytes": len(data),
            "crc": zlib.crc32(data) & 0xFFFFFFFF,
        })
        mutated = _chaos.inject_bytes(
            "handoff.transfer", commit.encode("utf-8"))
        if mutated is None:
            raise ConnectionResetError(
                "chaos: handoff commit dropped (lost message)")
        self.retry.call(
            self.store.set, f"{self.ns}/xfer/{seq}/commit",
            mutated.decode("utf-8", errors="surrogateescape"),
            deadline=dl, describe="handoff commit put")


class KVHandoffReceiver:
    """Decode-side transfer leg: poll committed transfers, reassemble
    + verify (per-part CRC frames AND the commit's whole-payload CRC),
    nack damage, ack + return verified payloads — deduped by req_id so
    resends and requeues import at most once. Partial transfers (parts
    without a commit — a sender killed mid-handoff) are simply never
    looked at: discard by construction (and deleted from the store
    after ``orphan_grace`` seconds, since the dead sender can't)."""

    def __init__(self, store, channel: str, *,
                 orphan_grace: float = 60.0):
        self.store = store
        self.channel = str(channel)
        self.ns = f"disagg/{self.channel}"
        self.orphan_grace = float(orphan_grace)
        self._done_seqs: Set[str] = set()
        self._seen_reqs: Set = set()
        self._orphan_first_seen: Dict[str, float] = {}
        self.n_received = 0
        self.n_nacked = 0
        self.n_duplicates = 0
        self.n_orphans_gcd = 0

    def recv_handoff(self) -> List[HandoffPayload]:
        """One poll: every newly committed, verifying transfer comes
        back as a payload (acked); corrupt/incomplete ones are nacked
        for the sender to retry. Non-blocking — callers poll from
        their serve loop."""
        out: List[HandoffPayload] = []
        seqs: Set[str] = set()
        committed: Set[str] = set()
        for key in self.store.keys(self.ns + "/xfer/"):
            seqs.add(key[len(self.ns + "/xfer/"):].split("/", 1)[0])
            if key.endswith("/commit"):
                committed.add(key[len(self.ns + "/xfer/"):
                                  -len("/commit")])
        for seq in sorted(committed):
            if seq in self._done_seqs:
                continue
            if self.store.get(f"{self.ns}/ack/{seq}"):
                # a previous incarnation of this receiver settled it
                # (and died between the ack write and the GC)
                self._done_seqs.add(seq)
                self._gc(seq)
                continue
            if not _chaos.inject("handoff.import"):
                continue  # dropped: deferred to the next poll
            payload = self._settle(seq, f"{self.ns}/xfer/{seq}/commit")
            if payload is not None:
                out.append(payload)
        self._gc_orphans(seqs - committed)
        return out

    def _gc_orphans(self, uncommitted: Set[str]) -> None:
        """Parts with no commit are a sender killed mid-handoff (or a
        commit put that never landed) — the dead sender can't clean
        them up, so the receiver does, after a grace window generous
        vs any live sender's part-upload time. GC'ing a slow-but-ALIVE
        sender is safe (crash-only: its commit then assembles against
        missing parts, nacks, and the sender re-sends fresh); leaking
        is not — each orphan pins MB-scale KV bytes in the store
        forever and inflates every later poll's key scan."""
        now = time.monotonic()
        for seq in list(self._orphan_first_seen):
            if seq not in uncommitted:
                del self._orphan_first_seen[seq]  # committed or gone
        for seq in uncommitted:
            if seq in self._done_seqs:
                continue
            first = self._orphan_first_seen.setdefault(seq, now)
            if now - first > self.orphan_grace:
                self._gc(seq)
                del self._orphan_first_seen[seq]
                self.n_orphans_gcd += 1

    def _settle(self, seq: str, commit_key: str
                ) -> Optional[HandoffPayload]:
        # the span starts BEFORE the trace context is known (it rides
        # the payload being assembled); Span is mutable, so the parent
        # is attached once the header verifies
        sp = _obs.start_span("handoff_recv", tid="handoff",
                             channel=self.channel, seq=seq)
        try:
            payload = self._assemble(seq, commit_key)
        except (CorruptBlobError, ValueError, KeyError) as e:
            # damage is TRANSIENT: nack so the sender's RetryPolicy
            # re-sends instead of the importer swallowing garbage
            # (as a FRESH transfer — this seq's records are garbage)
            self._done_seqs.add(seq)
            self.store.set(f"{self.ns}/ack/{seq}",
                           f"corrupt:{type(e).__name__}: {e}"[:200])
            self.n_nacked += 1
            self._gc(seq)
            _obs.finish_span(sp, verdict="nack",
                             error=type(e).__name__)
            return None
        t = payload.trace or {}
        if t.get("trace_id"):
            sp.trace_id = t["trace_id"]
            sp.parent_id = t.get("span_id")
        self._done_seqs.add(seq)
        self.store.set(f"{self.ns}/ack/{seq}", "ok")
        self._gc(seq)
        if payload.req_id in self._seen_reqs:
            self.n_duplicates += 1  # resend of an imported request
            _obs.finish_span(sp, verdict="duplicate")
            return None
        self._seen_reqs.add(payload.req_id)
        self.n_received += 1
        _fr.record("handoff_recv", shape=tuple(payload.pages.shape),
                   dtype=str(payload.pages.dtype),
                   group=f"disagg/{self.channel}",
                   detail=f"req={payload.req_id}")
        _obs.finish_span(sp, verdict="ok", req=str(payload.req_id),
                         bytes=int(payload.pages.nbytes))
        return payload

    def _assemble(self, seq: str,
                  commit_key: str) -> HandoffPayload:
        raw = self.store.get(commit_key)
        if raw is None:
            raise ValueError(f"commit {seq} vanished")
        commit = json.loads(raw)
        n_parts = int(commit["parts"])
        chunks = []
        for i in range(n_parts):
            part = self.store.get_bytes(f"{self.ns}/xfer/{seq}/part/{i:04d}")
            if part is None:
                raise ValueError(f"transfer {seq}: part {i} missing")
            chunks.append(part)
        data = b"".join(chunks)
        if len(data) != int(commit["bytes"]):
            raise ValueError(
                f"transfer {seq}: reassembled {len(data)} bytes, commit "
                f"promises {commit['bytes']}")
        if (zlib.crc32(data) & 0xFFFFFFFF) != int(commit["crc"]):
            raise CorruptBlobError(
                f"transfer {seq}: whole-payload CRC mismatch")
        return HandoffPayload.unpack(data)

    def _gc(self, seq: str) -> None:
        """Best-effort cleanup of a settled transfer's whole record
        (parts AND commit; the persisted ACK is the durable idempotence
        record a relaunch reads). Without this the receiver's poll
        scans every commit it ever settled, so the decode hot path's
        store round trip would grow with lifetime transfer count."""
        try:
            for key in self.store.keys(f"{self.ns}/xfer/{seq}/"):
                self.store.delete(key)
        except Exception:  # noqa: BLE001 — cleanup must not fail a poll
            pass


# ---------------------------------------------------------------------------
# Workers (pump-driven; DisaggServer wraps one in a process loop)


class PrefillWorker:
    """A supervised ``role="prefill_only"`` engine plus the sender side
    of the handoff: each pump steps the engine, drains finished
    prefills, exports + sends them (each under a deadline carved from
    the request's remaining budget), marks delivered ones
    "transferred" in the journal, and surfaces failures as
    ``handoff_failed`` records the router turns into colocated
    fallback — a transfer that can't make it never strands a request."""

    def __init__(self, worker_id: str, engine_factory, store,
                 decode_ids: Sequence[str], *,
                 journal_dir: Optional[str] = None,
                 handoff_budget: float = 30.0,
                 sender_kwargs: Optional[dict] = None,
                 **supervisor_kwargs):
        self.replica_id = str(worker_id)
        self.journal_dir = journal_dir
        self.handoff_budget = float(handoff_budget)
        self.supervisor = ServingSupervisor(
            engine_factory, journal_dir=journal_dir, **supervisor_kwargs)
        if self.supervisor.engine.role != "prefill_only":
            raise ValueError(
                "PrefillWorker needs a role='prefill_only' engine "
                f"factory (got role={self.supervisor.engine.role!r})")
        kw = dict(sender_kwargs or {})
        kw.setdefault("sender_id", self.replica_id)
        self.senders = [KVHandoffSender(store, did, **kw)
                        for did in decode_ids]
        self._rr = 0
        # ack-timeout circuit breaker: a decode channel whose transfer
        # just timed out is skipped for one handoff_budget window, so
        # a dead decode worker doesn't keep eating every N-th handoff's
        # full 30s ack wait (any verdict — ok OR nack — re-closes it)
        self._down_until: Dict[str, float] = {}
        self._dead = False
        self._published: Set = set()
        self._markers: List[dict] = []  # transferred / handoff_failed
        # posted transfers awaiting the receiver's verdict:
        # req_id -> {req, payload, sender, seq, dl, resends}
        self._outstanding: Dict[object, dict] = {}
        self._graft_ledger = _res.current()
        self.export_retry = RetryPolicy(
            max_attempts=3, base_delay=0.02, max_delay=0.5,
            transient=_handoff_transient)

    # -- router-handle surface ------------------------------------------
    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        self._dead = True

    def stop(self, deadline: Optional[Deadline] = None) -> None:
        del deadline
        self._dead = True

    def submit(self, rec: dict) -> None:
        self.supervisor.submit(
            rec["req_id"], np.asarray(rec["prompt"], np.int32),
            int(rec["max_new_tokens"]),
            deadline=remaining_budget(rec),
            priority=rec.get("priority", "interactive"),
            retries=int(rec.get("retries", 0)),
            trace=rec.get("trace"),
            tenant=rec.get("tenant", "default"))

    def pending(self) -> bool:
        return (not self._dead) and (
            self.supervisor.pending
            or bool(self.supervisor.engine._handoff_ready)
            or bool(self._outstanding))

    def active(self) -> bool:
        """Engine-side work RIGHT NOW — unlike :meth:`pending`, an
        outstanding transfer merely awaiting its ack doesn't count, so
        a serve loop can sleep between ack polls instead of spinning
        on the store."""
        return (not self._dead) and (
            self.supervisor.pending
            or bool(self.supervisor.engine._handoff_ready))

    def load(self) -> Optional[dict]:
        eng = self.supervisor.engine
        d = eng.load().as_dict()
        d["role"] = "prefill"
        d["handed_off"] = eng.n_handed_off
        return d

    def poll_completed(self) -> List[dict]:
        """Final results settled AT the prefill side (eos-on-first-
        token, shed, expired) plus the routing markers: "transferred"
        (the decode side owns it now — carries ``target``) and
        "handoff_failed" (the router should fall back)."""
        out, self._markers = list(self._markers), []
        for rid, r in list(self.supervisor.results.items()):
            if rid in self._published or r.status == "transferred":
                continue
            self._published.add(rid)
            out.append(result_record(rid, r.status, r.out,
                               shed_reason=r.shed_reason,
                               times=list(r.times)))
        return out

    # -- the pump --------------------------------------------------------
    def pump(self, deadline: Optional[Deadline] = None) -> None:
        del deadline  # per-handoff budgets bound every leg below
        if self._dead:
            return
        if self.supervisor.pending:
            self.supervisor.step()
        eng = self.supervisor.engine
        for req in eng.drain_prefilled():
            self._begin_handoff(eng, req)
        self._check_acks()

    def _pick_sender(self) -> KVHandoffSender:
        """Round-robin over decode channels, skipping any inside its
        ack-timeout cooldown; when EVERY channel is cooling down, take
        the round-robin pick anyway (a wrong guess costs one budget,
        stranding the handoff costs the request)."""
        now = time.monotonic()
        for _ in range(len(self.senders)):
            s = self.senders[self._rr % len(self.senders)]
            self._rr += 1
            if self._down_until.get(s.channel, 0.0) <= now:
                return s
        s = self.senders[self._rr % len(self.senders)]
        self._rr += 1
        return s

    def _fail(self, req: GenRequest, why: str) -> None:
        self._markers.append(result_record(
            req.req_id, "handoff_failed", reason=why[:200]))

    def _begin_handoff(self, eng, req: GenRequest) -> None:
        """Export + post one finished prefill. The export is gathered
        to HOST arrays and the blocks released immediately — resends
        reuse the packed payload, so a supervisor engine rebuild
        between post and ack cannot strand the transfer."""
        if req.expired():
            eng.release_handoff(req.req_id)
            req.status = "expired"
            self.supervisor._finish(req)
            return
        budget = self.handoff_budget
        if req.deadline is not None and req.deadline.budget is not None:
            budget = min(budget, req.deadline.remaining())
        dl = Deadline(budget)
        sender = self._pick_sender()
        try:
            pages, scales, meta = self.export_retry.call(
                eng.export_kv, req.req_id, kv_len=int(req.prompt.size),
                deadline=dl, describe="KV export")
        except (OSError, ValueError, TimeoutError,
                BlockImportError) as e:
            eng.release_handoff(req.req_id)
            self._fail(req, f"export: {type(e).__name__}: {e}")
            return
        payload = HandoffPayload.from_request(req, pages, scales, meta)
        eng.release_handoff(req.req_id)
        try:
            seq = sender.send_handoff(payload, deadline=dl)
        except (OSError, ValueError, TimeoutError) as e:
            self._fail(req, f"transfer: {type(e).__name__}: {e}")
            return
        self._outstanding[req.req_id] = {
            "req": req, "payload": payload, "sender": sender,
            "seq": seq, "dl": dl, "resends": 0}
        if self._graft_ledger is not None:
            self._graft_ledger.acquire("handoff.part", req.req_id)

    def _drop_outstanding(self, rid) -> None:
        """Every settle path funnels through here so the leak ledger's
        ``handoff.part`` entry can never outlive the tracking dict."""
        del self._outstanding[rid]
        if self._graft_ledger is not None:
            self._graft_ledger.release("handoff.part", rid)

    def _check_acks(self) -> None:
        """Settle posted transfers: ok → journal "transferred" + tell
        the router; nack → resend (idempotent by req_id) while budget
        remains; deadline → handoff_failed (the router falls back to
        colocated serving)."""
        for rid, st in list(self._outstanding.items()):
            channel = st["sender"].channel
            try:
                verdict = st["sender"].poll_ack(st["seq"])
            except (OSError, ValueError) as e:
                verdict = None
                if st["dl"].expired():
                    self._drop_outstanding(rid)
                    self._down_until[channel] = (
                        time.monotonic() + self.handoff_budget)
                    self._fail(st["req"],
                               f"ack: {type(e).__name__}: {e}")
                    continue
            if verdict == "ok":
                self._drop_outstanding(rid)
                self._down_until.pop(channel, None)
                self.supervisor.mark_transferred(st["req"])
                self._markers.append(result_record(
                    rid, "transferred", target=channel))
            elif verdict is None:
                if st["dl"].expired():
                    self._drop_outstanding(rid)
                    self._down_until[channel] = (
                        time.monotonic() + self.handoff_budget)
                    self._fail(st["req"], "ack wait exceeded the "
                                          "handoff deadline budget")
            else:  # nacked: damage in transit — resend the same bytes
                self._down_until.pop(channel, None)  # channel is alive
                st["resends"] += 1
                if (st["resends"] > st["sender"].max_resends
                        or st["dl"].expired()):
                    self._drop_outstanding(rid)
                    self._fail(st["req"], f"nacked {st['resends']}x: "
                                          f"{verdict}")
                    continue
                try:
                    st["seq"] = st["sender"].send_handoff(
                        st["payload"], deadline=st["dl"])
                except (OSError, ValueError, TimeoutError) as e:
                    self._drop_outstanding(rid)
                    self._fail(st["req"],
                               f"resend: {type(e).__name__}: {e}")


class DecodeWorker:
    """A supervised decode engine plus the receiver side: each pump
    polls verified transfers, imports them (journaled, so a relaunch
    re-serves by colocated prefill), retries pool-full imports under
    the request's remaining budget, and steps the engine. Direct
    ``submit`` is the colocated-FALLBACK front door — behaviourally the
    proven unified engine."""

    def __init__(self, worker_id: str, engine_factory, store, *,
                 journal_dir: Optional[str] = None,
                 steps_per_pump: int = 1,
                 **supervisor_kwargs):
        self.replica_id = str(worker_id)
        self.journal_dir = journal_dir
        # decode steps between store interactions: raising this trades
        # handoff-ingest latency for inter-token latency (the serve
        # loop's store round trips stop punctuating every decode step)
        self.steps_per_pump = max(1, int(steps_per_pump))
        self.supervisor = ServingSupervisor(
            engine_factory, journal_dir=journal_dir, **supervisor_kwargs)
        self.receiver = KVHandoffReceiver(store, worker_id)
        self._pending_imports: List[HandoffPayload] = []
        self._dead = False
        self._published: Set = set()
        self._expired: List[dict] = []

    # -- router-handle surface ------------------------------------------
    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        self._dead = True

    def stop(self, deadline: Optional[Deadline] = None) -> None:
        del deadline
        self._dead = True

    def _knows(self, rid) -> bool:
        """At-least-once delivery meets one engine: a requeue/fallback
        clone of a request this worker is ALREADY serving (itself, or
        via an earlier import) must be dropped — two live owners of one
        req_id would collide in the BlockManager."""
        if rid in self.supervisor.results:
            return True
        if any(p.req_id == rid for p in self._pending_imports):
            return True
        eng = self.supervisor.engine
        if eng.manager.owned_blocks(rid):
            return True
        return any(r.req_id == rid for r in list(eng._queue))

    def submit(self, rec: dict) -> None:
        """Colocated fallback: a plain admission-controlled submission
        — the engine prefills it itself (chunked when configured).
        Idempotent per req_id: a clone of in-flight work is dropped."""
        if self._knows(rec["req_id"]):
            return
        self.supervisor.submit(
            rec["req_id"], np.asarray(rec["prompt"], np.int32),
            int(rec["max_new_tokens"]),
            deadline=remaining_budget(rec),
            priority=rec.get("priority", "interactive"),
            retries=int(rec.get("retries", 0)),
            trace=rec.get("trace"),
            tenant=rec.get("tenant", "default"))

    def pending(self) -> bool:
        return (not self._dead) and (
            self.supervisor.pending or bool(self._pending_imports))

    def active(self) -> bool:
        """Engine-side work RIGHT NOW — a pool-full import parked for
        retry doesn't count (the pool frees as the engine steps, which
        :attr:`supervisor.pending` already covers), so a serve loop
        can sleep instead of spinning on the store."""
        return (not self._dead) and self.supervisor.pending

    def load(self) -> Optional[dict]:
        eng = self.supervisor.engine
        d = eng.load().as_dict()
        d["role"] = "decode"
        d["imported"] = eng.n_imported
        d["pending_imports"] = len(self._pending_imports)
        return d

    def poll_completed(self) -> List[dict]:
        out, self._expired = list(self._expired), []
        for rid, r in list(self.supervisor.results.items()):
            if rid in self._published:
                continue
            self._published.add(rid)
            # per-token perf_counter stamps ride along: differences
            # within one worker process are valid inter-token
            # latencies, which is what the disagg bench reports
            out.append(result_record(rid, r.status, r.out,
                               shed_reason=r.shed_reason,
                               times=list(r.times)))
        return out

    # -- the pump --------------------------------------------------------
    def pump(self, deadline: Optional[Deadline] = None) -> None:
        del deadline  # the supervisor's step budget bounds each step
        if self._dead:
            return
        self._pending_imports.extend(self.receiver.recv_handoff())
        self._try_imports()
        for _ in range(self.steps_per_pump):
            if not self.supervisor.pending:
                break
            self.supervisor.step()

    def _try_imports(self) -> None:
        still: List[HandoffPayload] = []
        pending, self._pending_imports = self._pending_imports, []
        for p in pending:
            rem = p.remaining_budget()
            if rem is not None and rem <= 0:
                # the budget died in transit: close at zero token cost
                self._expired.append(result_record(p.req_id, "expired"))
                continue
            if self._knows(p.req_id):
                continue  # already serving it colocated (or finished)
            req = p.to_request()
            try:
                self.supervisor.engine.import_kv(
                    req, p.first_token, p.pages, p.scales, p.meta)
            except (BlockImportError, EngineFenced):
                still.append(p)  # transient: retry next pump
                continue
            except ValueError:
                # config skew (block size / layers / quantization /
                # max_len) — NO retry can import this payload here, but
                # the prompt rode along: serve it colocated (the engine
                # re-prefills; token-exact under greedy) instead of
                # letting one misrouted request crash the whole worker
                self.supervisor.submit(
                    req.req_id, req.prompt, req.max_new_tokens,
                    deadline=rem, priority=req.priority,
                    retries=req.retries, trace=req, tenant=req.tenant)
                continue
            self.supervisor.submit_imported(req)
        self._pending_imports = still


# ---------------------------------------------------------------------------
# The router: two pools + crash-only recovery + graceful degradation


class DisaggRouter:
    """Front door over a prefill pool and a decode pool. Placement is
    least-routed over LIVE prefill workers; when the prefill pool is
    EMPTY (or a transfer fails its budget) the request goes straight to
    a decode worker's colocated front door — graceful degradation, not
    an outage. Recovery is the cluster.py design: a dead worker's
    supervisor journal is replayed + compacted and unioned with the
    router's own routing table; survivors get the work token-exact with
    only the remaining deadline budget; repeat offenders quarantine
    per REQUEST."""

    def __init__(self, prefill_workers: Sequence,
                 decode_workers: Sequence, *,
                 max_request_retries: int = 2):
        if not decode_workers:
            raise ValueError("need at least one decode worker")
        self.prefill = list(prefill_workers)
        self.decode = list(decode_workers)
        self.max_request_retries = int(max_request_retries)
        self._decode_idx = {w.replica_id: i
                            for i, w in enumerate(self.decode)}
        # req_id -> (record, ("prefill"|"decode"|"decode?", idx))
        # "decode?" = transferred but target marker not yet seen
        self.inflight: Dict[object, Tuple[dict, Tuple[str, int]]] = {}
        self.orphans: Dict[object, dict] = {}
        self.results: Dict[object, dict] = {}
        self.retries: Dict[object, int] = {}
        self.poisoned_ids: List[object] = []
        self.dead_prefill: Set[int] = set()
        self.dead_decode: Set[int] = set()
        self.n_routed_prefill = [0] * len(self.prefill)
        self.n_routed_decode = [0] * len(self.decode)
        self.n_fallback = 0
        self.n_handoff_failed = 0
        self.n_recoveries = 0
        self.events: List[tuple] = []

    # -- placement -------------------------------------------------------
    def _live_prefill(self, exclude: Sequence[int] = ()) -> List[int]:
        return [i for i, w in enumerate(self.prefill)
                if i not in self.dead_prefill and i not in exclude
                and w.alive()]

    def _live_decode(self) -> List[int]:
        return [i for i, w in enumerate(self.decode)
                if i not in self.dead_decode and w.alive()]

    def submit(self, req_id, prompt, max_new_tokens: int = 32, *,
               deadline=None, priority: str = "interactive",
               trace=None, tenant: str = "default") -> Tuple[str, int]:
        """Route one request; returns ``(pool, index)`` — pool is
        "prefill" normally, "decode" when the prefill pool is down
        (colocated fallback). Results arrive via :meth:`poll` /
        :meth:`run`, keyed by ``req_id``, across any worker deaths.
        ``tenant`` rides the wire record and the handoff header."""
        with _obs.span("route", parent=_obs.trace_ctx(trace),
                       tid="router", req=str(req_id),
                       tenant=str(tenant)) as sp:
            rec = make_record(req_id, prompt, max_new_tokens,
                              deadline=deadline, priority=priority,
                              tenant=tenant,
                              retries=self.retries.get(req_id, 0),
                              trace=sp.ctx())
            pool, idx = self._place(rec)
            sp.args["pool"], sp.args["worker"] = pool, idx
        return pool, idx

    def _place(self, rec: dict,
               exclude_prefill: Sequence[int] = ()) -> Tuple[str, int]:
        live = self._live_prefill(exclude_prefill)
        if live:
            idx = min(live, key=lambda i: (self.n_routed_prefill[i], i))
            self.prefill[idx].submit(rec)
            self.n_routed_prefill[idx] += 1
            self.inflight[rec["req_id"]] = (rec, ("prefill", idx))
            return "prefill", idx
        return self._place_fallback(rec)

    def _place_fallback(self, rec: dict) -> Tuple[str, int]:
        live = self._live_decode()
        if not live:
            self.orphans[rec["req_id"]] = rec
            return "orphan", -1
        idx = min(live, key=lambda i: (self.n_routed_decode[i], i))
        self.decode[idx].submit(rec)
        self.n_routed_decode[idx] += 1
        self.n_fallback += 1
        self.inflight[rec["req_id"]] = (rec, ("decode", idx))
        return "decode", idx

    # -- harvest ---------------------------------------------------------
    def poll(self) -> List[dict]:
        new: List[dict] = []
        for pool, workers, dead in (("prefill", self.prefill,
                                     self.dead_prefill),
                                    ("decode", self.decode,
                                     self.dead_decode)):
            for i, w in enumerate(workers):
                if i in dead:
                    continue
                try:
                    done = w.poll_completed()
                except Exception:  # noqa: BLE001 — store blip
                    continue
                for rec in done:
                    new.extend(self._ingest(rec))
        return new

    def _ingest(self, rec: dict) -> List[dict]:
        rid = rec["req_id"]
        status = rec.get("status")
        if status == "transferred":
            # a baton pass, not a result: the decode pool owns it now
            if rid in self.inflight:
                old_rec, _ = self.inflight[rid]
                target = self._decode_idx.get(rec.get("target"), -1)
                kind = "decode" if target >= 0 else "decode?"
                self.inflight[rid] = (old_rec, (kind, target))
            return []
        if status == "handoff_failed":
            # the transfer lost; re-place colocated (not a worker
            # death — no retry penalty, the prompt just re-prefills)
            if rid in self.inflight and rid not in self.results:
                old_rec, _ = self.inflight.pop(rid)
                self.n_handoff_failed += 1
                self._place_fallback(old_rec)
            return []
        if rid in self.results:
            return []
        self.results[rid] = rec
        self.inflight.pop(rid, None)
        return [rec]

    # -- failure handling ------------------------------------------------
    def check_workers(self) -> None:
        for i, w in enumerate(self.prefill):
            if i not in self.dead_prefill and not w.alive():
                self.recover_prefill(i)
        for i, w in enumerate(self.decode):
            if i not in self.dead_decode and not w.alive():
                self.recover_decode(i)

    def _journal_pending(self, worker) -> Dict[object, dict]:
        """Replay + compact a dead worker's journal; harvest completed
        records; return the pending ones. "transferred" completions are
        a baton pass — NOT harvested as results, NOT pending here (the
        decode side owns them; the router table already tracks it)."""
        pending: Dict[object, dict] = {}
        if worker.journal_dir is None:
            return pending
        journal = Journal(worker.journal_dir)
        pend, completed = journal.replay()
        journal.compact(pend, completed)
        for rid, rec in completed.items():
            if rec.get("status") == "transferred":
                ent = self.inflight.get(rid)
                if ent is not None and ent[1][0] == "prefill":
                    self.inflight[rid] = (ent[0], ("decode?", -1))
                continue
            if rid not in self.results:
                self.results[rid] = result_record(
                    rid, rec.get("status", "ok"), rec.get("out", []))
                self.inflight.pop(rid, None)
        pending.update(pend)
        return pending

    def _requeue(self, pending: Dict[object, dict],
                 exclude_prefill: Sequence[int] = ()) -> Tuple[int, int]:
        n_requeued = n_poisoned = 0
        for rid, rec in pending.items():
            if rid in self.results:
                continue
            self.inflight.pop(rid, None)
            remaining = remaining_budget(rec)
            if remaining is not None and remaining <= 0:
                self.results[rid] = result_record(rid, "expired")
                continue
            retries = max(self.retries.get(rid, 0),
                          int(rec.get("retries", 0))) + 1
            self.retries[rid] = retries
            if retries > self.max_request_retries:
                self.results[rid] = result_record(rid, "poisoned")
                self.poisoned_ids.append(rid)
                n_poisoned += 1
                continue
            new_rec = dict(rec)
            new_rec.pop("type", None)
            new_rec["retries"] = retries
            self._place(new_rec, exclude_prefill=exclude_prefill)
            n_requeued += 1
        return n_requeued, n_poisoned

    def recover_prefill(self, idx: int) -> None:
        """Crash-only prefill-worker recovery: journal replay ∪ the
        router's own table covers every accepted-but-unfinished request
        (mailed-never-pulled included); survivors take them token-exact
        with only the remaining budget — or the decode pool serves them
        colocated when no prefill worker is left."""
        w = self.prefill[idx]
        self.dead_prefill.add(idx)
        self.n_recoveries += 1
        try:
            for rec in w.poll_completed():
                self._ingest(rec)
        except Exception:  # noqa: BLE001 — the store may be gone too
            pass
        pending = self._journal_pending(w)
        for rid, (rec, where) in list(self.inflight.items()):
            if where == ("prefill", idx) and rid not in pending:
                pending[rid] = rec
        n_req, n_poi = self._requeue(pending, exclude_prefill=(idx,))
        self.events.append(
            ("prefill-dead", w.replica_id, n_req, n_poi))

    def recover_decode(self, idx: int) -> None:
        """Decode-worker death: its KV dies with it, so journal-pending
        (imports + fallback submissions) ∪ router-table entries
        targeting it re-enter the FULL pipeline (prefill pool again, or
        a surviving decode colocated). Unknown-target transfers
        ("decode?" — the marker never reached us) are requeued too:
        idempotent transfer + first-result-wins make the duplicate
        harmless if the target was actually a survivor."""
        w = self.decode[idx]
        self.dead_decode.add(idx)
        self.n_recoveries += 1
        try:
            for rec in w.poll_completed():
                self._ingest(rec)
        except Exception:  # noqa: BLE001
            pass
        pending = self._journal_pending(w)
        for rid, (rec, where) in list(self.inflight.items()):
            if where in (("decode", idx), ("decode?", -1)) \
                    and rid not in pending:
                pending[rid] = rec
        n_req, n_poi = self._requeue(pending)
        self.events.append(
            ("decode-dead", w.replica_id, n_req, n_poi))

    def _place_orphans(self) -> None:
        for rid, rec in list(self.orphans.items()):
            remaining = remaining_budget(rec)
            if remaining is not None and remaining <= 0:
                del self.orphans[rid]
                self.results[rid] = result_record(rid, "expired")
                continue
            pool, _ = self._place(rec)
            if pool == "orphan":
                return  # still nobody home
            del self.orphans[rid]

    # -- drive loop ------------------------------------------------------
    def step(self) -> List[dict]:
        for i, w in enumerate(self.prefill):
            if i not in self.dead_prefill:
                w.pump()
        for i, w in enumerate(self.decode):
            if i not in self.dead_decode:
                w.pump()
        out = self.poll()
        self.check_workers()
        if self.orphans:
            self._place_orphans()
        return out

    def run(self, deadline=None, poll_interval: float = 0.02) -> dict:
        dl = Deadline.coerce(deadline)
        while (self.inflight or self.orphans) and not dl.expired():
            got = self.step()
            if got:
                continue
            if any(w.pending() for i, w in enumerate(self.prefill)
                   if i not in self.dead_prefill) or \
                    any(w.pending() for i, w in enumerate(self.decode)
                        if i not in self.dead_decode):
                continue  # local work ready to pump: no sleep
            if dl.budget is None:
                time.sleep(poll_interval)
            else:
                dl.sleep(poll_interval)
        return dict(self.results)

    def stop(self, deadline=None) -> None:
        dl = Deadline.coerce(deadline)
        for w in self.prefill + self.decode:
            w.stop(deadline=dl.sub(fraction=0.5))

    def health(self) -> dict:
        def entry(w, i, dead):
            alive = i not in dead and w.alive()
            e = {"replica_id": w.replica_id, "alive": alive}
            if alive:
                try:
                    e["load"] = w.load()
                except Exception:  # noqa: BLE001 — best-effort snapshot
                    e["load"] = None
            return e

        return _obs.health_envelope("disagg", {
            "prefill": [entry(w, i, self.dead_prefill)
                        for i, w in enumerate(self.prefill)],
            "decode": [entry(w, i, self.dead_decode)
                       for i, w in enumerate(self.decode)],
            "inflight": len(self.inflight),
            "orphans": len(self.orphans),
            "results": len(self.results),
            "poisoned": list(self.poisoned_ids),
            "fallback": self.n_fallback,
            "handoff_failed": self.n_handoff_failed,
            "recoveries": self.n_recoveries,
        })


# ---------------------------------------------------------------------------
# Process mode


class DisaggServer:
    """Process-mode serve loop for EITHER role: wraps a
    :class:`PrefillWorker` / :class:`DecodeWorker`, pulls request
    records from the router's mailbox (``cluster/<id>/req/NNN`` — the
    same schema :class:`~paddle_tpu.inference.cluster.ProcessReplica`
    speaks, so the router reuses that handle unchanged), pumps the
    worker, and publishes results / load / heartbeats. Also attaches
    the flight-recorder contract so a hang dump on either side names
    BOTH roles' schedules. The default contract topology (prefill =
    rank 0, decode = rank 1, world 2) fits the canonical one-prefill +
    one-decode pair ONLY — deployments with several workers per role
    must pass explicit ``contract_rank``/``contract_world`` (e.g. an
    enumeration over the whole deployment) or same-role workers would
    publish their schedules under the same rank key and clobber each
    other exactly when the dump is needed."""

    ROLE_RANKS = {"prefill": 0, "decode": 1}

    def __init__(self, store, worker, *, poll_interval: float = 0.02,
                 contract_rank: Optional[int] = None,
                 contract_world: int = 2):
        self.store = store
        self.worker = worker
        self.replica_id = worker.replica_id
        self.ns = f"cluster/{self.replica_id}"
        self.poll_interval = float(poll_interval)
        self._taken: Set[str] = set()
        self._hb = 0
        self._pub_seq = 0
        self._pub_nonce = uuid.uuid4().hex[:6]
        if contract_rank is None:
            role = ("prefill" if isinstance(worker, PrefillWorker)
                    else "decode")
            contract_rank = self.ROLE_RANKS[role]
        _fr.attach_contract(store, int(contract_rank),
                            int(contract_world))
        # fleet-obs publication (ISSUE 15): disagg workers were the one
        # serve loop NOT publishing their registry/trace ring, so the
        # fleet snapshot (and the absence rules) could not see them
        self._obs_pub = _obs_agg.Publisher(
            store, f"rep-{self.replica_id}")

    def _pull(self) -> int:
        n = 0
        for key in sorted(self.store.keys(self.ns + "/req/")):
            if key in self._taken:
                continue
            raw = self.store.get(key)
            if raw is None:
                continue
            self._taken.add(key)
            rec = json.loads(raw)
            sup = self.worker.supervisor
            rid = rec["req_id"]
            # skip a submission a relaunch already replayed — but a
            # router REQUEUE of work this worker already served (the
            # decode side died after our baton pass) carries a BUMPED
            # retries count and must be accepted, not dropped forever
            if (rid in sup.journaled_ids
                    and int(rec.get("retries", 0))
                    <= sup.journaled_retries.get(rid, 0)):
                continue
            self.worker.submit(rec)
            n += 1
        return n

    def _publish(self) -> None:
        for rec in self.worker.poll_completed():
            # per-ATTEMPT key: one request can legitimately publish
            # several records ("transferred", then "handoff_failed"
            # after a requeue, then a final result) and ProcessReplica
            # dedups by key — a fixed done/<rid> slot would swallow
            # every record after the first; the nonce keeps keys fresh
            # across worker relaunches too
            self._pub_seq += 1
            self.store.set(
                f"{self.ns}/done/{rec['req_id']}@{self._pub_nonce}"
                f"-{self._pub_seq:06d}", json.dumps(rec))
        load = self.worker.load()
        if load is not None:
            self.store.set(self.ns + "/load", json.dumps(load))
        self._hb += 1
        self.store.set(self.ns + "/hb", str(self._hb))
        self._obs_pub.maybe_publish()
        _obs.default_manager().maybe_evaluate(
            min_interval_s=self._obs_pub.interval_s)

    def serve(self, deadline=None) -> None:
        """Serve until ``stop`` is posted or the Deadline runs out;
        every blocking edge bounded (store ops carry their own per-op
        budget, idle waits go through ``Deadline.sleep``)."""
        dl = Deadline.coerce(deadline)
        self._publish()  # first heartbeat: visible before any work
        try:
            while not dl.expired():
                if self.store.get(self.ns + "/stop"):
                    break
                took = self._pull()
                self.worker.pump()
                # sleep whenever only store-side waits remain (an
                # outstanding ack, a pool-full import retry): pending()
                # counts those, but polling them at full speed would
                # hammer the store with no engine work to show for it
                if not (took or self.worker.active()):
                    if dl.budget is None:
                        time.sleep(self.poll_interval)
                    else:
                        dl.sleep(self.poll_interval)
                self._publish()
            self._publish()
        finally:
            try:
                self._obs_pub.publish()  # final full-registry flush
            except Exception:
                pass  # the store may be the thing that died
