"""Closed-loop fleet autoscaling driven by SLO burn-rate alerts.

PRs 11–13 built the *sense* side of fleet operation — per-tenant burn
rates, error budgets, absence detection, fleet snapshots — but nothing
consumed those signals to act: fleet size was fixed at construction.
This module is the *act* side: a :class:`FleetAutoscaler` that owns a
:class:`~paddle_tpu.inference.cluster.ClusterRouter`'s replica set and
closes the loop on the alert engine itself.

Controller state machine (one action per ``step``)::

      STEADY ── short-window BurnRateRule fires ──────────▶ SCALE-UP
        ▲        (spawn via replica_factory; chaos          │
        │         `scale.spawn` drop/error = bounded        │
        │         exponential backoff, heartbeat withheld   │
        │         so an AbsenceRule sees the stall —        │
        │         never a crash-loop)                       │
        │                                                   ▼
      DRAIN ◀── budget_remaining_frac recovered past     STEADY
        │        `recover_budget_frac` AND held
        │        `recover_hold_s` AND cooldown passed
        │
        ├── drained (no inflight, queue empty) ──▶ retire (forfeit the
        │                                          replica's radix tree;
        │                                          the host tier keeps
        │                                          its spilled prefixes)
        └── dies mid-drain (chaos `scale.drain`) ─▶ router recovery:
                                                   journal-∪-table
                                                   requeue, zero
                                                   accepted requests
                                                   lost

Why the alert engine is the control signal: the multi-window burn-rate
rules (Google SRE Workbook policy, PR 13) already encode "is the SLO
in danger *now*" with flap suppression — re-deriving that from raw
latencies in the controller would just be a worse copy. Scale-up keys
off any firing burn alert (the short window makes it fast); scale-down
keys off the *budget* annotation recovering past hysteresis and
holding there, so a transient lull inside an incident never sheds
capacity. A feed-forward term (the loadgen ``TraceSpec`` diurnal/burst
shape, or any ``now -> expected-rate-multiple`` callable) raises the
replica floor BEFORE a predictable peak arrives — feedback alone
always pays one breach per ramp.

Disaggregated fleets (``AutoscalerConfig(disagg=True)``): the next
spawn's role steers by measured pressure — prefill pressure (chunk
backlog: queued-work delay estimate + prefilling slots) against decode
pressure (slot occupancy + step-latency EWMA, the ITL proxy) — so the
prefill:decode pool ratio follows the workload's prompt/generation mix
instead of being frozen at deploy time.
"""
from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..obs.alerts import AbsenceRule, ThresholdRule
from ..obs.metrics import registry as _reg
from ..testing import chaos as _chaos

__all__ = ["AutoscalerConfig", "FleetAutoscaler"]


@dataclass
class AutoscalerConfig:
    """Controller knobs. The hysteresis pair — breach fires scale-up,
    but scale-down additionally needs the error budget back above
    ``recover_budget_frac`` for ``recover_hold_s`` — is what keeps the
    controller from oscillating at the SLO boundary."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_cooldown_s: float = 1.0
    scale_down_cooldown_s: float = 5.0
    # scale-down hysteresis: budget_remaining_frac must exceed this...
    recover_budget_frac: float = 0.5
    # ...continuously for this long before a drain may start
    recover_hold_s: float = 3.0
    # bounded exponential backoff after a failed spawn
    spawn_backoff_s: float = 0.5
    spawn_backoff_max_s: float = 8.0
    # a draining replica that cannot quiesce within this window is
    # treated as crashed (recovery requeues its accepted work)
    drain_timeout_s: float = 30.0
    # feed-forward: floor = ceil(min_replicas * rate_multiple * headroom)
    feedforward_headroom: float = 1.0
    # alert evaluation cadence inside step() (0 = every step)
    evaluate_interval_s: float = 0.25
    # disaggregated fleets: steer the next spawn's role by prefill vs
    # decode pressure; >1 biases toward prefill workers
    disagg: bool = False
    prefill_decode_bias: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 < self.recover_budget_frac < 1.0:
            raise ValueError("recover_budget_frac must be in (0, 1)")
        if self.spawn_backoff_s <= 0 or self.spawn_backoff_max_s \
                < self.spawn_backoff_s:
            raise ValueError("spawn backoff bounds must satisfy "
                             "0 < spawn_backoff_s <= spawn_backoff_max_s")


def _squash(x: Optional[float]) -> float:
    x = float(x or 0.0)
    return x / (1.0 + x)


class FleetAutoscaler:
    """SLO-burn-driven replica controller over a
    :class:`~paddle_tpu.inference.cluster.ClusterRouter`.

    ``replica_factory(replica_id)`` (or ``(replica_id, role=...)`` with
    ``disagg=True``) builds one replica transport; the controller joins
    it via ``router.add_replica``. ``alerts`` is the
    :class:`~paddle_tpu.obs.alerts.AlertManager` holding the fleet's
    :class:`BurnRateRule`s — the controller reads its statuses and
    ticks ``maybe_evaluate`` itself, so a bench or single-process
    deployment needs no separate evaluation loop. ``feedforward`` is an
    optional ``now -> expected-rate-multiple`` callable (see
    ``benchmarks.loadgen.feedforward_from_spec``).

    Drive it either by calling :meth:`step` from an existing loop or
    via the background thread (:meth:`start`/:meth:`stop`). All mutable
    state is guarded by one lock; every public method is thread-safe.
    """

    SOURCE = "autoscaler"

    def __init__(self, router, replica_factory: Callable, *,
                 config: Optional[AutoscalerConfig] = None,
                 alerts=None,
                 feedforward: Optional[Callable[[float], float]] = None,
                 clock=time.monotonic):
        self.router = router
        self.replica_factory = replica_factory
        self.config = config if config is not None else AutoscalerConfig()
        self.alerts = alerts
        self.feedforward = feedforward
        self._clock = clock
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._last_scale_up = -math.inf
        self._last_scale_down = -math.inf
        self._recovered_since: Optional[float] = None
        self._spawn_fail_streak = 0
        self._spawn_retry_at = -math.inf
        self._spawn_seq = 0
        self._last_healthy: Optional[float] = None
        self._last_step_t: Optional[float] = None
        self._draining: Dict[int, float] = {}  # idx -> drain start
        self.replica_seconds = 0.0
        self.decisions: List[dict] = []
        reg = _reg()
        self._reg = reg
        self._g_replicas = reg.gauge(
            "autoscale_replicas",
            help="replicas accepting NEW placements (live minus "
                 "draining)")
        self._g_desired = reg.gauge(
            "autoscale_desired_replicas",
            help="controller's current replica target")
        self._g_fail = reg.gauge(
            "autoscale_spawn_consecutive_failures",
            help="consecutive failed spawn attempts (0 = healthy)")

    # -- alert-side helpers ---------------------------------------------
    def alert_rules(self, *, heartbeat_max_age_s: float = 10.0) -> list:
        """Rules making the controller's OWN failure modes page: a
        spawn crash-loop (consecutive-failure gauge > 0) and controller
        silence (the withheld heartbeat, via an AbsenceRule over source
        ``autoscaler`` — feed :meth:`heartbeat_age` into
        ``AlertManager.evaluate(ages=...)``)."""
        return [
            ThresholdRule(
                "autoscale_spawn_failing",
                "autoscale_spawn_consecutive_failures",
                threshold=0.0, op=">", stat="value",
                severity="critical"),
            AbsenceRule("autoscale_silent", source=self.SOURCE,
                        max_age_s=heartbeat_max_age_s),
        ]

    def heartbeat_age(self, now: Optional[float] = None) -> float:
        """Seconds since the controller last completed a HEALTHY step
        (healthy = no unresolved spawn failure). While a spawn is
        failing the heartbeat is withheld, so an
        ``AbsenceRule(source="autoscaler")`` fires — the satellite
        contract: spawn failure is alert-visible, never a crash-loop."""
        with self._lock:
            now = self._clock() if now is None else float(now)
            if self._spawn_fail_streak > 0 or self._last_healthy is None:
                return math.inf
            return max(now - self._last_healthy, 0.0)

    # -- fleet introspection --------------------------------------------
    def _live_idxs(self) -> List[int]:
        return [i for i, rep in enumerate(self.router.replicas)
                if i not in self.router.dead and rep.alive()]

    def _burn_signal(self):
        """(breach, worst budget_remaining_frac) from the manager's
        burn statuses. Only burn rules annotate a budget, so the filter
        is structural — no rule-name convention needed."""
        if self.alerts is None:
            return False, None
        breach, budget = False, None
        for st in self.alerts.statuses():
            ann = st.get("annotations") or {}
            if "budget_remaining_frac" not in ann:
                continue
            if st.get("state") == "firing":
                breach = True
            b = float(ann["budget_remaining_frac"])
            budget = b if budget is None else min(budget, b)
        return breach, budget

    def _floor(self, now: float) -> int:
        floor = self.config.min_replicas
        if self.feedforward is not None:
            try:
                mult = max(float(self.feedforward(now)), 0.0)
            except Exception:  # noqa: BLE001 — a broken hint never
                mult = 1.0     # takes the controller down with it
            floor = max(floor, math.ceil(
                self.config.min_replicas * mult
                * self.config.feedforward_headroom))
        return min(floor, self.config.max_replicas)

    # -- the control step ------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One control tick: integrate replica-seconds, sweep drains,
        evaluate alerts, then at most ONE scaling action. Returns the
        decision record (also appended to ``decisions``)."""
        with self._lock:
            now = self._clock() if now is None else float(now)
            cfg = self.config
            live = self._live_idxs()
            if self._last_step_t is not None:
                self.replica_seconds += len(live) * max(
                    now - self._last_step_t, 0.0)
            self._last_step_t = now
            if self.alerts is not None:
                try:
                    self.alerts.maybe_evaluate(
                        min_interval_s=cfg.evaluate_interval_s)
                except Exception:  # noqa: BLE001 — a broken rule set
                    pass           # must not stop the control loop
            self._sweep_drains(now)
            live = self._live_idxs()
            placeable = [i for i in live if i not in self._draining]
            breach, budget = self._burn_signal()
            floor = self._floor(now)
            desired = len(placeable)
            action = "hold"
            if breach:
                self._recovered_since = None
            if len(placeable) < floor:
                desired = floor
                action = self._try_spawn(now, reason="feedforward-floor")
            elif breach and len(placeable) < cfg.max_replicas \
                    and now - self._last_scale_up >= cfg.scale_up_cooldown_s:
                desired = len(placeable) + 1
                action = self._try_spawn(now, reason="burn-breach")
            elif (not breach and not self._draining
                    and len(placeable) > floor):
                recovered = budget is None \
                    or budget >= cfg.recover_budget_frac
                if recovered:
                    if self._recovered_since is None:
                        self._recovered_since = now
                    if (now - self._recovered_since >= cfg.recover_hold_s
                            and now - self._last_scale_down
                            >= cfg.scale_down_cooldown_s):
                        desired = len(placeable) - 1
                        action = self._start_drain(now)
                else:
                    self._recovered_since = None
            if self._spawn_fail_streak == 0:
                self._last_healthy = now
            self._g_replicas.set(float(len(placeable)))
            self._g_desired.set(float(desired))
            rec = {"t": now, "action": action, "live": len(live),
                   "placeable": len(placeable), "desired": desired,
                   "floor": floor, "breach": breach,
                   "budget_remaining_frac": budget,
                   "draining": sorted(self._draining),
                   "replica_seconds": self.replica_seconds}
            if action != "hold":
                self.decisions.append(rec)
            return rec

    def _count(self, action: str) -> None:
        self._reg.counter(
            "autoscale_decisions_total", {"action": action},
            help="autoscaler actions by kind").inc()

    # -- scale-up --------------------------------------------------------
    def _try_spawn(self, now: float, *, reason: str) -> str:
        cfg = self.config
        if now < self._spawn_retry_at:
            return "spawn-backoff"
        role = self._pick_role() if cfg.disagg else None
        rid = f"auto{self._spawn_seq}"
        try:
            # chaos site: spawn failure (drop or error) — the
            # controller backs off exponentially (bounded) and stays
            # in its loop; the withheld heartbeat + failure gauge make
            # the stall alert-visible
            if not _chaos.inject("scale.spawn"):
                raise RuntimeError("chaos: spawn dropped")
            rep = (self.replica_factory(rid) if role is None
                   else self.replica_factory(rid, role=role))
        except Exception as e:  # noqa: BLE001 — ANY spawn failure backs
            self._spawn_fail_streak += 1
            self._g_fail.set(float(self._spawn_fail_streak))
            backoff = min(
                cfg.spawn_backoff_s * (2 ** (self._spawn_fail_streak - 1)),
                cfg.spawn_backoff_max_s)
            self._spawn_retry_at = now + backoff
            self._count("spawn-failed")
            self.decisions.append(
                {"t": now, "action": "spawn-failed", "reason": reason,
                 "error": str(e), "backoff_s": backoff,
                 "streak": self._spawn_fail_streak})
            return "spawn-failed"
        self._spawn_seq += 1
        self._spawn_fail_streak = 0
        self._g_fail.set(0.0)
        self._spawn_retry_at = now
        idx = self.router.add_replica(rep)
        # a spawn outranks any in-progress drain of the same capacity
        if idx in self._draining:  # pragma: no cover — fresh index
            del self._draining[idx]
        self._last_scale_up = now
        self._recovered_since = None
        self._count("scale-up")
        self.decisions.append(
            {"t": now, "action": "scale-up", "reason": reason,
             "replica": rep.replica_id, "index": idx, "role": role})
        return "scale-up"

    def _pick_role(self) -> str:
        """Disagg pool-ratio steering: compare fleet-wide prefill
        pressure (queued-chunk backlog / delay estimate + prefilling
        slots) against decode pressure (slot occupancy + the ITL proxy,
        step-latency EWMA); spawn the starved side."""
        prefill_p = decode_p = 0.0
        for i in self._live_idxs():
            try:
                d = self.router.replicas[i].load() or {}
            except Exception:  # noqa: BLE001 — unreadable load: skip
                continue
            mb = max(int(d.get("max_batch") or 1), 1)
            prefill_p += (_squash(d.get("est_queue_delay_s"))
                          + int(d.get("prefilling") or 0) / mb)
            decode_p += (int(d.get("active_slots") or 0) / mb
                         + _squash(d.get("ewma_step_s")))
        if prefill_p * self.config.prefill_decode_bias >= decode_p:
            return "prefill"
        return "decode"

    # -- scale-down ------------------------------------------------------
    def _start_drain(self, now: float) -> str:
        victim = self._pick_drain_victim()
        if victim is None:
            return "hold"
        self.router.mark_draining(victim)
        self._draining[victim] = now
        self._last_scale_down = now
        self._count("drain-start")
        self.decisions.append(
            {"t": now, "action": "drain-start", "index": victim,
             "replica": self.router.replicas[victim].replica_id})
        # chaos site: a drop here is a SIGKILL MID-DRAIN — the replica
        # dies with accepted work still on it. The router's liveness
        # sweep then runs journal-∪-table recovery; the acceptance
        # proof is that zero accepted requests are lost even so.
        if not _chaos.inject("scale.drain"):
            try:
                self.router.replicas[victim].kill()
            except Exception:  # noqa: BLE001 — no kill hook: the
                pass           # timeout path recovers it instead
        return "drain-start"

    def _pick_drain_victim(self) -> Optional[int]:
        """Prefix-cache-aware victim choice: forfeit the replica whose
        radix tree is worth the least (fewest cached nodes, then
        fewest routed requests, then the newest index) — the cheapest
        tree to re-warm on the survivors."""
        cands = [i for i in self._live_idxs() if i not in self._draining]
        if len(cands) <= 1:
            return None

        def value(i):
            nodes = 0
            try:
                pf = (self.router.replicas[i].load() or {}).get(
                    "prefix") or {}
                nodes = int(pf.get("nodes") or 0)
            except Exception:  # noqa: BLE001 — unreadable load scores 0
                pass
            return (nodes, self.router.n_routed[i], -i)

        return min(cands, key=value)

    def _sweep_drains(self, now: float) -> None:
        for idx, since in list(self._draining.items()):
            rep = self.router.replicas[idx]
            if idx in self.router.dead or not rep.alive():
                # died mid-drain: the router's check_replicas owns the
                # recovery (journal ∪ table requeue); nothing to retire
                del self._draining[idx]
                self._count("drain-died")
                self.decisions.append(
                    {"t": now, "action": "drain-died", "index": idx})
                continue
            if self.router.drained(idx):
                self.router.retire_replica(idx)
                del self._draining[idx]
                self._last_scale_down = now
                self._count("scale-down")
                self.decisions.append(
                    {"t": now, "action": "scale-down", "index": idx,
                     "replica": rep.replica_id})
            elif now - since > self.config.drain_timeout_s:
                # cannot quiesce (a stuck session keeps following it):
                # crash-only fallback — recover requeues its accepted
                # work onto survivors, then the replica is stopped
                del self._draining[idx]
                self.router.recover_replica(idx)
                try:
                    rep.stop()
                except Exception:  # noqa: BLE001 — best-effort stop
                    pass
                self._count("drain-timeout")
                self.decisions.append(
                    {"t": now, "action": "drain-timeout", "index": idx})

    # -- background serve loop -------------------------------------------
    def start(self, interval_s: float = 0.25) -> None:
        """Run :meth:`step` on a daemon thread every ``interval_s``."""
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("autoscaler already started")
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._serve, args=(float(interval_s),),
                name="paddle-tpu-autoscaler", daemon=True)
            self._thread.start()

    def _serve(self, interval_s: float) -> None:
        while not self._stop_evt.wait(interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive any single bad tick (a dying replica's load()
                # mid-teardown, a racing router mutation); the next
                # tick re-reads everything from scratch
                continue

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
            self._stop_evt.set()
        if thread is not None:
            thread.join(timeout=10.0)

    # -- observability ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "live": len(self._live_idxs()),
                "draining": sorted(self._draining),
                "replica_seconds": self.replica_seconds,
                "spawn_fail_streak": self._spawn_fail_streak,
                "decisions": len(self.decisions),
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
            }
