"""Cluster serving: a replica router with prefix-cache-aware scheduling.

One :class:`~paddle_tpu.inference.serving.ContinuousBatchingEngine` is
deep but narrow — "millions of users" means a FLEET of engine replicas
(one per chip/host) behind a router. This module is that router plus
the two replica transports it fronts:

- :class:`InProcessReplica` — a :class:`ServingSupervisor` in this
  process (the bench / single-host shape; also the unit-test harness
  for the routing and recovery logic).
- :class:`ProcessReplica` + :class:`ReplicaServer` — a REAL process
  boundary over the existing
  :class:`~paddle_tpu.distributed.store.TCPKVStore`: the router mails
  request records into the store, the replica worker
  (:class:`ReplicaServer`, run in its own process like the
  ``_mc_worker`` machinery runs trainers) polls them into its local
  supervisor, serves, and mails results + a live load snapshot +
  heartbeats back.

Placement (:meth:`ClusterRouter.route`) scores every live replica from
the SAME :class:`~paddle_tpu.inference.admission.EngineLoad` signal the
admission controller uses — queue pressure, KV-block occupancy,
token-backlog-derived queueing delay, step-latency EWMA — minus an
AFFINITY bonus with two sources:

- **session affinity**: a request carrying ``session=`` is pulled
  toward the replica that last served that session (its KV/prefix
  state lives there);
- **prefix affinity**: the router keeps a per-replica radix tree
  (matcher-mode :class:`~paddle_tpu.ops.paged_attention.PrefixCache`)
  over the BLOCK-ALIGNED token prefixes it has routed; a prompt whose
  prefix a replica has already seen scores toward that replica, where
  the engine-side prefix cache (ref-counted copy-on-write KV blocks)
  turns the affinity into actual skipped prefill work. Routing
  prefix-blind would halve the hit rate at 2 replicas — affinity is
  what makes per-replica caches compose into a cluster-level cache.

Failure handling is replica-level crash-only recovery, the
:class:`ServingSupervisor` design one level up: a replica that stops
heartbeating / whose process died is never repaired in place. Its
fsync'd journal is replayed + compacted (the same
:class:`~paddle_tpu.inference.supervisor.Journal` format the
in-process resume uses), completed work is harvested, and every
accepted-but-unfinished request requeues onto the SURVIVORS —
token-exact under greedy decode, deadlines carrying only the remaining
wall-clock budget. Poison quarantine stays per REQUEST: a request whose
replica died more than ``max_request_retries`` times is quarantined
(``status="poisoned"``) instead of being allowed to hunt the fleet.

Chaos site ``cluster.route`` (a ``drop`` fault) deterministically
MISROUTES a placement to the next live replica — correctness (token
exactness, completion) must never depend on the scorer's choice, only
efficiency may.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import agg as _obs_agg
from ..ops.paged_attention import PrefixCache
from ..testing import chaos as _chaos
from ..utils.retries import Deadline
from .serving import GenRequest  # noqa: F401  (result/record contract)
from .supervisor import Journal, ServingSupervisor

__all__ = [
    "ClusterRouter",
    "InProcessReplica",
    "ProcessReplica",
    "ReplicaServer",
    "NoLiveReplica",
]


class NoLiveReplica(RuntimeError):
    """Every replica is dead or excluded — nothing can take the work."""


def make_record(req_id, prompt, max_new_tokens: int = 32, *,
                deadline=None, priority: str = "interactive",
                session: Optional[str] = None, retries: int = 0,
                trace=None, tenant: str = "default") -> dict:
    """The wire/journal-compatible request record. The deadline is
    carried as an ABSOLUTE unix expiry (wall time is the only clock two
    processes share) so every hop — router -> store -> replica ->
    journal -> requeue — grants only the REMAINING budget. ``trace``
    (a ``{"trace_id", "span_id"}`` dict or anything
    :func:`paddle_tpu.obs.trace_ctx` accepts) rides the record so the
    receiving worker's spans parent under the submitter's."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    expires = None
    if deadline is not None:
        dl = Deadline.coerce(deadline)
        if dl.budget is not None:
            expires = time.time() + dl.remaining()
    return {
        "req_id": req_id,
        "prompt": [int(t) for t in prompt],
        "max_new_tokens": int(max_new_tokens),
        "priority": priority,
        "tenant": str(tenant),
        "deadline_unix": expires,
        "session": session,
        "retries": int(retries),
        "trace": _obs.trace_ctx(trace),
    }


def _remaining_budget(rec: dict) -> Optional[float]:
    """None = unbounded; <= 0 = already expired."""
    expires = rec.get("deadline_unix")
    return None if expires is None else expires - time.time()


def _result(req_id, status: str, out=(), **extra) -> dict:
    rec = {"req_id": req_id, "status": status,
           "out": [int(t) for t in out]}
    rec.update(extra)
    return rec


# the disagg handoff router (inference/disagg.py) speaks the same
# record/result/remaining-budget wire contract — one format from the
# cluster router through the journal to the prefill/decode pools
result_record = _result
remaining_budget = _remaining_budget


# ---------------------------------------------------------------------------
# Replica transports


class InProcessReplica:
    """A supervised engine in THIS process. ``journal_dir`` makes its
    accepted work recoverable by the router exactly like a process
    replica's; ``kill()`` is the fault hook tests/operators use to take
    it out of rotation (the router then runs journal recovery)."""

    def __init__(self, replica_id: str, engine_factory, *,
                 journal_dir: Optional[str] = None, **supervisor_kwargs):
        self.replica_id = str(replica_id)
        self.journal_dir = journal_dir
        self.supervisor = ServingSupervisor(
            engine_factory, journal_dir=journal_dir, **supervisor_kwargs)
        self._dead = False
        self._published: Set = set()

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        """Simulate replica death: no further pumps; pending work is
        recovered by the router from the journal / its routing table."""
        self._dead = True

    def submit(self, rec: dict) -> None:
        self.supervisor.submit(
            rec["req_id"], np.asarray(rec["prompt"], np.int32),
            int(rec["max_new_tokens"]),
            deadline=_remaining_budget(rec),
            priority=rec.get("priority", "interactive"),
            retries=int(rec.get("retries", 0)),
            trace=rec.get("trace"),
            tenant=rec.get("tenant", "default"))

    def poll_completed(self) -> List[dict]:
        out = []
        for rid, r in list(self.supervisor.results.items()):
            if rid in self._published:
                continue
            self._published.add(rid)
            out.append(_result(rid, r.status, r.out,
                               shed_reason=r.shed_reason))
        return out

    def load(self) -> Optional[dict]:
        eng = self.supervisor.engine
        d = eng.load().as_dict()
        d["prefix"] = eng.prefix_stats()
        return d

    def pending(self) -> bool:
        return (not self._dead) and self.supervisor.pending

    def pump(self, deadline: Optional[Deadline] = None) -> None:
        """Drive one supervised engine step (no-op when idle/dead)."""
        del deadline  # the supervisor's own step_budget bounds the step
        if not self._dead and self.supervisor.pending:
            self.supervisor.step()

    def stop(self, deadline: Optional[Deadline] = None) -> None:
        del deadline
        self._dead = True


class ProcessReplica:
    """Router-side handle for a replica served by a
    :class:`ReplicaServer` in ANOTHER process, over a shared KV store.

    Store schema under ``cluster/<replica_id>/``::

        req/<seq>   one JSON request record per submission (ordered)
        done/<id>   one JSON result record per finished request
        load        latest EngineLoad.as_dict() + prefix stats
        hb          heartbeat counter (liveness = the BACKEND-clock age
                    of this key via ``store.dump`` — immune to clock
                    skew between router and replica hosts)
        stop        set by the router to shut the worker down

    ``proc`` (a Popen-style object with ``poll()``) makes liveness
    exact for locally-spawned workers; without it the heartbeat age
    alone decides."""

    def __init__(self, store, replica_id: str, *,
                 journal_dir: Optional[str] = None, proc=None,
                 heartbeat_timeout: float = 15.0):
        self.store = store
        self.replica_id = str(replica_id)
        self.ns = f"cluster/{self.replica_id}"
        self.journal_dir = journal_dir
        self.proc = proc
        self.heartbeat_timeout = float(heartbeat_timeout)
        self._seq = 0
        self._seen_done: Set[str] = set()

    def alive(self) -> bool:
        if self.proc is not None and self.proc.poll() is not None:
            return False
        try:
            ents = self.store.dump(self.ns + "/hb")
        except Exception:  # noqa: BLE001 — store blip != replica death
            return True
        if not ents:
            # not heartbeating YET (still importing/compiling): only a
            # dead process handle can prove death this early
            return True
        return ents[0][2] <= self.heartbeat_timeout

    def submit(self, rec: dict) -> None:
        self.store.set(f"{self.ns}/req/{self._seq:08d}", json.dumps(rec))
        self._seq += 1

    def poll_completed(self) -> List[dict]:
        out = []
        for key in self.store.keys(self.ns + "/done/"):
            if key in self._seen_done:
                continue
            raw = self.store.get(key)
            if raw is None:
                continue
            self._seen_done.add(key)
            out.append(json.loads(raw))
        return out

    def load(self) -> Optional[dict]:
        raw = self.store.get(self.ns + "/load")
        return None if raw is None else json.loads(raw)

    def pending(self) -> bool:
        return False  # the worker pumps itself; run() polls results

    def pump(self, deadline: Optional[Deadline] = None) -> None:
        del deadline  # nothing to drive from here

    def stop(self, deadline: Optional[Deadline] = None) -> None:
        """Ask the worker to exit; reap the process handle if we own
        one (bounded by ``deadline``, default 10s)."""
        dl = Deadline.coerce(deadline)
        try:
            self.store.set(self.ns + "/stop", "1")
        except Exception:  # noqa: BLE001 — store may already be down
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=dl.timeout(10.0, floor=0.1))
            except Exception:  # noqa: BLE001 — still running: kill it
                self.proc.kill()


class ReplicaServer:
    """The replica-side serve loop for process-mode clustering: polls
    request records from the store into a local supervised engine,
    steps it, and publishes results / load / heartbeats. Crash-safe by
    construction — every accepted submission is journaled by the
    supervisor BEFORE it is served, so the router (or a relaunch of
    this worker over the same ``journal_dir``) can always reconstruct
    accepted-but-unfinished work."""

    def __init__(self, store, replica_id: str, engine_factory, *,
                 journal_dir: str, poll_interval: float = 0.02,
                 obs_publish_interval: float = 0.5,
                 **supervisor_kwargs):
        self.store = store
        self.replica_id = str(replica_id)
        self.ns = f"cluster/{self.replica_id}"
        self.poll_interval = float(poll_interval)
        self.supervisor = ServingSupervisor(
            engine_factory, journal_dir=journal_dir, **supervisor_kwargs)
        self._taken: Set[str] = set()
        self._published: Set = set()
        self._hb = 0
        # fleet observability (ISSUE 14): this worker's registry dump +
        # trace ring publish under obs/rep-<id>/ in the SAME store the
        # cluster protocol already shares, rate-limited off the poll loop
        self._obs_pub = _obs_agg.Publisher(
            store, f"rep-{self.replica_id}",
            interval_s=float(obs_publish_interval))

    def _pull(self) -> int:
        """Ingest new request records; returns how many."""
        n = 0
        for key in sorted(self.store.keys(self.ns + "/req/")):
            if key in self._taken:
                continue
            raw = self.store.get(key)
            if raw is None:
                continue
            self._taken.add(key)
            rec = json.loads(raw)
            rid = rec["req_id"]
            if rid in self.supervisor.journaled_ids:
                continue  # a relaunch already replayed this submission
            self.supervisor.submit(
                rid, np.asarray(rec["prompt"], np.int32),
                int(rec["max_new_tokens"]),
                deadline=_remaining_budget(rec),
                priority=rec.get("priority", "interactive"),
                retries=int(rec.get("retries", 0)),
                trace=rec.get("trace"),
                tenant=rec.get("tenant", "default"))
            n += 1
        return n

    def _publish(self) -> None:
        for rid, r in list(self.supervisor.results.items()):
            if rid in self._published:
                continue
            self._published.add(rid)
            self.store.set(f"{self.ns}/done/{rid}", json.dumps(
                _result(rid, r.status, r.out, shed_reason=r.shed_reason)))
        eng = self.supervisor.engine
        d = eng.load().as_dict()
        d["prefix"] = eng.prefix_stats()
        self.store.set(self.ns + "/load", json.dumps(d))
        self._hb += 1
        self.store.set(self.ns + "/hb", str(self._hb))
        self._obs_pub.maybe_publish()
        # tick the local alert rules (ISSUE 15) at the same cadence the
        # registry is published — a replica's own burn-rate / queue
        # alerts fire here and ride the next publication fleet-wide
        # (obs_alerts_fired_total is a registry counter like any other)
        _obs.default_manager().maybe_evaluate(
            min_interval_s=self._obs_pub.interval_s)

    def serve(self, deadline=None) -> None:
        """Serve until ``stop`` is posted or the Deadline runs out.
        Every blocking edge is bounded: store ops carry their own
        per-op budget, idle waits go through ``Deadline.sleep``.

        At exit — normal stop, deadline, or a crash unwinding through
        here — the final registry dump is flushed to the store and, when
        ``CLUSTER_TRACE_DUMP`` names a file, the trace ring is dumped
        there the way ``DISAGG_TRACE_DUMP`` does for disagg workers, so
        cluster-mode runs stitch complete traces."""
        dl = Deadline.coerce(deadline)
        try:
            self._publish()  # first heartbeat: visible before any work
            while not dl.expired():
                if self.store.get(self.ns + "/stop"):
                    break
                took = self._pull()
                if self.supervisor.pending:
                    self.supervisor.step()
                elif not took:
                    if dl.budget is None:
                        time.sleep(self.poll_interval)
                    else:
                        dl.sleep(self.poll_interval)
                self._publish()
            self._publish()
        finally:
            try:
                self._obs_pub.publish()
            except Exception:
                pass  # the store may be the thing that died
            dump_path = os.environ.get("CLUSTER_TRACE_DUMP")
            if dump_path:
                with open(dump_path, "w", encoding="utf-8") as fh:
                    json.dump(_obs.ring().dump(), fh)


# ---------------------------------------------------------------------------
# The router


class ClusterRouter:
    """Route requests across replicas by load + session/prefix
    affinity; recover a dead replica's accepted work onto survivors.

    ``replicas`` is a sequence of transports (:class:`InProcessReplica`
    / :class:`ProcessReplica` / anything with their surface).
    ``block_size`` should match the engines' KV block size — the
    router's prefix trees index block-aligned chunks so its affinity
    estimate predicts the engine-side cache hit exactly.

    Scoring (lower wins)::

        busy     = wq * queue_frac + wkv * kv_occupancy
                 + wd * squash(est_queue_delay_s)
                 + wl * squash(ewma_step_s)
                 + wb * host_blocked_frac
        score    = busy - affinity_weight * prefix_fraction
                        - session_weight  * session_match

    ``host_blocked_frac`` (ISSUE 10) is the replica engine's measured
    fraction of step time spent BLOCKED on device fetches: a host-bound
    replica (sync fetch loop, or an overlap pipeline that degraded to
    draining) services its queue slower than its depth suggests, so it
    scores as busier at equal queue/KV occupancy.

    ``squash(x) = x / (1 + x)`` keeps unbounded seconds-valued signals
    commensurable with the [0, 1] fractions without magic scale
    constants. Ties break toward the replica with fewer routed
    requests, then the lower index — deterministic placement for
    deterministic tests."""

    def __init__(self, replicas: Sequence, *, block_size: int = 16,
                 max_request_retries: int = 2,
                 affinity_weight: float = 1.0,
                 session_weight: float = 1.0,
                 queue_weight: float = 1.0, kv_weight: float = 1.0,
                 delay_weight: float = 1.0, latency_weight: float = 0.25,
                 blocked_weight: float = 0.5,
                 max_prefix_nodes: int = 4096):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self.block_size = int(block_size)
        self.max_request_retries = int(max_request_retries)
        self.affinity_weight = float(affinity_weight)
        self.session_weight = float(session_weight)
        self.queue_weight = float(queue_weight)
        self.kv_weight = float(kv_weight)
        self.delay_weight = float(delay_weight)
        self.latency_weight = float(latency_weight)
        self.blocked_weight = float(blocked_weight)
        self._max_prefix_nodes = int(max_prefix_nodes)
        self._prefix = [PrefixCache(self.block_size,
                                    max_nodes=self._max_prefix_nodes)
                        for _ in self.replicas]
        self._sessions: Dict[str, int] = {}
        # replicas being scaled down: zero-capacity for NEW placements,
        # but session follow-ups still land on them (their KV/prefix
        # state is there) until the autoscaler retires them
        self.draining: Set[int] = set()
        self.inflight: Dict[object, Tuple[dict, int]] = {}
        # accepted records with NO live replica to take them (a total-
        # outage window): parked here, re-placed by every step() until
        # a replica comes back — never silently dropped
        self.orphans: Dict[object, dict] = {}
        self.results: Dict[object, dict] = {}
        self.retries: Dict[object, int] = {}
        self.poisoned_ids: List[object] = []
        self.dead: Set[int] = set()
        self.n_routed = [0] * len(self.replicas)
        self.n_misroutes = 0
        self.n_recoveries = 0
        self.events: List[tuple] = []

    # -- placement -------------------------------------------------------
    def _live(self, exclude: Sequence[int] = ()) -> List[int]:
        return [i for i, rep in enumerate(self.replicas)
                if i not in self.dead and i not in exclude and rep.alive()]

    @staticmethod
    def _squash(x: Optional[float]) -> float:
        x = float(x or 0.0)
        return x / (1.0 + x)

    def _score(self, idx: int, load: Optional[dict], prompt,
               session: Optional[str]) -> float:
        if load is None:
            busy = 1.0  # unknown load: neither favourite nor pariah
        else:
            qlim = load.get("queue_limit") or 16
            busy = (
                self.queue_weight
                * (load.get("queue_depth", 0) / float(qlim))
                + self.kv_weight * float(load.get("kv_occupancy", 0.0))
                + self.delay_weight
                * self._squash(load.get("est_queue_delay_s"))
                + self.latency_weight
                * self._squash(load.get("ewma_step_s"))
                + self.blocked_weight
                * float(load.get("host_blocked_frac", 0.0)))
        affinity = 0.0
        if session is not None and self._sessions.get(session) == idx:
            affinity += self.session_weight
        matched, _ = self._prefix[idx].lookup(prompt)
        affinity += self.affinity_weight * (
            matched / max(len(prompt), 1))
        return busy - affinity

    def route(self, prompt, *, session: Optional[str] = None,
              exclude: Sequence[int] = ()) -> int:
        """Pick a replica for ``prompt``. Raises :class:`NoLiveReplica`
        when nothing is alive. Chaos site ``cluster.route``: a ``drop``
        fault deterministically misroutes to the next live replica —
        the correctness envelope the router tests pin down."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        live = self._live(exclude)
        if not live:
            raise NoLiveReplica(
                f"no live replica ({len(self.replicas)} configured, "
                f"{sorted(self.dead)} dead, {list(exclude)} excluded)")
        # a draining replica is ZERO-capacity for new placements — only
        # a session already pinned to it may follow (its KV/prefix
        # state lives there, and re-placing follow-ups elsewhere would
        # keep the drain from ever finishing the conversation). If the
        # whole fleet is draining, serve anyway: drain is a preference,
        # refusal is an outage.
        cands = [i for i in live if i not in self.draining]
        pinned = self._sessions.get(session) if session is not None \
            else None
        if pinned is not None and pinned in live \
                and pinned in self.draining:
            cands.append(pinned)
        if not cands:
            cands = live
        loads = {i: self.replicas[i].load() for i in cands}
        best = min(cands, key=lambda i: (
            self._score(i, loads[i], prompt, session),
            self.n_routed[i], i))
        if not _chaos.inject("cluster.route"):
            best = live[(live.index(best) + 1) % len(live)]
            self.n_misroutes += 1
        return best

    # -- submission ------------------------------------------------------
    def submit(self, req_id, prompt, max_new_tokens: int = 32, *,
               deadline=None, priority: str = "interactive",
               session: Optional[str] = None, trace=None,
               tenant: str = "default") -> int:
        """Route + dispatch one request; returns the replica index it
        was placed on. Results arrive via :meth:`poll` / :meth:`run`,
        keyed by ``req_id`` — across any number of replica deaths.
        ``trace`` joins an upstream trace; otherwise a fresh one is
        minted here so the replica's admission span parents under this
        ``route`` span. ``tenant`` rides the wire record end-to-end
        (replica admission, journal, requeue-on-death)."""
        with _obs.span("route", parent=_obs.trace_ctx(trace),
                       tid="router", req=str(req_id),
                       tenant=str(tenant)) as sp:
            rec = make_record(
                req_id, prompt, max_new_tokens, deadline=deadline,
                priority=priority, session=session, tenant=tenant,
                retries=self.retries.get(req_id, 0), trace=sp.ctx())
            idx = self.route(rec["prompt"], session=session)
            sp.args["replica"] = self.replicas[idx].replica_id
        self._dispatch(rec, idx)
        return idx

    def _dispatch(self, rec: dict, idx: int) -> None:
        self.replicas[idx].submit(rec)
        self.inflight[rec["req_id"]] = (rec, idx)
        self.n_routed[idx] += 1
        self._prefix[idx].insert(rec["prompt"])
        if rec.get("session"):
            self._sessions[rec["session"]] = idx

    # -- harvest ---------------------------------------------------------
    def poll(self) -> List[dict]:
        """Collect newly completed results from every live replica."""
        new = []
        for i, rep in enumerate(self.replicas):
            if i in self.dead:
                continue
            try:
                done = rep.poll_completed()
            except Exception:  # noqa: BLE001 — a dying replica's store
                continue  # blip; liveness checking owns the verdict
            for rec in done:
                rid = rec["req_id"]
                if rid in self.results:
                    continue
                self.results[rid] = rec
                self.inflight.pop(rid, None)
                new.append(rec)
        return new

    # -- failure handling ------------------------------------------------
    def check_replicas(self) -> List[int]:
        """Liveness sweep; runs recovery for each newly-dead replica.
        Returns the indices recovered this call."""
        recovered = []
        for i, rep in enumerate(self.replicas):
            if i not in self.dead and not rep.alive():
                self.recover_replica(i)
                recovered.append(i)
        return recovered

    def recover_replica(self, idx: int) -> None:
        """Crash-only, replica-level recovery (the supervisor's design
        one level up): harvest anything the dead replica published,
        replay + compact its journal, close already-expired work at
        zero cost, quarantine repeat offenders, and requeue the rest
        onto surviving replicas with only their remaining deadline
        budget. The union of journal-pending and the router's own
        routing table covers the mailed-but-never-pulled window, so an
        accepted request can never be lost between the two."""
        rep = self.replicas[idx]
        self.dead.add(idx)
        self.draining.discard(idx)  # a mid-drain death is just a death
        self.n_recoveries += 1
        try:  # last published results (process replicas: still in store)
            for rec in rep.poll_completed():
                rid = rec["req_id"]
                if rid not in self.results:
                    self.results[rid] = rec
                    self.inflight.pop(rid, None)
        except Exception:  # noqa: BLE001 — the store may be gone too
            pass
        pending: Dict[object, dict] = {}
        if rep.journal_dir is not None:
            journal = Journal(rep.journal_dir)
            pending, completed = journal.replay()
            journal.compact(pending, completed)
            for rid, rec in completed.items():
                if rid not in self.results:
                    self.results[rid] = _result(
                        rid, rec.get("status", "ok"), rec.get("out", []))
                    self.inflight.pop(rid, None)
        # union with the router's table: records mailed to the store
        # the worker never pulled have no journal entry yet
        for rid, (rec, where) in list(self.inflight.items()):
            if where == idx and rid not in pending:
                pending[rid] = rec
        n_requeued = n_poisoned = 0
        for rid, rec in pending.items():
            if rid in self.results:
                continue
            self.inflight.pop(rid, None)
            remaining = _remaining_budget(rec)
            if remaining is not None and remaining <= 0:
                # the budget died with the replica: close at zero cost
                self.results[rid] = _result(rid, "expired")
                continue
            retries = max(self.retries.get(rid, 0),
                          int(rec.get("retries", 0))) + 1
            self.retries[rid] = retries
            if retries > self.max_request_retries:
                self.results[rid] = _result(rid, "poisoned")
                self.poisoned_ids.append(rid)
                n_poisoned += 1
                continue
            new_rec = {k: v for k, v in rec.items() if k != "type"}
            new_rec.setdefault("session", None)
            new_rec["retries"] = retries
            try:
                target = self.route(new_rec["prompt"],
                                    session=new_rec.get("session"),
                                    exclude=(idx,))
            except NoLiveReplica:
                # nobody can take it RIGHT NOW (total outage / every
                # survivor mid-compile): park it — step() retries
                # placement until a replica is live again
                self.orphans[rid] = new_rec
                continue
            self._dispatch(new_rec, target)
            n_requeued += 1
        self.events.append(("replica-dead", rep.replica_id,
                            n_requeued, n_poisoned))

    def _place_orphans(self) -> int:
        """Re-place parked records once replicas are live; returns how
        many found a home (expired orphans close at zero cost)."""
        placed = 0
        for rid, rec in list(self.orphans.items()):
            remaining = _remaining_budget(rec)
            if remaining is not None and remaining <= 0:
                del self.orphans[rid]
                self.results[rid] = _result(rid, "expired")
                continue
            try:
                target = self.route(rec["prompt"],
                                    session=rec.get("session"))
            except NoLiveReplica:
                return placed  # still nobody home; keep them parked
            del self.orphans[rid]
            self._dispatch(rec, target)
            placed += 1
        return placed

    # -- fleet membership (the autoscaler's surface) ---------------------
    def add_replica(self, rep) -> int:
        """Join a freshly spawned replica to the rotation; returns its
        index. The router starts it with an empty prefix tree and zero
        routed count — affinity warms up as traffic lands."""
        self.replicas.append(rep)
        self._prefix.append(PrefixCache(self.block_size,
                                        max_nodes=self._max_prefix_nodes))
        self.n_routed.append(0)
        idx = len(self.replicas) - 1
        self.events.append(("replica-added", rep.replica_id))
        return idx

    def mark_draining(self, idx: int) -> None:
        """Take ``idx`` out of NEW-placement rotation (in-flight work
        and session follow-ups keep landing on it)."""
        idx = int(idx)
        if idx in self.dead:
            raise ValueError(f"replica {idx} is dead, cannot drain")
        self.draining.add(idx)
        self.events.append(("replica-draining",
                            self.replicas[idx].replica_id))

    def clear_draining(self, idx: int) -> None:
        """Cancel a drain (scale-up won the race): back in rotation."""
        self.draining.discard(int(idx))

    def inflight_on(self, idx: int) -> int:
        """Router-table entries currently placed on ``idx``."""
        idx = int(idx)
        return sum(1 for _, where in self.inflight.values()
                   if where == idx)

    def drained(self, idx: int) -> bool:
        """True when a draining replica has quiesced: nothing in the
        routing table points at it and its local queue is empty — safe
        to retire without recovery."""
        idx = int(idx)
        if self.inflight_on(idx):
            return False
        try:
            return not self.replicas[idx].pending()
        except Exception:  # noqa: BLE001 — an unreachable replica is
            return True    # not quiescable; retire falls back to kill

    def retire_replica(self, idx: int, deadline=None) -> None:
        """Remove a QUIESCED draining replica from the fleet: its
        prefix tree is forfeited (the radix state dies with it — the
        cluster-level cache re-warms on the survivors), pinned sessions
        are released for re-placement, and NO recovery runs — a clean
        drain has nothing to recover. A replica that dies mid-drain
        instead goes through :meth:`recover_replica` like any other
        death (journal-∪-table requeue; zero accepted requests lost)."""
        idx = int(idx)
        rep = self.replicas[idx]
        self.draining.discard(idx)
        self.dead.add(idx)
        self._prefix[idx].clear()
        self._sessions = {s: i for s, i in self._sessions.items()
                          if i != idx}
        try:
            rep.stop(deadline=deadline)
        except Exception:  # noqa: BLE001 — already-gone is fine here
            pass
        self.events.append(("replica-retired", rep.replica_id))

    # -- the drive loop --------------------------------------------------
    def step(self) -> List[dict]:
        """One router tick: pump in-process replicas, harvest results,
        sweep liveness (dead replicas recover onto survivors)."""
        for i, rep in enumerate(self.replicas):
            if i not in self.dead:
                rep.pump()
        out = self.poll()
        self.check_replicas()
        if self.orphans:
            self._place_orphans()
        return out

    def run(self, deadline=None, poll_interval: float = 0.02) -> dict:
        """Drive until every submitted request has a result (or the
        Deadline runs out); returns ``{req_id: result-record}``."""
        dl = Deadline.coerce(deadline)
        while (self.inflight or self.orphans) and not dl.expired():
            got = self.step()
            if got:
                continue
            if any(rep.pending() for i, rep in enumerate(self.replicas)
                   if i not in self.dead):
                continue  # local work ready to pump: no sleep
            if dl.budget is None:
                time.sleep(poll_interval)
            else:
                dl.sleep(poll_interval)
        return dict(self.results)

    def stop(self, deadline=None) -> None:
        """Shut every live replica down (bounded per replica)."""
        dl = Deadline.coerce(deadline)
        for i, rep in enumerate(self.replicas):
            if i not in self.dead:
                rep.stop(deadline=dl.sub(fraction=0.5))

    # -- observability ---------------------------------------------------
    def prefix_hit_rate(self) -> float:
        """Cluster-wide engine-side prefix hit rate: cached prompt
        tokens / prompt tokens that entered a slot, summed over live
        replicas (0.0 when none publish prefix stats — e.g. a worker
        that died before its first snapshot)."""
        hit = tot = 0
        for i, rep in enumerate(self.replicas):
            if i in self.dead:
                continue
            pf = ((rep.load() or {}).get("prefix") or {})
            if pf.get("enabled"):
                hit += pf.get("hit_tokens", 0)
                tot += pf.get("hit_tokens", 0) + pf.get(
                    "prefill_tokens", 0)
        return hit / tot if tot else 0.0

    def health(self) -> dict:
        reps = []
        for i, rep in enumerate(self.replicas):
            alive = i not in self.dead and rep.alive()
            entry = {"replica_id": rep.replica_id, "alive": alive,
                     "routed": self.n_routed[i]}
            if alive:
                try:
                    entry["load"] = rep.load()
                except Exception:  # noqa: BLE001 — snapshot best-effort
                    entry["load"] = None
            reps.append(entry)
        return _obs.health_envelope("router", {
            "replicas": reps,
            "dead": sorted(self.dead),
            "draining": sorted(self.draining),
            "inflight": len(self.inflight),
            "orphans": len(self.orphans),
            "results": len(self.results),
            "poisoned": list(self.poisoned_ids),
            "misroutes": self.n_misroutes,
            "recoveries": self.n_recoveries,
            "sessions": len(self._sessions),
            # per-tenant SLO view (ISSUE 14) — in-process replicas only
            # (process replicas' registries live behind obs/agg)
            "tenants": _obs.tenant_slo_table(),
        })
