"""Self-healing supervision for the serving engine: crash-only
recovery without losing accepted work.

A single hung or poison request must not take the serving loop down
with it. The recovery shape follows MegaScale-style in-flight health
checking plus crash-only design: detect a sick step fast (a watchdog
ladder over every ``engine.step()``, reusing the warn → stack-dump →
escalate pattern of ``CommWatchdog`` and the ``Deadline`` budget from
``utils/retries``), then REBUILD instead of untangling — tear the
engine down, construct a fresh one from the same factory, and requeue
every accepted-but-unfinished request. Greedy decoding makes requeued
survivors token-exact: the rebuilt engine reproduces their full output
from scratch, identical to an isolated ``generate()`` run.

Fault taxonomy (what :meth:`ServingSupervisor.step` does per outcome):

- **crash** — ``engine.step()`` raised. Recover in place: fence the old
  engine, rebuild, requeue. Every request in a slot at crash time is
  *blamed* (``retries`` += 1); one whose count exceeds
  ``max_request_retries`` is quarantined with ``status="poisoned"``
  instead of being requeued, so a deterministic engine-killer cannot
  crash-loop the service while healthy requests starve.
- **hang** — the step exceeded ``step_budget``. The stepping thread
  cannot be interrupted, so it is ABANDONED: the old engine is fenced
  (when the thread ever wakes, ``step()`` raises ``EngineFenced``
  before touching anything) and a fresh engine + runner take over.
  With ``escalate="exit"`` the supervisor instead dies loudly
  (``os._exit(124)``) for an external relaunch — the right mode when a
  hang means a wedged device rather than a wedged request.
- **kill / power loss** — the process is gone; in-process recovery is
  impossible by definition. With ``journal_dir`` set, every accepted
  submission and every completion is appended (fsync'd JSONL) to a
  journal; the relaunched supervisor replays it, restores finished
  results, and requeues the rest — accepted work survives the crash.

``health()`` returns a structured snapshot (supervisor state + the
engine's ``load()``) for routers and tests.
"""
from __future__ import annotations

import faulthandler
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..testing import chaos as _chaos
from ..utils.retries import Deadline
from .serving import GenRequest

__all__ = ["ServingSupervisor", "SupervisorGaveUp", "Journal"]


class SupervisorGaveUp(RuntimeError):
    """Too many consecutive failed recoveries — the fault is not a
    request, it is the engine/factory itself; surface it instead of
    crash-looping forever."""


class _StepRunner(threading.Thread):
    """Owns one engine generation. The supervisor triggers steps and
    waits under its own Deadline; a hung generation is abandoned (the
    thread parks itself once retired — or raises ``EngineFenced`` the
    moment the fenced engine is stepped again)."""

    def __init__(self, engine):
        super().__init__(name="paddle_tpu_serving_step", daemon=True)
        self.engine = engine
        self._go = threading.Event()
        self._done = threading.Event()
        self._quit = False
        self.result: Optional[list] = None
        self.error: Optional[BaseException] = None
        self.start()

    def run(self):
        while True:
            # bounded poll so a retired runner always exits
            if not self._go.wait(timeout=0.25):
                if self._quit:
                    return
                continue
            self._go.clear()
            if self._quit:
                return
            try:
                self.result, self.error = self.engine.step(), None
            except BaseException as e:  # noqa: BLE001 — supervisor triages
                self.result, self.error = None, e
            self._done.set()

    def begin(self):
        self.result, self.error = None, None
        self._done.clear()
        self._go.set()

    def wait(self, timeout: float) -> bool:
        return self._done.wait(timeout=timeout)

    def retire(self):
        self._quit = True


class _Journal:
    """Append-only JSONL of accepted submissions and completions.
    Each record is flushed + fsync'd so an ``os._exit``-style death
    loses at most the record being written; replay tolerates a torn
    final line. ``compact()`` (run at every resume) rewrites the file
    to one record per live request — relaunch cost is bounded by the
    CURRENT workload, not the lifetime request history. Long-term
    retention of completed results beyond a relaunch cycle is the
    operator's policy, not the journal's. ``req_id``s must be
    JSON-serializable."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "serving-journal.jsonl")

    def replay(self) -> Tuple[Dict[object, dict], Dict[object, dict]]:
        pending: Dict[object, dict] = {}
        completed: Dict[object, dict] = {}
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return pending, completed
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from a mid-append death
            rid = rec.get("req_id")
            if rec.get("type") == "submit":
                pending[rid] = rec
            elif rec.get("type") == "complete":
                completed[rid] = rec
                pending.pop(rid, None)
        return pending, completed

    def compact(self, pending: Dict[object, dict],
                completed: Dict[object, dict]) -> None:
        """Atomically rewrite the journal from a replay result: drops
        torn lines, superseded duplicates, and any bloat a long first
        life accumulated."""
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                for rec in list(pending.values()) + list(completed.values()):
                    f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            # compaction is an optimization: the append-only file is
            # still the source of truth if the rewrite fails
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _append(self, rec: dict):
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def submit(self, req: GenRequest):
        # the deadline is journaled as an ABSOLUTE wall-clock expiry:
        # a relaunch grants the request only its REMAINING budget (a
        # client that timed out during the outage must not have full
        # prefill+decode tokens spent on it). Deadlines on virtual test
        # clocks serialize approximately — wall time is the only clock
        # two processes share.
        expires = None
        if req.deadline is not None and req.deadline.budget is not None:
            expires = time.time() + req.deadline.remaining()
        self._append({
            "type": "submit", "req_id": req.req_id,
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "priority": req.priority,
            "tenant": req.tenant,
            "deadline_unix": expires,
            # prior recoveries travel WITH the request: a cluster
            # router replaying this journal onto a surviving replica
            # must count engine deaths per request, not per replica
            "retries": int(req.retries),
        })

    def complete(self, req: GenRequest):
        self._append({
            "type": "complete", "req_id": req.req_id,
            "status": req.status, "out": [int(t) for t in req.out],
        })


class ServingSupervisor:
    """Run a :class:`ContinuousBatchingEngine` under a step watchdog
    with crash-only recovery.

    ``engine_factory`` is a zero-arg callable returning a fresh engine
    over the same model/config — recovery calls it again. Completed
    requests are harvested into ``results`` every step, so they survive
    any number of engine teardowns.
    """

    def __init__(self, engine_factory: Callable[[], object], *,
                 step_budget: Optional[float] = None,
                 warn_fraction: float = 0.5,
                 dump_fraction: float = 0.75,
                 dump_stacks: bool = True,
                 warmup_budget: Optional[float] = 120.0,
                 warmup_max_steps: int = 64,
                 max_request_retries: int = 2,
                 max_consecutive_failures: int = 8,
                 journal_dir: Optional[str] = None,
                 escalate: str = "rebuild"):
        if escalate not in ("rebuild", "exit"):
            raise ValueError("escalate must be 'rebuild' or 'exit'")
        if not 0.0 < warn_fraction <= dump_fraction <= 1.0:
            raise ValueError(
                "need 0 < warn_fraction <= dump_fraction <= 1")
        self._factory = engine_factory
        self.step_budget = None if step_budget is None else float(step_budget)
        self.warn_fraction = float(warn_fraction)
        self.dump_fraction = float(dump_fraction)
        self.dump_stacks = bool(dump_stacks)
        # until the engine reports ``warmed_up`` — every compiled phase
        # dispatched at least once — steps run under the roomy
        # ``warmup_budget`` instead of ``step_budget``: phases
        # jit-compile lazily at their FIRST dispatch (chunked mode
        # compiles decode many steps after step 1), warn/dump/hang
        # would misfire on legitimate compile latency, and each
        # recovery re-jits so the cascade would be unrecoverable. The
        # warmup budget stays FINITE so a permanently wedged dispatch —
        # which also keeps the model's exec lock and therefore stalls
        # every replacement's first step — ends in SupervisorGaveUp
        # instead of an invisible deadlock; None opts into unbounded
        # warmup.
        self.warmup_budget = (None if warmup_budget is None
                              else float(warmup_budget))
        if (self.warmup_budget is not None and step_budget is not None
                and self.warmup_budget < float(step_budget)):
            self.warmup_budget = float(step_budget)
        # ...and the grace is itself bounded: a workload that never
        # dispatches some phase (max_new_tokens=1 never decodes) must
        # not leave hang detection at the roomy budget forever — after
        # warmup_max_steps GRANTS of the roomy budget per incarnation
        # the strict budget applies regardless
        self.warmup_max_steps = int(warmup_max_steps)
        self.max_request_retries = int(max_request_retries)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.escalate = escalate
        self.results: Dict[object, GenRequest] = {}
        self.poisoned_ids: List[object] = []
        self.restarts = 0
        # shed/expired counters accumulated from RETIRED engine
        # incarnations (each rebuild starts a fresh engine whose own
        # counters begin at zero; health() reports the running totals
        # so alerting never sees a reset at exactly the wrong moment)
        self._prior_shed = {"interactive": 0, "batch": 0}
        self._prior_expired = 0
        self.events: List[tuple] = []  # (kind, detail) observability log
        self._failures = 0  # consecutive recoveries without progress
        self._journaled_done: set = set()
        self.journal = None if journal_dir is None else _Journal(journal_dir)
        self.journaled_ids: set = set()
        # highest retries value journaled per id: lets a mailbox-fed
        # server distinguish a stale re-read of a consumed submission
        # (same retries — skip) from a router REQUEUE of work this
        # worker already served (router bumps retries — accept)
        self.journaled_retries: Dict[object, int] = {}
        # warmup-budget grants this incarnation (vs engine.steps: a
        # role engine's missing phase — e.g. a decode_only worker's
        # colocated-fallback prefill — can first compile long after
        # step warmup_max_steps, and must still get the compile grace)
        self._warmup_grants = 0
        self.engine = engine_factory()
        self._runner = _StepRunner(self.engine)
        if self.journal is not None:
            self._resume_from_journal()

    # -- journal resume -------------------------------------------------
    def _resume_from_journal(self):
        pending, completed = self.journal.replay()
        self.journal.compact(pending, completed)
        self.journaled_ids = set(pending) | set(completed)
        for rid, rec in list(pending.items()) + list(completed.items()):
            self.journaled_retries[rid] = max(
                self.journaled_retries.get(rid, 0),
                int(rec.get("retries", 0)))
        for rid, rec in completed.items():
            req = GenRequest(rid, np.zeros(0, np.int32))
            req.status, req.out = rec.get("status", "ok"), rec.get("out", [])
            self.results[rid] = req
            self._journaled_done.add(rid)
            if req.status == "poisoned":
                self.poisoned_ids.append(rid)
        for rid, rec in pending.items():
            expires = rec.get("deadline_unix")
            remaining = None if expires is None else expires - time.time()
            req = GenRequest(
                rid, np.asarray(rec["prompt"], np.int32),
                int(rec["max_new_tokens"]),
                deadline=None if remaining is None else Deadline(remaining),
                priority=rec.get("priority", "interactive"),
                tenant=rec.get("tenant", "default"),
                retries=int(rec.get("retries", 0)))
            if remaining is not None and remaining <= 0:
                # the budget ran out during the outage: close it as
                # expired at zero token cost instead of serving a
                # client that already gave up
                req.status = "expired"
                self._finish(req)
                continue
            # accepted in a previous life: a relaunch must not re-run
            # admission control over work the front door already took
            self.engine.requeue(req)
        # requeue sheds work this engine can never serve (e.g. the
        # relaunch shrank the pool): close those journal entries now
        for r in self.engine.drain_shed():
            self._finish(r)
        if pending or completed:
            self.events.append(("resume", len(pending), len(completed)))

    # -- submission -----------------------------------------------------
    def submit(self, req_id, prompt, max_new_tokens: int = 32, *,
               deadline=None, priority: str = "interactive",
               retries: int = 0, trace=None,
               tenant: str = "default") -> GenRequest:
        """Front door: runs the engine's admission control. Shed
        submissions are recorded as results immediately; accepted ones
        are journaled (when journaling) so a crash cannot lose them.
        ``retries`` seeds the recovery counter for work resubmitted by
        a cluster router after another replica's death.

        The returned handle reflects the SUBMISSION (status at the
        front door, shed_reason). Do not poll it for completion across
        recoveries: a rebuild requeues detached clones, so the final
        state of every request lives in ``results`` / ``run()``'s
        return value, keyed by ``req_id``."""
        req = self.engine.add_request(
            req_id, prompt, max_new_tokens, deadline=deadline,
            priority=priority, retries=retries, trace=trace,
            tenant=tenant)
        self.journaled_ids.add(req_id)
        self.journaled_retries[req_id] = max(
            self.journaled_retries.get(req_id, 0), int(retries))
        if req.status != "shed" and self.journal is not None:
            self.journal.submit(req)
        # harvest every shed this submission caused: the request itself
        # and/or a queue-full displacement VICTIM that was accepted
        # earlier — victims never appear in a step() return, and
        # leaving their journal entry pending would make a relaunch
        # re-execute work the front door shed
        for r in self.engine.drain_shed():
            self._finish(r)
        return req

    def _finish(self, req: GenRequest):
        self.results[req.req_id] = req
        if self.journal is not None and req.req_id not in self._journaled_done:
            self._journaled_done.add(req.req_id)
            self.journal.complete(req)

    # -- disaggregated-serving hooks ------------------------------------
    def submit_imported(self, req: GenRequest) -> None:
        """Journal a request that entered the engine OUTSIDE the front
        door (a disagg KV import bypasses ``add_request``): a relaunch
        of this decode worker replays it and — the KV pages having died
        with the process — serves it by colocated re-prefill,
        token-exact. No-op without a journal."""
        self.journaled_ids.add(req.req_id)
        self.journaled_retries[req.req_id] = max(
            self.journaled_retries.get(req.req_id, 0), int(req.retries))
        if self.journal is not None:
            self.journal.submit(req)

    def mark_transferred(self, req: GenRequest) -> None:
        """Close a prefill-role request's journal entry once its KV
        handoff was ACKED: ownership moved to the decode pool, so a
        relaunch of THIS worker must not re-prefill it (the router's
        own table still covers a later decode-side death). Recorded as
        a ``complete`` with status "transferred" — routers treat that
        status as a baton pass, not a final result."""
        if self.journal is not None \
                and req.req_id not in self._journaled_done:
            self._journaled_done.add(req.req_id)
            was = req.status
            req.status = "transferred"
            self.journal.complete(req)
            req.status = was

    # -- the supervised loop --------------------------------------------
    @property
    def pending(self) -> bool:
        return bool(self.engine._queue or self.engine.num_active)

    def _step_budget(self):
        """Strict ``step_budget``, or ``warmup_budget`` while compiled
        phases are still missing. Counted in GRANTS, not engine steps:
        a role engine's missing phase (e.g. a decode_only worker's
        colocated-fallback prefill) can first compile thousands of
        steps in, and must still get the compile grace — while the
        grant cap keeps a permanently wedged dispatch escalating."""
        budget = self.step_budget
        if (budget is not None and not self.engine.warmed_up
                and self._warmup_grants < self.warmup_max_steps):
            self._warmup_grants += 1
            budget = self.warmup_budget
        return budget

    def step(self) -> list:
        """One supervised engine iteration: run ``engine.step()`` on
        the runner thread, escalate warn → dump → recover at fractions
        of ``step_budget`` (the CommWatchdog ladder under the step's
        Deadline), and triage any raise as an engine failure."""
        if not _chaos.inject("serving.loop"):
            return []  # dropped supervisor tick
        runner = self._runner
        budget = self._step_budget()
        dl = Deadline(budget)
        runner.begin()
        stages = ((self.warn_fraction, "warn"),
                  (self.dump_fraction, "dump"), (1.0, "hung"))
        si = 0
        finished = False
        while not finished:
            if budget is None:
                finished = runner.wait(timeout=dl.timeout(60.0))
                continue
            target = budget * stages[si][0]
            span = max(target - dl.elapsed(), 0.001)
            finished = runner.wait(timeout=span)
            if finished:
                break
            stage = stages[si][1]
            si += 1
            age = dl.elapsed()
            if stage == "warn":
                self._note("warn", f"step at {age:.3f}s of "
                                   f"{budget:.3f}s budget")
            elif stage == "dump":
                self._note("dump", f"step at {age:.3f}s — dumping stacks")
                if self.dump_stacks:
                    faulthandler.dump_traceback(
                        all_threads=True, file=sys.stderr)
                    # disagg: a decode-worker hang is only debuggable
                    # against the PREFILL side's schedule — with a
                    # handoff contract attached, the flight-recorder
                    # dump names BOTH roles' recorded schedules
                    try:
                        from ..distributed.communication import (
                            flight_recorder as _fr,
                        )

                        _fr.dump_on_watchdog(sys.stderr)
                    except Exception:  # noqa: BLE001 — diagnostics only
                        pass
            else:  # hung: the full budget elapsed
                self._note("hung", f"step exceeded its {budget:.3f}"
                                   "s budget")
                if self.escalate == "exit":
                    sys.stderr.write(
                        "ServingSupervisor: step hung; exiting 124 for "
                        "external relaunch\n")
                    sys.stderr.flush()
                    os._exit(124)
                return self._recover(reason="hang", exc=None)
        if runner.error is not None:
            return self._recover(reason="crash", exc=runner.error)
        self._failures = 0
        out = runner.result or []
        for r in out:
            self._finish(r)
        return out

    def run(self, max_steps: int = 100_000) -> Dict[object, GenRequest]:
        """Drive the engine until idle (or ``max_steps``); returns the
        harvested ``{req_id: GenRequest}`` across every engine
        incarnation — shed, expired, poisoned and ok alike."""
        while self.pending and max_steps > 0:
            self.step()
            max_steps -= 1
        # safety net: anything that completed outside a step() return
        # (e.g. shed between steps) still lands in the result map
        for r in self.engine.drain_shed():
            self._finish(r)
        for r in list(self.engine._completed.values()):
            if r.req_id not in self.results:
                self._finish(r)
        return dict(self.results)

    # -- recovery -------------------------------------------------------
    def _recover(self, *, reason: str, exc: Optional[BaseException]) -> list:
        eng = self.engine
        eng.fence()
        # snapshot the async pipeline's in-flight depth AT the fence:
        # entries dispatched but never harvested die with this engine —
        # their rows' requests are still visible in the slot snapshot
        # below (a slot stays bound until its tokens are harvested), so
        # the requeue replays them from scratch, token-exact; the depth
        # is recorded so operators can see a crash landed mid-pipeline
        inflight_dispatches = len(getattr(eng, "_ring", ()))
        self._runner.retire()
        self.restarts += 1
        self._failures += 1
        if self._failures > self.max_consecutive_failures:
            raise SupervisorGaveUp(
                f"{self._failures} consecutive failed recoveries "
                f"(last: {reason})") from exc
        # Iterate SNAPSHOTS throughout: a hang-path step thread may
        # still be finishing inside the old engine concurrently (the
        # fence stops it at the next checkpoint, not instantaneously),
        # and a live dict/slot must not be read while it mutates.
        # Snapshot order matters: queue first, slots second, completed
        # LAST — a request can only move forward (queue → slot →
        # completed), so this order can DUPLICATE a request mid-
        # transition but never lose one; duplicates are dropped below.
        queued_snap = list(eng._queue)
        inflight_snap = [r for r in [s.req for s in eng._slots]
                         if r is not None]
        # prefill-role engines park finished prefills handoff-ready
        # (out of both queue and slots) until the handoff layer drains
        # them: their KV dies with this engine, so they recover exactly
        # like in-flight work — requeued for a fresh prefill
        inflight_snap += list(getattr(eng, "_handoff_ready", {}).values())
        # harvest whatever completed before the fault (incl. shed and
        # expired requests only present in the engine's map)
        harvested = set()
        for req in list(eng._completed.values()):
            harvested.add(req.req_id)
            if req.req_id not in self.results:
                self._finish(req)
        # DETACH by cloning: the old engine (and a possibly-still-hung
        # step thread inside it) keeps its own request objects — any
        # late mutation lands on orphans, never on the requests the
        # replacement engine now owns
        inflight = [self._clone(r) for r in inflight_snap
                    if r.req_id not in harvested]
        inflight_ids = {r.req_id for r in inflight}
        queued = [self._clone(r) for r in queued_snap
                  if r.req_id not in harvested
                  and r.req_id not in inflight_ids]
        survivors = []
        for req in inflight:
            req.retries += 1
            if req.retries > self.max_request_retries:
                # this request was in a slot for every one of its
                # retries + 1 engine deaths: quarantine it
                req.status = "poisoned"
                self.poisoned_ids.append(req.req_id)
                self._finish(req)
            else:
                survivors.append(req)
        detail = (f"{reason}: restart #{self.restarts}, requeue "
                  f"{len(survivors)} in-flight + {len(queued)} queued, "
                  f"poisoned {len(inflight) - len(survivors)}")
        if inflight_dispatches:
            detail += (f", {inflight_dispatches} un-harvested pipeline "
                       "dispatch(es) dropped")
        if exc is not None:
            detail += f" ({exc!r})"
        self._note("recover", detail)
        for k, v in eng.n_shed.items():
            self._prior_shed[k] = self._prior_shed.get(k, 0) + v
        self._prior_expired += eng.n_expired
        self.engine = self._factory()
        self._runner = _StepRunner(self.engine)
        self._warmup_grants = 0  # fresh incarnation: fresh compile grace
        for req in survivors:  # longest-waiting work first
            self.engine.requeue(req)
        for req in queued:
            self.engine.requeue(req)
        # requeue sheds work the rebuilt engine can never serve (a
        # factory whose config shrank) — close those out here: they
        # enter _completed between steps, so no step() would ever
        # return them
        for r in self.engine.drain_shed():
            self._finish(r)
        return []

    @staticmethod
    def _clone(req: GenRequest) -> GenRequest:
        """A fresh GenRequest carrying the submission (identity, prompt,
        budget, class, retry count) but none of the old engine's
        generation state — ``requeue`` resets that anyway; what matters
        is the fresh OBJECT, so the orphaned engine cannot reach it."""
        return GenRequest(
            req.req_id, req.prompt, req.max_new_tokens,
            deadline=req.deadline, t_submit=req.t_submit,
            priority=req.priority, retries=req.retries,
            clamped=req.clamped, tenant=req.tenant,
            trace_id=req.trace_id, span_id=req.span_id)

    def _note(self, kind: str, detail: str):
        self.events.append((kind, detail))
        # watchdog/recovery escalations land on the obs timeline as
        # instant events beside the request spans (ISSUE 12)
        _obs.instant(f"supervisor_{kind}", tid="supervisor",
                     detail=detail)
        if kind in ("warn", "dump", "hung"):
            sys.stderr.write(f"ServingSupervisor: {detail}\n")

    # -- health surface -------------------------------------------------
    def health(self) -> dict:
        """Structured snapshot for routers/probes: supervisor state,
        restart/poison counts, and the live engine load signal. Wrapped
        in the shared, registry-sourced :func:`paddle_tpu.obs
        .health_envelope` (``schema_version``/``kind``/...), so every
        health() surface carries the same top-level keys."""
        status_counts: Dict[str, int] = {}
        for r in self.results.values():
            status_counts[r.status] = status_counts.get(r.status, 0) + 1
        eng = self.engine
        return _obs.health_envelope("supervisor", {
            "state": "serving" if self.pending else "idle",
            "restarts": self.restarts,
            "consecutive_failures": self._failures,
            "poisoned": list(self.poisoned_ids),
            "completed": status_counts,
            "step_budget_s": self.step_budget,
            "last_step_s": eng.last_step_s,
            "journaling": self.journal is not None,
            # running totals across every engine incarnation (the
            # current engine's load() counters restart at each rebuild)
            "total_shed": {
                k: self._prior_shed.get(k, 0) + eng.n_shed.get(k, 0)
                for k in set(self._prior_shed) | set(eng.n_shed)},
            "total_expired": self._prior_expired + eng.n_expired,
            "load": eng.load().as_dict(),
            # async-pipeline occupancy (ISSUE 10): zeros/disabled on
            # engines without the overlap machinery
            "overlap": (eng.overlap_stats()
                        if hasattr(eng, "overlap_stats")
                        else {"enabled": False}),
            # per-tenant SLO view (ISSUE 14): one hot tenant's pain is
            # visible here instead of averaged into the fleet totals
            "tenants": _obs.tenant_slo_table(),
        })


# Public alias: the cluster router (inference/cluster.py) replays a dead
# replica's journal through the same reader/compactor the in-process
# resume path uses — one journal format, two recovery scopes.
Journal = _Journal
