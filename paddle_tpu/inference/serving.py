"""Continuous batching over the paged KV cache.

The serving loop paged attention exists for (ref:
python/paddle/incubate/nn/functional/block_multihead_attention.py —
the reference's serving kernel keeps per-sequence block tables exactly
so sequences can join and leave a running batch): a fixed pool of HBM
blocks, a fixed number of batch slots, requests admitted as slots and
blocks free up, finished sequences evicted and their blocks recycled.

TPU-native design (single compiled program per phase, static shapes):

- ONE decode program serves every engine iteration: tokens [B],
  per-layer pools, block tables [B, max_blocks], per-sequence
  ``cache_len`` [B] (the scalar-or-[B] contract of
  ops/paged_attention.py). Slot membership changes only change the
  TABLE CONTENTS and lengths — never shapes — so XLA compiles once.
- ONE prefill program per static width admits prompt tokens into a
  slot: rows not participating have their table pointed entirely at a
  reserved TRASH block, so their scattered writes land in a sacrificial
  page and live sequences are untouched (the positions a padded prompt
  writes past its real length are overwritten by later decode steps
  before they are ever attended).
- ``BlockManager`` (ops/paged_attention.py) is the allocator; eviction
  returns a sequence's blocks to the free list, and the next admission
  may reuse them immediately — correctness is guaranteed by the tables
  alone, which is what the eviction test pins down.

Two prefill policies:

- Whole-prompt (default, ``prefill_chunk=None``): admission runs ONE
  padded prefill of width ``prompt_pad`` — the Orca-style baseline. A
  long prompt stalls every in-flight decode for its full prefill.
- CHUNKED (``prefill_chunk=K``, Sarathi-Serve-style): prompts split
  into K-token chunks, each chunk writing its KV at the slot's current
  ``cache_len`` offset through the same block tables (the compiled
  prefill program is width-polymorphic via retrace — one cached XLA
  program per chunk width, nonzero per-row offsets drive RoPE and the
  causal mask). Every engine step schedules at most
  ``max_num_batched_tokens`` REAL tokens: the running decode batch
  first (decode-priority, so inter-token latency stays flat), then
  prefill chunks round-robin across prefilling slots for fairness.
  Admission switches from whole-prompt-fits-``prompt_pad`` to
  token-budget pacing + block availability (full prompt+budget block
  reservation up front, so a mid-prefill slot can never deadlock on
  allocation). Deadline eviction works mid-prefill: a partially
  prefilled slot's blocks recycle immediately.

Greedy decoding (temperature 0) — matching models.generation.generate's
default — so engine outputs are token-identical to isolated generate()
runs, which is the correctness contract the tests assert.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import no_grad
from ..base.tensor import Tensor
from ..ops.paged_attention import BlockManager, PagedLayerCache
from ..testing import chaos as _chaos
from ..utils.retries import Deadline

__all__ = ["GenRequest", "ContinuousBatchingEngine"]


@dataclass
class GenRequest:
    """One generation request (ref: the reference's serving request —
    prompt ids + budget). ``deadline`` is the request's wall-clock
    budget: admission rejects it once expired, and an in-flight slot is
    EVICTED when it expires mid-decode or MID-PREFILL — one
    stuck/abandoned client can never pin a slot (its blocks recycle
    immediately). ``status`` is "ok" for a normally finished request,
    "expired" for a rejected or evicted one (whatever tokens were
    produced stay in ``out``). ``times[i]`` is the perf_counter stamp
    when ``out[i]`` was produced; with ``t_submit`` it gives
    time-to-first-token and inter-token latencies for free."""

    req_id: object
    prompt: np.ndarray  # [s] int
    max_new_tokens: int = 32
    out: List[int] = field(default_factory=list)
    deadline: Optional[Deadline] = None
    status: str = "ok"
    t_submit: float = 0.0
    times: List[float] = field(default_factory=list)

    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    def ttft(self) -> Optional[float]:
        """Seconds from submission to the first token (None if none)."""
        return self.times[0] - self.t_submit if self.times else None

    def inter_token_latencies(self) -> List[float]:
        return [b - a for a, b in zip(self.times, self.times[1:])]


class _Slot:
    __slots__ = ("req", "cache_len", "remaining", "prefill_pos")

    def __init__(self):
        self.req: Optional[GenRequest] = None
        self.cache_len = 0
        self.remaining = 0
        self.prefill_pos = 0  # prompt tokens written to KV so far

    @property
    def active(self):
        return self.req is not None

    @property
    def prefilling(self):
        return self.req is not None and self.prefill_pos < self.req.prompt.size


class ContinuousBatchingEngine:
    def __init__(self, model, *, max_batch: int, max_len: int,
                 block_size: int = 64, num_blocks: int,
                 prompt_pad: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 decode_chunk: int = 1,
                 prefill_chunk: Optional[int] = None,
                 max_num_batched_tokens: Optional[int] = None):
        """``num_blocks`` fixes the HBM budget (the pool allocates one
        extra trash block); ``max_len`` bounds any sequence's positions
        (tables carry ceil(max_len/block_size) slots per row);
        ``prompt_pad`` is the static whole-prompt prefill width
        (default: one block; unused once chunking is on).

        ``decode_chunk=K`` scans K decode steps in ONE device dispatch
        (lax.scan; tokens + eos state carried on device — the
        generate(decode_chunk=K) idiom) whenever every active slot has
        at least K tokens of budget left; otherwise the engine falls
        back to single steps. Admissions happen between chunks. With a
        token budget the scan additionally requires no slot to be
        mid-prefill and active*K to fit the budget.

        ``prefill_chunk=C`` turns on chunked prefill: prompts (up to
        ``max_len - max_new_tokens``, no longer capped by
        ``prompt_pad``) are fed C tokens per scheduled chunk.
        ``max_num_batched_tokens`` caps the REAL tokens any engine step
        processes (default ``max_batch + prefill_chunk``: one full
        decode round plus one chunk). It must cover a full decode round
        (>= max_batch — the decode dispatch is indivisible) and one
        chunk (>= prefill_chunk — otherwise a lone prefill could never
        be scheduled).
        """
        self.model = model
        self.B = int(max_batch)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        self.prompt_pad = int(prompt_pad or block_size)
        if self.prompt_pad > self.max_len:
            raise ValueError("prompt_pad exceeds max_len")
        # generation parity: generate() refuses positions beyond the
        # model's limit — the engine serves the same contract instead
        # of silently extrapolating RoPE past it
        limit = getattr(getattr(model, "config", None),
                        "max_position_embeddings", None)
        if limit is not None and self.max_len > limit:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"max_position_embeddings ({limit})")
        self.eos_token_id = eos_token_id
        self.manager = BlockManager(num_blocks, block_size)
        self._trash = num_blocks  # reserved sacrificial pool row
        self.max_blocks_per_seq = -(-self.max_len // block_size)

        self.prefill_chunk = None if prefill_chunk is None \
            else int(prefill_chunk)
        if self.prefill_chunk is not None:
            if not 0 < self.prefill_chunk <= self.max_len:
                raise ValueError(
                    f"prefill_chunk must be in [1, max_len={self.max_len}], "
                    f"got {self.prefill_chunk}")
            if max_num_batched_tokens is None:
                max_num_batched_tokens = self.B + self.prefill_chunk
            self.max_num_batched_tokens = int(max_num_batched_tokens)
            floor = max(self.B, self.prefill_chunk)
            if self.max_num_batched_tokens < floor:
                raise ValueError(
                    f"max_num_batched_tokens={self.max_num_batched_tokens} "
                    f"must be >= max(max_batch, prefill_chunk)={floor}: a "
                    "decode round is one indivisible dispatch and a lone "
                    "prefill must be able to schedule one chunk")
        else:
            self.max_num_batched_tokens = None  # whole-prompt: unbudgeted

        was_training = model.training
        model.eval()
        self._restore_training = was_training
        caches = model.init_cache(
            self.B, self.max_len, block_size=block_size,
            num_blocks=num_blocks + 1,
            tables=np.full((self.B, self.max_blocks_per_seq), self._trash,
                           np.int32),
        )
        self._pools = [(c.k_pool._data, c.v_pool._data) for c in caches]
        self._tables = np.full(
            (self.B, self.max_blocks_per_seq), self._trash, np.int32)
        self._slots = [_Slot() for _ in range(self.B)]
        self._queue: List[GenRequest] = []
        self._completed: Dict[object, GenRequest] = {}
        self._params = list(model.parameters())
        self._prefill_jit = None
        self._decode_jit = None
        self._chunk_jit = None
        self.decode_chunk = max(1, int(decode_chunk))
        self._rr = 0  # round-robin start for chunk scheduling fairness
        self.steps = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.last_step_tokens = 0
        self.max_step_tokens = 0

    # -- compiled phases -------------------------------------------------
    def _caches_from(self, pools, tables_arr):
        t = Tensor(tables_arr, _internal=True)
        return [
            PagedLayerCache(Tensor(k, _internal=True),
                            Tensor(v, _internal=True), t, False)
            for k, v in pools
        ]

    def _build_jits(self):
        model, params = self.model, self._params

        def prefill(param_arrays, pools, ids, tables, cache_len):
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(ids, _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            toks = jnp.argmax(logits._data, axis=-1)  # [B, s_pad]
            return toks, [(c.k_pool._data, c.v_pool._data)
                          for c in new_caches]

        def decode(param_arrays, pools, tok, tables, cache_len):
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(tok[:, None], _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            nxt = jnp.argmax(logits._data[:, -1], axis=-1).astype(jnp.int32)
            return nxt, [(c.k_pool._data, c.v_pool._data)
                         for c in new_caches]

        def decode_chunk(param_arrays, pools, tok, tables, cache_len,
                         finished):
            for p, a in zip(params, param_arrays):
                p._data = a
            eos = self.eos_token_id

            def body(carry, _):
                t, pl, cl, fin = carry
                with no_grad():
                    caches = self._caches_from(pl, tables)
                    logits, new_caches = model.forward_with_cache(
                        Tensor(t[:, None], _internal=True), caches,
                        Tensor(cl, _internal=True))
                nxt = jnp.argmax(
                    logits._data[:, -1], axis=-1).astype(jnp.int32)
                if eos is not None:
                    nxt = jnp.where(fin, eos, nxt)
                    fin = fin | (nxt == eos)
                new_pl = [(c.k_pool._data, c.v_pool._data)
                          for c in new_caches]
                return (nxt, new_pl, cl + 1, fin), nxt

            (t, pl, cl, fin), toks = jax.lax.scan(
                body, (tok, pools, cache_len, finished), None,
                length=self.decode_chunk)
            return toks, pl  # toks: [K, B]

        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(decode, donate_argnums=(1,))
        self._chunk_jit = jax.jit(decode_chunk, donate_argnums=(1,))

    def _run_jit(self, jit_fn, *args):
        """Invoke a compiled phase with the params' CURRENT host arrays
        (weight updates after engine construction are served) and
        restore them afterwards: the traced body writes tracers into
        p._data; leaving them there would leak tracers into the next
        eager/jit use."""
        current = [p._data for p in self._params]
        try:
            return jit_fn(current, *args)
        finally:
            for p, a in zip(self._params, current):
                p._data = a

    # -- public API ------------------------------------------------------
    @property
    def chunked(self) -> bool:
        return self.prefill_chunk is not None

    def add_request(self, req_id, prompt, max_new_tokens: int = 32,
                    deadline=None):
        """``deadline``: seconds or a ``Deadline`` — the request's total
        budget (queue wait included). None = no deadline."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt length 0 not in [1, ...]")
        if not self.chunked and prompt.size > self.prompt_pad:
            raise ValueError(
                f"prompt length {prompt.size} not in [1, prompt_pad="
                f"{self.prompt_pad}] (enable prefill_chunk to serve "
                "prompts beyond the whole-prompt pad)")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        dl = None if deadline is None else Deadline.coerce(deadline)
        req = GenRequest(req_id, prompt, max_new_tokens, deadline=dl,
                         t_submit=time.perf_counter())
        if self._blocks_needed(req) > self.manager.num_blocks:
            raise ValueError(
                f"request needs {self._blocks_needed(req)} blocks but the "
                f"pool only has {self.manager.num_blocks} — it could never "
                "be admitted")
        self._queue.append(req)

    def _append_token(self, req: GenRequest, tok: int):
        req.out.append(tok)
        req.times.append(time.perf_counter())

    def _expire(self, req: GenRequest):
        req.status = "expired"
        self._completed[req.req_id] = req

    def _evict_expired(self):
        """Reclaim slots whose request's deadline passed: free the
        blocks, point the row at the trash block, surface the request as
        completed-with-status-expired. Works mid-prefill too — a
        partially prefilled slot's blocks recycle the same way (the
        trash table makes the half-written KV unreachable)."""
        for slot_idx, slot in enumerate(self._slots):
            if slot.active and slot.req.expired():
                self.manager.free_sequence(slot.req.req_id)
                self._tables[slot_idx] = self._trash
                self._expire(slot.req)
                slot.req = None

    @property
    def num_active(self):
        return sum(s.active for s in self._slots)

    @property
    def num_prefilling(self):
        return sum(s.prefilling for s in self._slots)

    def _blocks_needed(self, req):
        if self.chunked:
            total = int(req.prompt.size) + req.max_new_tokens
        else:
            total = max(int(req.prompt.size) + req.max_new_tokens,
                        self.prompt_pad)
        return self.manager.blocks_for(total)

    def _admit(self) -> int:
        """Fill free slots from the queue while blocks last. Whole-
        prompt mode runs one padded prefill per admission (per-slot
        isolation via the trash table); chunked mode only binds the
        slot and reserves its full block budget — the token-budget
        scheduler feeds the prompt in chunks. Returns the number of
        real tokens processed (whole-prompt admissions only)."""
        used = 0
        for slot_idx, slot in enumerate(self._slots):
            # admission rejects requests whose budget already expired
            # while queued (the client gave up; don't burn a prefill)
            while self._queue and self._queue[0].expired():
                self._expire(self._queue.pop(0))
            if not self._queue or slot.active:
                continue
            req = self._queue[0]
            total = self._blocks_needed(req) * self.block_size
            if not self.manager.can_allocate(req.req_id, total):
                break  # head-of-line; keep FIFO fairness
            self._queue.pop(0)
            blocks = self.manager.allocate(req.req_id, total)
            row = np.full((self.max_blocks_per_seq,), self._trash, np.int32)
            row[: len(blocks)] = blocks
            self._tables[slot_idx] = row
            slot.req = req
            slot.remaining = req.max_new_tokens

            if self.chunked:
                slot.prefill_pos = 0
                slot.cache_len = 0
                continue

            slot.prefill_pos = int(req.prompt.size)
            slot.cache_len = int(req.prompt.size)
            # isolated prefill: only this row's table points at real
            # blocks; every other row scatters into the trash block
            iso = np.full_like(self._tables, self._trash)
            iso[slot_idx] = row
            ids = np.zeros((self.B, self.prompt_pad), np.int32)
            ids[slot_idx, : req.prompt.size] = req.prompt
            if self._prefill_jit is None:
                self._build_jits()
            toks, self._pools = self._run_jit(
                self._prefill_jit, self._pools, jnp.asarray(ids),
                jnp.asarray(iso), jnp.zeros((self.B,), jnp.int32))
            first = int(np.asarray(toks)[slot_idx, req.prompt.size - 1])
            used += int(req.prompt.size)
            self.prefill_tokens += int(req.prompt.size)
            self._append_token(req, first)
            slot.remaining -= 1
            if self._finish_if_done(slot_idx, first):
                continue
        return used

    def _finish_if_done(self, slot_idx, last_tok) -> bool:
        slot = self._slots[slot_idx]
        req = slot.req
        done = slot.remaining <= 0 or (
            self.eos_token_id is not None and last_tok == self.eos_token_id)
        if done:
            self.manager.free_sequence(req.req_id)
            self._tables[slot_idx] = self._trash
            self._completed[req.req_id] = req
            slot.req = None
        return done

    def _schedule_prefill(self, budget_left: int) -> Dict[int, int]:
        """Round-robin chunk scheduler: starting at the fairness
        pointer, grant each prefilling slot one ``prefill_chunk``-sized
        bite of its remaining prompt per pass until the leftover budget
        cannot cover the next bite. Returns {slot_idx: real tokens}."""
        chunk = self.prefill_chunk
        order = sorted(
            (i for i, s in enumerate(self._slots) if s.prefilling),
            key=lambda i: (i - self._rr) % self.B)
        sched = {i: 0 for i in order}
        used, progress = 0, True
        while progress:
            progress = False
            for i in order:
                slot = self._slots[i]
                rem = slot.req.prompt.size - slot.prefill_pos - sched[i]
                if rem <= 0:
                    continue
                real = min(chunk, int(rem))
                if used + real > budget_left:
                    return {i: n for i, n in sched.items() if n}
                sched[i] += real
                used += real
                progress = True
        return {i: n for i, n in sched.items() if n}

    def _prefill_step(self, budget_left: int) -> int:
        """Execute this step's scheduled prefill chunks: one batched
        dispatch per ROUND (every slot with work left advances one
        chunk per round — multiple rounds when the budget grants a slot
        several chunks). Each chunk writes its KV at the slot's current
        ``cache_len`` offset through the slot's own block-table row;
        non-participating rows are isolated via the trash table. The
        slot whose final chunk lands also gets its first generated
        token from that chunk's logits — no extra dispatch."""
        sched = self._schedule_prefill(budget_left)
        if not sched:
            return 0
        chunk = self.prefill_chunk
        used = 0
        if self._prefill_jit is None:
            self._build_jits()
        while sched:
            ids = np.zeros((self.B, chunk), np.int32)
            cl = np.zeros((self.B,), np.int32)
            iso = np.full_like(self._tables, self._trash)
            round_rows = []
            for i in list(sched):
                slot = self._slots[i]
                start = slot.prefill_pos
                real = min(chunk, slot.req.prompt.size - start, sched[i])
                ids[i, :real] = slot.req.prompt[start:start + real]
                cl[i] = start
                iso[i] = self._tables[i]
                round_rows.append((i, start, real))
                sched[i] -= real
                if sched[i] <= 0:
                    del sched[i]
            toks, self._pools = self._run_jit(
                self._prefill_jit, self._pools, jnp.asarray(ids),
                jnp.asarray(iso), jnp.asarray(cl))
            toks = np.asarray(toks)  # [B, chunk]
            for i, start, real in round_rows:
                slot = self._slots[i]
                slot.prefill_pos = start + real
                slot.cache_len = slot.prefill_pos
                self.prefill_tokens += real
                used += real
                if slot.prefill_pos == slot.req.prompt.size:
                    first = int(toks[i, real - 1])
                    self._append_token(slot.req, first)
                    slot.remaining -= 1
                    self._finish_if_done(i, first)
        self._rr = (self._rr + 1) % self.B
        return used

    def _decode_step(self, budget_left: Optional[int]) -> int:
        """One decode round for every decode-phase slot (single step or
        a ``decode_chunk`` scan). Returns real tokens scheduled."""
        active = [i for i, s in enumerate(self._slots)
                  if s.active and not s.prefilling]
        if not active:
            return 0
        if self._decode_jit is None:
            self._build_jits()
        tok = np.zeros((self.B,), np.int32)
        cl = np.zeros((self.B,), np.int32)
        for i in active:
            slot = self._slots[i]
            tok[i] = slot.req.out[-1]
            cl[i] = slot.cache_len
        tables = self._tables
        if self.num_prefilling:
            # the decode program writes EVERY row's (tok, cl) — rows
            # mid-prefill hold real tables now, so their lane's dummy
            # write (token 0 at position 0) would corrupt the KV their
            # first chunk just laid down; point them at the trash block
            # for this dispatch (inactive rows are already trashed)
            tables = self._tables.copy()
            for i, s in enumerate(self._slots):
                if s.prefilling:
                    tables[i] = self._trash
        k = self.decode_chunk
        scan_ok = (
            k > 1
            and min(self._slots[i].remaining for i in active) >= k
            # under a token budget the K-step scan must fit it, and a
            # mid-prefill slot must not be starved for K steps
            and (budget_left is None
                 or (len(active) * k <= budget_left
                     and self.num_prefilling == 0)))
        if scan_ok:
            finished = np.ones((self.B,), bool)
            finished[active] = False
            toks, self._pools = self._run_jit(
                self._chunk_jit, self._pools, jnp.asarray(tok),
                jnp.asarray(tables), jnp.asarray(cl),
                jnp.asarray(finished))
            toks = np.asarray(toks)  # [K, B]
        else:
            nxt, self._pools = self._run_jit(
                self._decode_jit, self._pools, jnp.asarray(tok),
                jnp.asarray(tables), jnp.asarray(cl))
            toks = np.asarray(nxt)[None]  # [1, B]
        for i in active:
            slot = self._slots[i]
            for j in range(toks.shape[0]):
                t = int(toks[j, i])
                self._append_token(slot.req, t)
                slot.cache_len += 1
                slot.remaining -= 1
                self.decode_tokens += 1
                if self._finish_if_done(i, t):
                    break
        return len(active) * toks.shape[0]

    def step(self):
        """One engine iteration: evict expired slots, admit, then the
        token-budgeted work — the decode round first (decode-priority
        keeps inter-token latency flat), leftover budget spent on
        prefill chunks round-robin. Whole-prompt mode keeps the legacy
        order (prefill inside admission, then decode). Returns the
        requests completed this iteration (expired ones included, with
        ``status == "expired"``)."""
        if not _chaos.inject("serving.step"):
            return []  # dropped engine iteration: no work this tick
        before = set(self._completed)
        self._evict_expired()
        used = self._admit()
        budget = self.max_num_batched_tokens
        used += self._decode_step(None if budget is None else budget - used)
        if self.chunked:
            used += self._prefill_step(budget - used)
        self.steps += 1
        self.last_step_tokens = used
        self.max_step_tokens = max(self.max_step_tokens, used)
        return [self._completed[r] for r in set(self._completed) - before]

    def run(self, max_steps: int = 100_000) -> Dict[object, GenRequest]:
        """Drain the queue + active slots; returns {req_id: GenRequest}."""
        while (self._queue or self.num_active) and max_steps > 0:
            self.step()
            max_steps -= 1
        if self._restore_training:
            self.model.train()
        return dict(self._completed)
