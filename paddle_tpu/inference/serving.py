"""Continuous batching over the paged KV cache.

The serving loop paged attention exists for (ref:
python/paddle/incubate/nn/functional/block_multihead_attention.py —
the reference's serving kernel keeps per-sequence block tables exactly
so sequences can join and leave a running batch): a fixed pool of HBM
blocks, a fixed number of batch slots, requests admitted as slots and
blocks free up, finished sequences evicted and their blocks recycled.

TPU-native design (single compiled program per phase, static shapes):

- ONE decode program serves every engine iteration: tokens [B],
  per-layer pools, block tables [B, max_blocks], per-sequence
  ``cache_len`` [B] (the scalar-or-[B] contract of
  ops/paged_attention.py). Slot membership changes only change the
  TABLE CONTENTS and lengths — never shapes — so XLA compiles once.
- ONE prefill program (prompts padded to ``prompt_pad``) admits a
  request into a slot: rows other than the admitted one have their
  table pointed entirely at a reserved TRASH block, so their scattered
  writes land in a sacrificial page and live sequences are untouched
  (the positions a padded prompt writes past its real length are
  overwritten by later decode steps before they are ever attended).
- ``BlockManager`` (ops/paged_attention.py) is the allocator; eviction
  returns a sequence's blocks to the free list, and the next admission
  may reuse them immediately — correctness is guaranteed by the tables
  alone, which is what the eviction test pins down.

Greedy decoding (temperature 0) — matching models.generation.generate's
default — so engine outputs are token-identical to isolated generate()
runs, which is the correctness contract the tests assert.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import no_grad
from ..base.tensor import Tensor
from ..ops.paged_attention import BlockManager, PagedLayerCache
from ..testing import chaos as _chaos
from ..utils.retries import Deadline

__all__ = ["GenRequest", "ContinuousBatchingEngine"]


@dataclass
class GenRequest:
    """One generation request (ref: the reference's serving request —
    prompt ids + budget). ``deadline`` is the request's wall-clock
    budget: admission rejects it once expired, and an in-flight slot is
    EVICTED when it expires mid-decode — one stuck/abandoned client can
    never pin a slot (its blocks recycle immediately). ``status`` is
    "ok" for a normally finished request, "expired" for a rejected or
    evicted one (whatever tokens were produced stay in ``out``)."""

    req_id: object
    prompt: np.ndarray  # [s] int
    max_new_tokens: int = 32
    out: List[int] = field(default_factory=list)
    deadline: Optional[Deadline] = None
    status: str = "ok"

    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()


class _Slot:
    __slots__ = ("req", "cache_len", "remaining")

    def __init__(self):
        self.req: Optional[GenRequest] = None
        self.cache_len = 0
        self.remaining = 0

    @property
    def active(self):
        return self.req is not None


class ContinuousBatchingEngine:
    def __init__(self, model, *, max_batch: int, max_len: int,
                 block_size: int = 64, num_blocks: int,
                 prompt_pad: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 decode_chunk: int = 1):
        """``num_blocks`` fixes the HBM budget (the pool allocates one
        extra trash block); ``max_len`` bounds any sequence's positions
        (tables carry ceil(max_len/block_size) slots per row);
        ``prompt_pad`` is the static prefill width (default: one block).

        ``decode_chunk=K`` scans K decode steps in ONE device dispatch
        (lax.scan; tokens + eos state carried on device — the
        generate(decode_chunk=K) idiom) whenever every active slot has
        at least K tokens of budget left; otherwise the engine falls
        back to single steps. Admissions happen between chunks.
        """
        self.model = model
        self.B = int(max_batch)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        self.prompt_pad = int(prompt_pad or block_size)
        if self.prompt_pad > self.max_len:
            raise ValueError("prompt_pad exceeds max_len")
        self.eos_token_id = eos_token_id
        self.manager = BlockManager(num_blocks, block_size)
        self._trash = num_blocks  # reserved sacrificial pool row
        self.max_blocks_per_seq = -(-self.max_len // block_size)

        was_training = model.training
        model.eval()
        self._restore_training = was_training
        caches = model.init_cache(
            self.B, self.max_len, block_size=block_size,
            num_blocks=num_blocks + 1,
            tables=np.full((self.B, self.max_blocks_per_seq), self._trash,
                           np.int32),
        )
        self._pools = [(c.k_pool._data, c.v_pool._data) for c in caches]
        self._tables = np.full(
            (self.B, self.max_blocks_per_seq), self._trash, np.int32)
        self._slots = [_Slot() for _ in range(self.B)]
        self._queue: List[GenRequest] = []
        self._completed: Dict[object, GenRequest] = {}
        self._params = list(model.parameters())
        self._prefill_jit = None
        self._decode_jit = None
        self._chunk_jit = None
        self.decode_chunk = max(1, int(decode_chunk))
        self.steps = 0
        self.decode_tokens = 0

    # -- compiled phases -------------------------------------------------
    def _caches_from(self, pools, tables_arr):
        t = Tensor(tables_arr, _internal=True)
        return [
            PagedLayerCache(Tensor(k, _internal=True),
                            Tensor(v, _internal=True), t, False)
            for k, v in pools
        ]

    def _build_jits(self):
        model, params = self.model, self._params

        def prefill(param_arrays, pools, ids, tables, cache_len):
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(ids, _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            toks = jnp.argmax(logits._data, axis=-1)  # [B, s_pad]
            return toks, [(c.k_pool._data, c.v_pool._data)
                          for c in new_caches]

        def decode(param_arrays, pools, tok, tables, cache_len):
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(tok[:, None], _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            nxt = jnp.argmax(logits._data[:, -1], axis=-1).astype(jnp.int32)
            return nxt, [(c.k_pool._data, c.v_pool._data)
                         for c in new_caches]

        def decode_chunk(param_arrays, pools, tok, tables, cache_len,
                         finished):
            for p, a in zip(params, param_arrays):
                p._data = a
            eos = self.eos_token_id

            def body(carry, _):
                t, pl, cl, fin = carry
                with no_grad():
                    caches = self._caches_from(pl, tables)
                    logits, new_caches = model.forward_with_cache(
                        Tensor(t[:, None], _internal=True), caches,
                        Tensor(cl, _internal=True))
                nxt = jnp.argmax(
                    logits._data[:, -1], axis=-1).astype(jnp.int32)
                if eos is not None:
                    nxt = jnp.where(fin, eos, nxt)
                    fin = fin | (nxt == eos)
                new_pl = [(c.k_pool._data, c.v_pool._data)
                          for c in new_caches]
                return (nxt, new_pl, cl + 1, fin), nxt

            (t, pl, cl, fin), toks = jax.lax.scan(
                body, (tok, pools, cache_len, finished), None,
                length=self.decode_chunk)
            return toks, pl  # toks: [K, B]

        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(decode, donate_argnums=(1,))
        self._chunk_jit = jax.jit(decode_chunk, donate_argnums=(1,))

    def _run_jit(self, jit_fn, *args):
        """Invoke a compiled phase with the params' CURRENT host arrays
        (weight updates after engine construction are served) and
        restore them afterwards: the traced body writes tracers into
        p._data; leaving them there would leak tracers into the next
        eager/jit use."""
        current = [p._data for p in self._params]
        try:
            return jit_fn(current, *args)
        finally:
            for p, a in zip(self._params, current):
                p._data = a

    # -- public API ------------------------------------------------------
    def add_request(self, req_id, prompt, max_new_tokens: int = 32,
                    deadline=None):
        """``deadline``: seconds or a ``Deadline`` — the request's total
        budget (queue wait included). None = no deadline."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0 or prompt.size > self.prompt_pad:
            raise ValueError(
                f"prompt length {prompt.size} not in [1, prompt_pad="
                f"{self.prompt_pad}]")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        dl = None if deadline is None else Deadline.coerce(deadline)
        req = GenRequest(req_id, prompt, max_new_tokens, deadline=dl)
        if self._blocks_needed(req) > self.manager.num_blocks:
            raise ValueError(
                f"request needs {self._blocks_needed(req)} blocks but the "
                f"pool only has {self.manager.num_blocks} — it could never "
                "be admitted")
        self._queue.append(req)

    def _expire(self, req: GenRequest):
        req.status = "expired"
        self._completed[req.req_id] = req

    def _evict_expired(self):
        """Reclaim slots whose request's deadline passed: free the
        blocks, point the row at the trash block, surface the request as
        completed-with-status-expired."""
        for slot_idx, slot in enumerate(self._slots):
            if slot.active and slot.req.expired():
                self.manager.free_sequence(slot.req.req_id)
                self._tables[slot_idx] = self._trash
                self._expire(slot.req)
                slot.req = None

    @property
    def num_active(self):
        return sum(s.active for s in self._slots)

    def _blocks_needed(self, req):
        total = max(int(req.prompt.size) + req.max_new_tokens,
                    self.prompt_pad)
        return -(-total // self.block_size)

    def _admit(self):
        """Fill free slots from the queue while blocks last; one padded
        prefill per admission (per-slot isolation via the trash table).
        """
        for slot_idx, slot in enumerate(self._slots):
            # admission rejects requests whose budget already expired
            # while queued (the client gave up; don't burn a prefill)
            while self._queue and self._queue[0].expired():
                self._expire(self._queue.pop(0))
            if not self._queue or slot.active:
                continue
            req = self._queue[0]
            if self._blocks_needed(req) > self.manager.free_blocks:
                break  # head-of-line; keep FIFO fairness
            self._queue.pop(0)
            blocks = self.manager.allocate(
                req.req_id,
                max(req.prompt.size + req.max_new_tokens, self.prompt_pad))
            row = np.full((self.max_blocks_per_seq,), self._trash, np.int32)
            row[: len(blocks)] = blocks
            self._tables[slot_idx] = row
            slot.req = req
            slot.cache_len = int(req.prompt.size)
            slot.remaining = req.max_new_tokens

            # isolated prefill: only this row's table points at real
            # blocks; every other row scatters into the trash block
            iso = np.full_like(self._tables, self._trash)
            iso[slot_idx] = row
            ids = np.zeros((self.B, self.prompt_pad), np.int32)
            ids[slot_idx, : req.prompt.size] = req.prompt
            if self._prefill_jit is None:
                self._build_jits()
            toks, self._pools = self._run_jit(
                self._prefill_jit, self._pools, jnp.asarray(ids),
                jnp.asarray(iso), jnp.zeros((self.B,), jnp.int32))
            first = int(np.asarray(toks)[slot_idx, req.prompt.size - 1])
            req.out.append(first)
            slot.remaining -= 1
            if self._finish_if_done(slot_idx, first):
                continue

    def _finish_if_done(self, slot_idx, last_tok) -> bool:
        slot = self._slots[slot_idx]
        req = slot.req
        done = slot.remaining <= 0 or (
            self.eos_token_id is not None and last_tok == self.eos_token_id)
        if done:
            self.manager.free_sequence(req.req_id)
            self._tables[slot_idx] = self._trash
            self._completed[req.req_id] = req
            slot.req = None
        return done

    def step(self):
        """One engine iteration: evict expired slots, admit, then one
        decode step for every active slot. Returns the requests
        completed this iteration (expired ones included, with
        ``status == "expired"``)."""
        if not _chaos.inject("serving.step"):
            return []  # dropped engine iteration: no work this tick
        before = set(self._completed)
        self._evict_expired()
        self._admit()
        active = [i for i, s in enumerate(self._slots) if s.active]
        if active:
            if self._decode_jit is None:
                self._build_jits()
            tok = np.zeros((self.B,), np.int32)
            cl = np.zeros((self.B,), np.int32)
            for i in active:
                slot = self._slots[i]
                tok[i] = slot.req.out[-1]
                cl[i] = slot.cache_len
            k = self.decode_chunk
            if k > 1 and min(self._slots[i].remaining for i in active) >= k:
                finished = np.ones((self.B,), bool)
                finished[active] = False
                toks, self._pools = self._run_jit(
                    self._chunk_jit, self._pools, jnp.asarray(tok),
                    jnp.asarray(self._tables), jnp.asarray(cl),
                    jnp.asarray(finished))
                toks = np.asarray(toks)  # [K, B]
            else:
                nxt, self._pools = self._run_jit(
                    self._decode_jit, self._pools, jnp.asarray(tok),
                    jnp.asarray(self._tables), jnp.asarray(cl))
                toks = np.asarray(nxt)[None]  # [1, B]
            for i in active:
                slot = self._slots[i]
                for j in range(toks.shape[0]):
                    t = int(toks[j, i])
                    slot.req.out.append(t)
                    slot.cache_len += 1
                    slot.remaining -= 1
                    self.decode_tokens += 1
                    if self._finish_if_done(i, t):
                        break
        self.steps += 1
        return [self._completed[r] for r in set(self._completed) - before]

    def run(self, max_steps: int = 100_000) -> Dict[object, GenRequest]:
        """Drain the queue + active slots; returns {req_id: GenRequest}."""
        while (self._queue or self.num_active) and max_steps > 0:
            self.step()
            max_steps -= 1
        if self._restore_training:
            self.model.train()
        return dict(self._completed)
