"""Continuous batching over the paged KV cache.

The serving loop paged attention exists for (ref:
python/paddle/incubate/nn/functional/block_multihead_attention.py —
the reference's serving kernel keeps per-sequence block tables exactly
so sequences can join and leave a running batch): a fixed pool of HBM
blocks, a fixed number of batch slots, requests admitted as slots and
blocks free up, finished sequences evicted and their blocks recycled.

TPU-native design (single compiled program per phase, static shapes):

- ONE decode program serves every engine iteration: tokens [B],
  per-layer pools, block tables [B, max_blocks], per-sequence
  ``cache_len`` [B] (the scalar-or-[B] contract of
  ops/paged_attention.py). Slot membership changes only change the
  TABLE CONTENTS and lengths — never shapes — so XLA compiles once.
- ONE prefill program per static width admits prompt tokens into a
  slot: rows not participating have their table pointed entirely at a
  reserved TRASH block, so their scattered writes land in a sacrificial
  page and live sequences are untouched (the positions a padded prompt
  writes past its real length are overwritten by later decode steps
  before they are ever attended).
- ``BlockManager`` (ops/paged_attention.py) is the allocator; eviction
  returns a sequence's blocks to the free list, and the next admission
  may reuse them immediately — correctness is guaranteed by the tables
  alone, which is what the eviction test pins down.

Two prefill policies:

- Whole-prompt (default, ``prefill_chunk=None``): admission runs ONE
  padded prefill of width ``prompt_pad`` — the Orca-style baseline. A
  long prompt stalls every in-flight decode for its full prefill.
- CHUNKED (``prefill_chunk=K``, Sarathi-Serve-style): prompts split
  into K-token chunks, each chunk writing its KV at the slot's current
  ``cache_len`` offset through the same block tables (the compiled
  prefill program is width-polymorphic via retrace — one cached XLA
  program per chunk width, nonzero per-row offsets drive RoPE and the
  causal mask). Every engine step schedules at most
  ``max_num_batched_tokens`` REAL tokens: the running decode batch
  first (decode-priority, so inter-token latency stays flat), then
  prefill chunks round-robin across prefilling slots for fairness.
  Admission switches from whole-prompt-fits-``prompt_pad`` to
  token-budget pacing + block availability (full prompt+budget block
  reservation up front, so a mid-prefill slot can never deadlock on
  allocation). Deadline eviction works mid-prefill: a partially
  prefilled slot's blocks recycle immediately.

Overload control (ISSUE 4): with an :class:`AdmissionConfig` the
engine grows a front door — a bounded priority queue (interactive ahead
of batch, deadline-aware within a class), watermark/adaptive-level load
shedding at ``add_request`` time (``status="shed"``, never admitted),
queue-full displacement (interactive arrivals evict the worst queued
batch request), and degraded modes when KV blocks run scarce (pause new
admissions; clamp batch-class token grants). ``engine.load()`` exposes
the live load signal the controller decides from. ``fence()`` +
``requeue()`` are the supervisor's crash-only recovery hooks (see
inference/supervisor.py): a fenced engine refuses further steps, and
requeue re-enters already-accepted work into a rebuilt engine without
re-running admission control.

Speculative decoding (``spec_decode_k=K``): each decode round, a
:class:`~paddle_tpu.inference.speculative.DraftProposer` (default:
the zero-dispatch n-gram prompt-lookup proposer) drafts up to K tokens
per decode-phase slot and ONE batched verify dispatch — the prefill
program at static width K+1, same trash-table isolation — scores all
K+1 positions, computing the per-slot greedy accepted-length ON DEVICE
(a proposed-tokens lane + cumprod prefix-match beside the existing
token/eos lanes). Greedy accept-prefix makes every emitted token
byte-identical to ``decode_chunk=1`` output: a draft is accepted only
by EQUALLING the argmax, and rejected drafts' KV writes land at
positions the causal mask hides until the next contiguous dispatch
overwrites them (the same already-relied-on invariant that covers
padded prefill writes). The scheduler accounts the dispatched K+1
positions per slot against ``max_num_batched_tokens`` (falling back to
plain decode when the budget can't cover a verify round) and credits
the VARIABLE accepted-length per slot against budgets/deadlines;
``spec_stats()`` reports proposed/accepted/acceptance-rate.

``kv_dtype="int8"`` allocates quantized KV pools (per-block scale
pools ride the same physical block ids — see ops/paged_attention.py),
halving KV bytes per slot; COW forks copy scale rows with value rows
so prefix reuse and cluster routing work unchanged. Both levers
compose: the verify dispatch reads/writes the quantized pools like any
other phase.

Disaggregated prefill/decode (``role=``, ISSUE 8): production stacks
separate the two phases into POOLS (DistServe-style) so a 4096-token
prefill never shares a compiled program or a batch with latency-
critical decode. ``role="prefill_only"`` turns this engine into a
prefill worker: prompts admit and prefill exactly as before, but when
a prompt's last chunk lands the slot is RELEASED and the request parks
in the handoff-ready set (first generated token already attached —
it came from the prefill logits) with its KV blocks still allocated;
``drain_prefilled()`` + ``export_kv()`` + ``release_handoff()`` are
the handoff layer's pickup counter (see inference/disagg.py).
``role="decode_only"`` marks a decode worker: ``import_kv()`` places
an exported prompt's blocks into this engine's own pool + a free slot
and resumes decode at the cached offset. A decode-role engine keeps
the FULL prefill machinery — when the prefill pool is down, the
handoff router submits prompts to it directly and it serves them
colocated (chunked prefill), the measured graceful-degradation path.
Token-exactness across the boundary is by construction: the exported
bytes ARE the prefill engine's pool rows, and decode attends only
positions its own dispatches wrote or the import placed.

Async host/device pipelining (``overlap=True``, ISSUE 10): the sync
loop blocks on a full D2H token fetch every decode step and re-uploads
block tables + cache_len from host — the device idles while the host
schedules (the vLLM-v1 "async scheduling" gap). Overlap mode closes it
with LAG-1 SCHEDULING: (a) **device-resident token recycling** — the
decode/scan/verify programs carry ``(tok, tables, cache_len,
finished)`` ON DEVICE across steps, so decode step N+1 consumes step
N's sampled-token array directly and no jitted output round-trips
through host on the critical path; (b) an **async D2H copy ring** —
each dispatch's token array starts a ``copy_to_host_async`` and parks
in a FIFO ring; the host harvests step N's entry (eos/finish/detok/
journal bookkeeping) WHILE step N+1 runs on device; (c) **dirty-slot
incremental upload** — block tables/cache_len/finished live on device
and only slots that JOIN or LEAVE at a dispatch boundary are re-
uploaded (one small ``update_slot`` program per dirty row) instead of
whole-array rebuilds per step. A slot that finishes in entry N may be
over-issued one extra dispatch before the host learns it: the extra
token is discarded at harvest and its KV write lands behind the causal
mask until the block's next owner overwrites it — the same invariant
that already covers padded prefill writes — so output streams are
token-exact BY CONSTRUCTION (the A/B bench asserts bitwise equality).
``overlap_stats()`` reports dispatches / host-blocked seconds /
overlap fraction / H2D-D2H bytes, and ``load()`` gains
``host_blocked_frac`` for admission + router scoring.

Greedy decoding (temperature 0) — matching models.generation.generate's
default — so engine outputs are token-identical to isolated generate()
runs, which is the correctness contract the tests assert.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..base.tape import no_grad
from ..obs.metrics import MetricAttr, registry as _obs_registry
from ..base.tensor import Tensor
from ..ops.paged_attention import (
    BlockImportError,
    BlockManager,
    PagedLayerCache,
    PrefixCache,
)
from ..testing import chaos as _chaos
from ..utils import resources as _res
from ..utils.retries import Deadline
from .admission import (
    AdmissionConfig,
    AdmissionController,
    EngineLoad,
    priority_rank,
)
from .speculative import DraftProposer, NgramProposer

__all__ = ["GenRequest", "ContinuousBatchingEngine", "EngineFenced"]


class EngineFenced(RuntimeError):
    """The engine was retired by its supervisor: a recovery already
    rebuilt a replacement, so this instance must not touch its
    (transferred) requests again. ``step()`` raises it after the fence
    is set — the seam that lets an abandoned, formerly-hung step thread
    wake up and exit without corrupting anything."""


def _exec_lock_for(model) -> threading.Lock:
    """Compiled-phase execution is serialized across engine instances
    SHARING A MODEL: the traced bodies temporarily rebind the shared
    Parameters' ``_data`` to tracers, so a supervisor-abandoned step
    thread still inside a jit call must never overlap a replacement
    engine's dispatch (the newcomer would capture tracers as inputs).
    The lock lives on the model — engines over disjoint parameter sets
    keep executing concurrently — and also gives ``_run_jit`` a safe
    place to honor the fence: a runner that was blocked on it while
    its engine was retired raises instead of working."""
    lock = getattr(model, "__serving_exec_lock__", None)
    if lock is None:
        lock = threading.Lock()
        model.__serving_exec_lock__ = lock
    return lock


@dataclass
class GenRequest:
    """One generation request (ref: the reference's serving request —
    prompt ids + budget). ``deadline`` is the request's wall-clock
    budget: admission rejects it once expired, and an in-flight slot is
    EVICTED when it expires mid-decode or MID-PREFILL — one
    stuck/abandoned client can never pin a slot (its blocks recycle
    immediately). ``status``: "ok" for a normally finished request,
    "expired" for a deadline-evicted one (whatever tokens were produced
    stay in ``out``), "shed" for one rejected at admission (overload
    control — it never consumed any token budget; ``shed_reason`` says
    why), "poisoned" for one quarantined by the supervisor after
    repeatedly killing the engine. ``priority`` is the admission class
    ("interactive" | "batch"); ``retries`` counts supervisor recoveries
    this request was in flight for; ``clamped`` records a degraded-mode
    ``max_new_tokens`` reduction. ``times[i]`` is the perf_counter
    stamp when ``out[i]`` was produced; with ``t_submit`` it gives
    time-to-first-token and inter-token latencies for free."""

    req_id: object
    prompt: np.ndarray  # [s] int
    max_new_tokens: int = 32
    out: List[int] = field(default_factory=list)
    deadline: Optional[Deadline] = None
    status: str = "ok"
    t_submit: float = 0.0
    times: List[float] = field(default_factory=list)
    priority: str = "interactive"
    shed_reason: Optional[str] = None
    retries: int = 0
    clamped: bool = False
    # tenant identity (ISSUE 14): rides the journal, the cluster wire
    # record and the disagg handoff header, and labels the SLO
    # histograms — per-tenant attainment needs the dimension end-to-end
    tenant: str = "default"
    # distributed-tracing context (ISSUE 12): minted at admission or
    # adopted from an upstream leg (router wire record / disagg handoff
    # header), so every leg's span lands under ONE trace_id
    trace_id: Optional[str] = None
    span_id: Optional[str] = None

    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    def ttft(self) -> Optional[float]:
        """Seconds from submission to the first token (None if none)."""
        return self.times[0] - self.t_submit if self.times else None

    def inter_token_latencies(self) -> List[float]:
        return [b - a for a, b in zip(self.times, self.times[1:])]


class _Slot:
    __slots__ = ("req", "cache_len", "remaining", "prefill_pos",
                 "pending_first")

    def __init__(self):
        self.req: Optional[GenRequest] = None
        self.cache_len = 0
        self.remaining = 0
        self.prefill_pos = 0  # prompt tokens written to KV so far
        # overlap mode: prefill done but the first generated token is
        # still riding the async copy ring — the slot must not join a
        # decode dispatch until it lands
        self.pending_first = False

    @property
    def active(self):
        return self.req is not None

    @property
    def prefilling(self):
        return self.req is not None and self.prefill_pos < self.req.prompt.size

    @property
    def decode_ready(self):
        return (self.req is not None and not self.pending_first
                and self.prefill_pos >= self.req.prompt.size
                and bool(self.req.out))


class _RingEntry:
    """One in-flight dispatch whose token results the host has not yet
    harvested. ``rows`` snapshots (slot_idx, request[, extra]) at
    DISPATCH time — harvest credits tokens to the request the dispatch
    actually served, and an identity check against the slot's current
    request discards the ≤1-step over-issue for rows that finished or
    were evicted while the entry was in flight."""

    __slots__ = ("kind", "arrays", "rows", "span")

    def __init__(self, kind, arrays, rows):
        self.kind = kind        # "decode" | "spec" | "first"
        self.arrays = arrays    # device arrays to fetch
        self.rows = rows
        self.span = None        # open obs "dispatch" span (issue→harvest)


class _ShedCounts:
    """Dict-shaped view over the per-priority ``serving_shed_total``
    registry series: ``eng.n_shed["interactive"]``, ``.get()``,
    ``.items()`` and dict equality all behave exactly like the plain
    dict this used to be, but the counts live in the obs registry
    (labels ``engine=<id>, priority=<class>``)."""

    __slots__ = ("_labels", "_handles")

    def __init__(self, labels: dict):
        self._labels = dict(labels)
        self._handles: Dict[str, object] = {}
        for pri in ("interactive", "batch"):
            self[pri] = 0

    def _h(self, pri: str):
        h = self._handles.get(pri)
        if h is None:
            h = _obs_registry().counter(
                "serving_shed_total",
                {**self._labels, "priority": str(pri)},
                help="requests shed at admission, by priority class")
            self._handles[pri] = h
        return h

    def __getitem__(self, pri) -> int:
        return int(self._h(pri).value)

    def __setitem__(self, pri, v) -> None:
        self._h(pri).set_(float(v))

    def get(self, pri, default=0):
        h = self._handles.get(pri)
        return int(h.value) if h is not None else default

    def keys(self):
        return self._handles.keys()

    def values(self):
        return [int(h.value) for h in self._handles.values()]

    def items(self):
        return [(k, int(h.value)) for k, h in self._handles.items()]

    def __iter__(self):
        return iter(self._handles)

    def __len__(self) -> int:
        return len(self._handles)

    def __eq__(self, other):
        if isinstance(other, (dict, _ShedCounts)):
            return dict(self.items()) == dict(other.items()) \
                if isinstance(other, _ShedCounts) \
                else dict(self.items()) == other
        return NotImplemented

    def __repr__(self) -> str:
        return repr(dict(self.items()))


_ENGINE_IDS = itertools.count(1)


class ContinuousBatchingEngine:
    # ISSUE 12: every stats counter below is a registry-backed series
    # (label engine=<id>). The data descriptors keep `self.steps += 1`
    # and external writes (`eng.ewma_step_s = None` in the overload
    # bench) byte-identical to the old plain attributes while the
    # numbers live in the process-global obs registry — EngineLoad,
    # prefix_stats(), spec_stats() and overlap_stats() are now VIEWS
    # over these series.
    n_imported = MetricAttr(
        "serving_kv_imported_total", as_int=True,
        help="decode side: requests entered via KV import")
    n_handed_off = MetricAttr(
        "serving_kv_handed_off_total", as_int=True,
        help="prefill side: KV exports released after ack")
    prefix_hit_tokens = MetricAttr(
        "serving_prefix_hit_tokens_total", as_int=True,
        help="prompt tokens served from the prefix cache")
    prefix_forks = MetricAttr(
        "serving_prefix_forks_total", as_int=True,
        help="copy-on-write block forks from adopted prefixes")
    spec_proposed = MetricAttr(
        "serving_spec_proposed_total", as_int=True,
        help="real draft tokens sent to verify")
    spec_accepted = MetricAttr(
        "serving_spec_accepted_total", as_int=True,
        help="draft tokens greedy-accepted by verify")
    spec_emitted = MetricAttr(
        "serving_spec_emitted_total", as_int=True,
        help="tokens emitted by verify dispatches")
    spec_dispatches = MetricAttr(
        "serving_spec_dispatches_total", as_int=True,
        help="speculative verify dispatches")
    spec_slot_rounds = MetricAttr(
        "serving_spec_slot_rounds_total", as_int=True,
        help="slot participations in verify dispatches")
    n_dispatches = MetricAttr(
        "serving_dispatches_total", as_int=True,
        help="decode-phase device dispatches")
    host_blocked_s = MetricAttr(
        "serving_host_blocked_seconds_total",
        help="cumulative seconds the host blocked on D2H fetches")
    busy_s = MetricAttr(
        "serving_busy_seconds_total",
        help="cumulative step() wall seconds")
    h2d_bytes = MetricAttr(
        "serving_h2d_bytes_total", as_int=True,
        help="host->device upload bytes")
    h2d_decode_bytes = MetricAttr(
        "serving_h2d_decode_bytes_total", as_int=True,
        help="host->device bytes on the decode-phase critical path")
    d2h_bytes = MetricAttr(
        "serving_d2h_bytes_total", as_int=True,
        help="device->host fetch bytes")
    steps = MetricAttr(
        "serving_steps_total", as_int=True, help="engine iterations")
    decode_tokens = MetricAttr(
        "serving_decode_tokens_total", as_int=True,
        help="decode-phase tokens emitted")
    prefill_tokens = MetricAttr(
        "serving_prefill_tokens_total", as_int=True,
        help="prompt tokens prefilled (cache hits excluded)")
    n_expired = MetricAttr(
        "serving_expired_total", as_int=True,
        help="accepted-then-expired requests (queue or in-flight)")
    ewma_blocked_frac = MetricAttr(
        "serving_host_blocked_frac", kind="gauge",
        help="EWMA of the per-step host-blocked fraction")
    ewma_step_s = MetricAttr(
        "serving_ewma_step_seconds", kind="gauge",
        help="EWMA of non-idle step wall time")
    ewma_step_tokens = MetricAttr(
        "serving_ewma_step_tokens", kind="gauge",
        help="EWMA of real tokens drained per non-idle step")

    def __init__(self, model, *, max_batch: int, max_len: int,
                 block_size: int = 64, num_blocks: int,
                 prompt_pad: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 decode_chunk: int = 1,
                 prefill_chunk: Optional[int] = None,
                 max_num_batched_tokens: Optional[int] = None,
                 admission: Optional[AdmissionConfig] = None,
                 prefix_cache: bool = False,
                 spec_decode_k: Optional[int] = None,
                 draft_proposer: Optional[DraftProposer] = None,
                 kv_dtype: Optional[str] = None,
                 fp8: bool = False,
                 role: str = "unified",
                 overlap: bool = False,
                 cache_tier=None,
                 tenant_namespaces: bool = False,
                 shared_prefixes=None):
        """``num_blocks`` fixes the HBM budget (the pool allocates one
        extra trash block); ``max_len`` bounds any sequence's positions
        (tables carry ceil(max_len/block_size) slots per row);
        ``prompt_pad`` is the static whole-prompt prefill width
        (default: one block; unused once chunking is on).

        ``decode_chunk=K`` scans K decode steps in ONE device dispatch
        (lax.scan; tokens + eos state carried on device — the
        generate(decode_chunk=K) idiom) whenever every active slot has
        at least K tokens of budget left; otherwise the engine falls
        back to single steps. Admissions happen between chunks. With a
        token budget the scan additionally requires no slot to be
        mid-prefill and active*K to fit the budget.

        ``prefill_chunk=C`` turns on chunked prefill: prompts (up to
        ``max_len - max_new_tokens``, no longer capped by
        ``prompt_pad``) are fed C tokens per scheduled chunk.
        ``max_num_batched_tokens`` caps the REAL tokens any engine step
        processes (default ``max_batch + prefill_chunk``: one full
        decode round plus one chunk). It must cover a full decode round
        (>= max_batch — the decode dispatch is indivisible) and one
        chunk (>= prefill_chunk — otherwise a lone prefill could never
        be scheduled).

        ``prefix_cache=True`` turns on radix-style prefix KV reuse
        (vLLM automatic-prefix-caching / SGLang RadixAttention class):
        a finished prompt's FULL KV blocks stay pinned in a
        :class:`~paddle_tpu.ops.paged_attention.PrefixCache`; a later
        prompt sharing a block-aligned prefix ADOPTS those blocks
        (ref-counted, copy-on-write) and prefill starts at the cached
        ``cache_len`` offset — a shared system prompt / few-shot header
        prefills once per engine, not once per request. Cached blocks
        are reclaimed LRU-first when admissions run out of free blocks,
        so the cache can never deadlock admission. Greedy decode keeps
        cache-hit outputs token-identical to cold runs.

        ``spec_decode_k=K`` turns on self-speculative decoding (see
        module docstring): ``draft_proposer`` supplies the drafts
        (default :class:`NgramProposer` — prompt-lookup, no second
        model); rounds where no slot has a draft fall back to the
        plain decode/scan path at zero cost. Greedy accept-prefix
        keeps outputs token-identical to ``spec_decode_k=None``.

        ``kv_dtype="int8"`` quantizes the KV pools (per-block scale
        pools; ~2x KV capacity at an int8-weights-class quality cost —
        the rel-err gate in tests/test_spec_decode.py pins it).

        ``role`` selects the engine's place in a disaggregated
        deployment (module docstring): "unified" (default — serve
        everything), "prefill_only" (no decode dispatches; finished
        prefills park handoff-ready with their blocks held, the first
        token attached), "decode_only" (a decode worker taking
        ``import_kv`` handoffs; behaviourally a unified engine, so
        colocated-fallback prompts still serve). A prefill-only engine
        reserves NO decode-growth blocks — its block budget is the
        prompt alone.

        ``overlap=True`` turns on the async host/device pipeline (lag-1
        scheduling; module docstring): decode dispatches consume the
        previous dispatch's on-device token array, tables/cache_len/
        finished persist on device with dirty-slot incremental upload,
        and the host harvests tokens one step behind through an async
        D2H copy ring. Output streams stay token-identical to
        ``overlap=False`` — only WHEN the host sees each token changes.

        ``admission=AdmissionConfig(...)`` turns on overload control:
        submissions run through an :class:`AdmissionController` (shed
        vs admit vs displace), the waiting queue becomes a bounded
        priority queue, and the KV watermarks drive the degraded modes
        (pause new admissions / clamp batch token grants). Without it
        the queue stays plain FIFO and every submission is accepted —
        the pre-overload-control behaviour, bit for bit. Tenant
        policies in the config additionally turn on token-bucket quotas
        and WFQ queue ordering (see :mod:`.admission`).

        ``cache_tier=HostTier(...)`` adds a host-RAM spill tier under
        the prefix cache (requires ``prefix_cache=True``): registered
        prefixes are written through to host memory as CRC-framed
        exports, and a prompt whose HBM radix hit is shorter than a
        spilled prefix imports it back before reservation — prefix
        capacity becomes a host-memory budget instead of an HBM one.

        ``tenant_namespaces=True`` keys the prefix cache by tenant so
        one tenant's prompts never adopt another's KV. Token sequences
        in ``shared_prefixes`` (common system prompts) are additionally
        registered under a shared namespace every tenant may adopt from
        — the physical blocks are multi-pinned and copy-on-write, so
        isolation costs nothing for the prompts everyone shares.
        """
        if role not in ("unified", "prefill_only", "decode_only"):
            raise ValueError(
                f"role must be 'unified', 'prefill_only' or "
                f"'decode_only', got {role!r}")
        self.role = role
        # obs identity FIRST: every MetricAttr write below routes into
        # registry series labeled engine=<id>, so the labels must exist
        # before the first counter assignment
        self._obs_id = f"eng{next(_ENGINE_IDS)}"
        self._obs_labels = {"engine": self._obs_id}
        _reg = _obs_registry()
        # SLO histogram series carry a tenant label (ISSUE 14): the
        # label sets PARTITION the observations (one observe per event,
        # on the request's tenant series), so slo_summary's cross-series
        # merge stays exact while per-tenant breakdowns come for free.
        # The registry's cardinality cap bounds the exported set; handle
        # acquisition is cached per tenant off the hot path.
        self._slo_hists: Dict[str, tuple] = {}
        self._c_tenant_req: Dict[str, object] = {}
        self._h_ttft, self._h_itl, self._h_queue = \
            self._slo_handles("default")
        self._c_requests = _reg.counter(
            "serving_requests_total", self._obs_labels,
            help="requests submitted (shed ones included)")
        # finished prefills awaiting export (prefill_only role): req_id
        # -> GenRequest; the KV blocks stay allocated under the req_id
        # until export_kv + release_handoff (or expiry/abandon)
        self._handoff_ready: Dict[object, GenRequest] = {}
        self.n_imported = 0   # decode side: requests entered via import
        self.n_handed_off = 0  # prefill side: exports released
        self.model = model
        self.B = int(max_batch)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        self.prompt_pad = int(prompt_pad or block_size)
        if self.prompt_pad > self.max_len:
            raise ValueError("prompt_pad exceeds max_len")
        # generation parity: generate() refuses positions beyond the
        # model's limit — the engine serves the same contract instead
        # of silently extrapolating RoPE past it
        limit = getattr(getattr(model, "config", None),
                        "max_position_embeddings", None)
        if limit is not None and self.max_len > limit:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"max_position_embeddings ({limit})")
        self.eos_token_id = eos_token_id
        self.manager = BlockManager(num_blocks, block_size)
        # leak-sanitizer stamp (graft-own): None when off — the slot/
        # handoff accounting hooks gate on one attribute load
        self._graft_ledger = _res.current()
        self.prefix_cache = (PrefixCache(block_size, manager=self.manager)
                             if prefix_cache else None)
        if cache_tier is not None and self.prefix_cache is None:
            raise ValueError("cache_tier requires prefix_cache=True")
        if tenant_namespaces and self.prefix_cache is None:
            raise ValueError("tenant_namespaces requires prefix_cache=True")
        self.cache_tier = cache_tier
        self._tenant_ns = bool(tenant_namespaces)
        self._shared_prefixes = [
            np.asarray(p, np.int32).reshape(-1)
            for p in (shared_prefixes or ())]
        self._tier_seq = 0
        self.tier_restores = 0
        self.tier_restore_tokens = 0
        self.prefix_hit_tokens = 0
        self.prefix_forks = 0
        self._trash = num_blocks  # reserved sacrificial pool row
        self.max_blocks_per_seq = -(-self.max_len // block_size)

        self.prefill_chunk = None if prefill_chunk is None \
            else int(prefill_chunk)
        if self.prefill_chunk is not None:
            if not 0 < self.prefill_chunk <= self.max_len:
                raise ValueError(
                    f"prefill_chunk must be in [1, max_len={self.max_len}], "
                    f"got {self.prefill_chunk}")
            if max_num_batched_tokens is None:
                max_num_batched_tokens = self.B + self.prefill_chunk
            self.max_num_batched_tokens = int(max_num_batched_tokens)
            floor = max(self.B, self.prefill_chunk)
            if self.max_num_batched_tokens < floor:
                raise ValueError(
                    f"max_num_batched_tokens={self.max_num_batched_tokens} "
                    f"must be >= max(max_batch, prefill_chunk)={floor}: a "
                    "decode round is one indivisible dispatch and a lone "
                    "prefill must be able to schedule one chunk")
        else:
            self.max_num_batched_tokens = None  # whole-prompt: unbudgeted

        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        # fp8 GEMMs: swap every Linear (except the lm_head — its logits
        # feed sampling, where fp8 costs measurable quality for one GEMM
        # of savings) for Fp8Linear BEFORE the programs compile. The
        # payoff is prefill: its wide [tokens, d] x [d, 4d] GEMMs are
        # MXU-bound, where fp8 doubles per-pass throughput; decode GEMMs
        # are HBM-bound so fp8 halves the weight-stream bytes instead.
        self.fp8 = bool(fp8)
        if self.fp8:
            from ..amp import convert_to_fp8

            self.fp8_layers = convert_to_fp8(
                model, exclude=lambda name: "lm_head" in name)
        self.spec_k = None if spec_decode_k is None else int(spec_decode_k)
        if self.spec_k is not None and self.spec_k < 1:
            raise ValueError(f"spec_decode_k must be >= 1, got {self.spec_k}")
        self.proposer = (draft_proposer if draft_proposer is not None
                         else NgramProposer())
        self.spec_proposed = 0   # real draft tokens sent to verify
        self.spec_accepted = 0   # of those, greedy-accepted
        self.spec_emitted = 0    # tokens emitted by verify dispatches
        self.spec_dispatches = 0
        self.spec_slot_rounds = 0  # slot-participations in dispatches

        was_training = model.training
        model.eval()
        self._restore_training = was_training
        caches = model.init_cache(
            self.B, self.max_len, block_size=block_size,
            num_blocks=num_blocks + 1,
            tables=np.full((self.B, self.max_blocks_per_seq), self._trash,
                           np.int32),
            kv_dtype=kv_dtype,
        )
        self._pools = self._pools_from(caches)
        self._tables = np.full(
            (self.B, self.max_blocks_per_seq), self._trash, np.int32)
        self._slots = [_Slot() for _ in range(self.B)]
        self._queue: List[GenRequest] = []
        self._completed: Dict[object, GenRequest] = {}
        self._params = list(model.parameters())
        self._prefill_jit = None
        self._decode_jit = None
        self._chunk_jit = None
        self._spec_jit = None  # k+1-wide verify + device accepted-length
        self._copy_jit = None  # COW block copy (prefix-cache forks)
        self._update_jit = None  # dirty-slot upload (overlap mode)
        # async host/device pipelining (overlap mode)
        self.overlap = bool(overlap)
        self.pipeline_depth = 1 if self.overlap else 0
        self._ring: deque = deque()  # in-flight _RingEntry FIFO
        self._dstate = None  # (tok, tables, cache_len, finished) on device
        self._dirty: set = set()  # slot rows needing device upload
        # host/device overlap telemetry (tracked in BOTH modes so the
        # A/B bench compares like for like)
        self.n_dispatches = 0       # decode-phase dispatches
        self.host_blocked_s = 0.0   # cumulative seconds blocked in D2H
        self.busy_s = 0.0           # cumulative step() wall seconds
        self.ewma_blocked_frac: Optional[float] = None
        self.h2d_bytes = 0          # total host->device upload bytes
        self.h2d_decode_bytes = 0   # ...on the decode-phase path only
        self.d2h_bytes = 0          # device->host fetch bytes
        self._harvested_step = 0    # real tokens harvested this step
        self.decode_chunk = max(1, int(decode_chunk))
        self._rr = 0  # round-robin start for chunk scheduling fairness
        self.steps = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.last_step_tokens = 0
        self.max_step_tokens = 0
        self._step_spec_overcharge = 0
        # overload control + supervision surface
        self.admission = (None if admission is None
                          else AdmissionController(admission))
        self.n_shed = _ShedCounts(self._obs_labels)
        self._pending_shed: List[GenRequest] = []  # sheds since drain
        self.n_expired = 0  # accepted-then-expired (queue or in-flight)
        self.prefill_paused = False  # degraded mode: KV blocks scarce
        self.ewma_step_s: Optional[float] = None
        self.ewma_step_tokens: Optional[float] = None
        self.last_step_s = 0.0
        self._fenced = False
        self._exec_lock = _exec_lock_for(model)
        self._phases_run: set = set()  # compiled phases dispatched so far

    # -- compiled phases -------------------------------------------------
    @staticmethod
    def _pools_from(caches):
        """Per-layer pool tuples for the donated jit carry: (k, v) for
        float pools, (k, v, k_scale, v_scale) for int8 — one shape for
        every compiled phase."""
        out = []
        for c in caches:
            if getattr(c, "k_scale", None) is not None:
                out.append((c.k_pool._data, c.v_pool._data,
                            c.k_scale._data, c.v_scale._data))
            else:
                out.append((c.k_pool._data, c.v_pool._data))
        return out

    def _caches_from(self, pools, tables_arr):
        t = Tensor(tables_arr, _internal=True)
        caches = []
        for entry in pools:
            scales = tuple(Tensor(s, _internal=True) for s in entry[2:])
            caches.append(PagedLayerCache(
                Tensor(entry[0], _internal=True),
                Tensor(entry[1], _internal=True), t, False, *scales))
        return caches

    def _build_jits(self):
        """Every phase program is STATE-ADVANCING: it returns the next
        step's ``(tok, cache_len, finished)`` lanes beside its token
        output, so overlap mode can feed dispatch N's device outputs
        straight into dispatch N+1 without a host round-trip. Sync mode
        runs the SAME programs and simply ignores the state lanes —
        one compiled program per phase serves both modes (the
        recompile-pin contract is unchanged). ``cache_len`` advances
        are clamped at ``max_len`` so inactive/trash rows cannot drift
        into out-of-range positions across long overlap runs."""
        model, params = self.model, self._params
        max_len = self.max_len

        def prefill(param_arrays, pools, ids, tables, cache_len,
                    last_idx):
            """Returns only the per-row token at ``last_idx`` (the
            completing chunk's final real position) — the ONE int per
            row the host ever reads from a prefill, so the D2H copy is
            [B] ints instead of the whole [B, width] token array."""
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(ids, _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            toks = jnp.argmax(logits._data, axis=-1)  # [B, width]
            firsts = toks[jnp.arange(toks.shape[0]),
                          last_idx].astype(jnp.int32)  # [B]
            return firsts, self._pools_from(new_caches)

        def decode(param_arrays, pools, tok, tables, cache_len,
                   finished):
            for p, a in zip(params, param_arrays):
                p._data = a
            eos = self.eos_token_id
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(tok[:, None], _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            nxt = jnp.argmax(logits._data[:, -1], axis=-1).astype(jnp.int32)
            if eos is not None:
                nxt = jnp.where(finished, eos, nxt)
                finished = finished | (nxt == eos)
            cl2 = jnp.minimum(cache_len + 1, max_len)
            return nxt, cl2, finished, self._pools_from(new_caches)

        def decode_chunk(param_arrays, pools, tok, tables, cache_len,
                         finished):
            for p, a in zip(params, param_arrays):
                p._data = a
            eos = self.eos_token_id

            def body(carry, _):
                t, pl, cl, fin = carry
                with no_grad():
                    caches = self._caches_from(pl, tables)
                    logits, new_caches = model.forward_with_cache(
                        Tensor(t[:, None], _internal=True), caches,
                        Tensor(cl, _internal=True))
                nxt = jnp.argmax(
                    logits._data[:, -1], axis=-1).astype(jnp.int32)
                if eos is not None:
                    nxt = jnp.where(fin, eos, nxt)
                    fin = fin | (nxt == eos)
                new_pl = self._pools_from(new_caches)
                return (nxt, new_pl, jnp.minimum(cl + 1, max_len),
                        fin), nxt

            (t, pl, cl, fin), toks = jax.lax.scan(
                body, (tok, pools, cache_len, finished), None,
                length=self.decode_chunk)
            return toks, t, cl, fin, pl  # toks: [K, B]

        def spec_verify(param_arrays, pools, tok, tables, cache_len,
                        finished, drafts):
            """ONE dispatch scoring all k+1 positions: the prefill path
            at width k+1 plus a drafts lane — the greedy accepted
            length (cumprod of prefix matches against the argmax one
            position back) comes back per slot, so the host only
            slices tokens, never logits. The continuation lanes
            (``tok`` = the bonus token at the last accepted position,
            ``cache_len + accepted + 1``) are computed ON DEVICE so
            overlap mode chains verify rounds without a host sync; an
            eos inside the accepted prefix sets ``finished`` (the host
            finishes the slot at harvest — any device-side over-advance
            lands on a slot the host is about to retire)."""
            for p, a in zip(params, param_arrays):
                p._data = a
            eos = self.eos_token_id
            ids = jnp.concatenate([tok[:, None], drafts], axis=1)
            with no_grad():
                caches = self._caches_from(pools, tables)
                logits, new_caches = model.forward_with_cache(
                    Tensor(ids, _internal=True), caches,
                    Tensor(cache_len, _internal=True))
            toks = jnp.argmax(
                logits._data, axis=-1).astype(jnp.int32)  # [B, k+1]
            ok = (drafts == toks[:, :-1]).astype(jnp.int32)  # [B, k]
            acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)  # [B], <= k
            nxt = toks[jnp.arange(toks.shape[0]), acc]
            cl2 = jnp.minimum(cache_len + acc + 1, max_len)
            if eos is not None:
                pos = jnp.arange(toks.shape[1])[None, :]
                hit = (toks == eos) & (pos <= acc[:, None])
                nxt = jnp.where(finished, eos, nxt)
                finished = finished | jnp.any(hit, axis=1)
            return toks, acc, nxt, cl2, finished, \
                self._pools_from(new_caches)

        def update_slot(state, i, row, cl_i, tok_i, fin_i):
            """Dirty-slot incremental upload: rewrite ONE row of the
            persistent device step state (a slot joined or left at a
            dispatch boundary). Traced row index — one compiled
            program serves every slot."""
            tok, tables, cl, fin = state
            return (tok.at[i].set(tok_i), tables.at[i].set(row),
                    cl.at[i].set(cl_i), fin.at[i].set(fin_i))

        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(decode, donate_argnums=(1,))
        self._chunk_jit = jax.jit(decode_chunk, donate_argnums=(1,))
        self._spec_jit = jax.jit(spec_verify, donate_argnums=(1,))
        # NOT donated: the tok lane doubles as a ring-fetch target, and
        # a donated buffer would be invalidated under the async copy
        self._update_jit = jax.jit(update_slot)

    def _run_jit(self, jit_fn, *args):
        """Invoke a compiled phase with the params' CURRENT host arrays
        (weight updates after engine construction are served) and
        restore them afterwards: the traced body writes tracers into
        p._data; leaving them there would leak tracers into the next
        eager/jit use."""
        with self._exec_lock:
            if self._fenced:
                raise EngineFenced(
                    "engine was retired by its supervisor while waiting "
                    "for the compiled-phase lock")
            current = [p._data for p in self._params]
            try:
                out = jit_fn(current, *args)
            finally:
                for p, a in zip(self._params, current):
                    p._data = a
        if self._fenced:
            # a slow (not hung-forever) dispatch that outlived the
            # watchdog: abort BEFORE the caller applies results — the
            # supervisor already harvested/requeued this engine's work
            raise EngineFenced(
                "engine was retired by its supervisor mid-dispatch")
        return out

    # -- host<->device transfer discipline -------------------------------
    def _h2d(self, x, *, decode: bool = False):
        """The ONE host->device upload path: counts bytes (the A/B
        bench's per-token-upload metric) and returns the device array.
        ``decode=True`` marks uploads on the decode-phase critical
        path — the bytes persistent device state exists to eliminate."""
        n = int(getattr(x, "nbytes", 0))
        self.h2d_bytes += n
        if decode:
            self.h2d_decode_bytes += n
        return jnp.asarray(x)

    @staticmethod
    def _start_async_copies(arrays) -> None:
        for a in arrays:
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:  # pragma: no cover - backend quirk
                    pass

    def _fetch(self, *arrays, copies_started: bool = False):
        """The ONE device->host fetch path: starts an async copy on
        every array FIRST (unless the ring already did at dispatch
        time), then gathers — so by the time the blocking gather runs,
        the copies (and in overlap mode the compute, a whole step
        earlier) are already in flight. Time spent actually blocked is
        accounted to ``host_blocked_s`` — the decode-phase host-blocked
        fraction overlap mode exists to shrink."""
        if not copies_started:
            self._start_async_copies(arrays)
        t0 = time.perf_counter()
        out = tuple(np.asarray(a) for a in arrays)
        self.host_blocked_s += time.perf_counter() - t0
        for o in out:
            self.d2h_bytes += int(o.nbytes)
        return out if len(out) > 1 else out[0]

    # -- persistent device step state (overlap mode) ----------------------
    def _mark_dirty(self, slot_idx: int) -> None:
        """Record a slot-membership change: the device-resident row is
        stale and must be re-uploaded before the next overlap decode
        dispatch. Over-marking is harmless (the flush derives the row
        content from host truth); UNDER-marking is the bug class the
        device-vs-host invariant test pins down."""
        if self.overlap:
            self._dirty.add(slot_idx)

    def _ensure_dstate(self):
        if self._dstate is not None:
            return
        B, mb = self.B, self.max_blocks_per_seq
        self._dstate = (
            self._h2d(np.zeros((B,), np.int32), decode=True),
            self._h2d(np.full((B, mb), self._trash, np.int32),
                      decode=True),
            self._h2d(np.zeros((B,), np.int32), decode=True),
            self._h2d(np.ones((B,), bool), decode=True),
        )

    def _flush_dirty(self) -> None:
        """Upload every dirty slot's row into the persistent device
        state — the ONLY steady-state H2D traffic in overlap mode (a
        steadily decoding batch has zero dirty slots, so zero upload
        bytes per step). A slot is decode-eligible on device iff it is
        active, past prefill, and its first token has landed; every
        other state maps to the trash row, exactly like the sync
        dispatch's table isolation."""
        if not self.overlap or not self._dirty:
            return
        self._ensure_dstate()
        if self._update_jit is None:
            self._build_jits()
        state = self._dstate
        mb = self.max_blocks_per_seq
        for i in sorted(self._dirty):
            slot = self._slots[i]
            if slot.decode_ready:
                row = np.ascontiguousarray(self._tables[i], np.int32)
                cl_i, tok_i = slot.cache_len, slot.req.out[-1]
                fin_i = False
            else:
                row = np.full((mb,), self._trash, np.int32)
                cl_i, tok_i, fin_i = 0, 0, True
            state = self._update_jit(
                state, self._h2d(np.int32(i), decode=True),
                self._h2d(row, decode=True),
                self._h2d(np.int32(cl_i), decode=True),
                self._h2d(np.int32(tok_i), decode=True),
                self._h2d(np.bool_(fin_i), decode=True))
        self._dstate = state
        self._dirty.clear()

    def _push_entry(self, kind, arrays, rows):
        """Queue a dispatch's token outputs on the async D2H copy ring:
        the copies start NOW, the host reads them a step later."""
        self._start_async_copies(arrays)
        e = _RingEntry(kind, arrays, rows)
        if _obs.enabled():
            # device-timeline span: dispatch issue → harvest (closed in
            # _harvest, possibly many steps later). Parent under the
            # first row's request so a single-request trace shows its
            # dispatches; co-batched requests ride in args.
            e.span = _obs.start_span(
                "dispatch", parent=(rows[0][1] if rows else None),
                tid="device", kind=kind, rows=len(rows))
        self._ring.append(e)

    def _harvest(self, *, drain: bool = False) -> int:
        """Process ring entries down to ``pipeline_depth`` (all of them
        with ``drain=True``): fetch each entry's tokens — usually
        already on host thanks to the async copy — and run the host
        bookkeeping (append/eos/finish/free) the sync loop did inline.
        Returns real tokens emitted, also accumulated into
        ``_harvested_step`` (one overlap step can harvest from several
        points: the lag-1 pop, the spec sync point, the idle drain)."""
        target = 0 if drain else self.pipeline_depth
        real = 0
        while len(self._ring) > target:
            e = self._ring.popleft()
            if e.span is not None:
                _obs.finish_span(e.span)  # issue → harvest pickup
            hsp = (_obs.start_span("harvest", parent=e.span,
                                   tid="serve", kind=e.kind)
                   if e.span is not None and _obs.enabled() else None)
            got0 = real
            if e.kind == "spec":
                toks, acc = self._fetch(*e.arrays, copies_started=True)
                real += self._apply_spec(toks, acc, e.rows)
            elif e.kind == "decode":
                toks = self._fetch(e.arrays[0], copies_started=True)
                if toks.ndim == 1:
                    toks = toks[None]  # single step: [B] -> [1, B]
                real += self._apply_decode(toks, e.rows)
            else:  # "first": a prefill round's completing rows
                firsts = self._fetch(e.arrays[0], copies_started=True)
                for i, req in e.rows:
                    real += self._apply_first_token(i, req,
                                                    int(firsts[i]))
            if hsp is not None:
                _obs.finish_span(hsp, tokens=real - got0)
        self._harvested_step += real
        return real

    # -- public API ------------------------------------------------------
    @property
    def chunked(self) -> bool:
        return self.prefill_chunk is not None

    @property
    def warmed_up(self) -> bool:
        """True once every compiled phase this configuration can
        dispatch has run at least once — i.e. no first-call XLA
        compile remains. The supervisor keeps a step under the roomy
        ``warmup_budget`` until then: phases compile lazily at their
        FIRST DISPATCH (the decode program's can be many steps after
        step 1 in chunked mode), and multi-second compile latency must
        not be diagnosed as a hang."""
        if self.role == "prefill_only":
            need = {"prefill"}  # this engine never dispatches decode
        else:
            need = {"prefill", "decode"}
            if self.decode_chunk > 1:
                need.add("decode_chunk")
        return need <= self._phases_run

    def _slo_handles(self, tenant: str):
        """(ttft, itl, queue-delay) histogram handles for one tenant's
        series (labels ``engine=<id>, tenant=<t>``), cached so the
        per-token path pays one dict hit, not a registry walk. Past the
        registry cardinality cap the handles stay fully live — exports
        fold them into the ``obs_overflow`` series instead."""
        hs = self._slo_hists.get(tenant)
        if hs is None:
            reg = _obs_registry()
            lab = {**self._obs_labels, "tenant": str(tenant)}
            hs = (
                reg.histogram("serving_ttft_seconds", lab,
                              help="seconds from submission to first token"),
                reg.histogram("serving_itl_seconds", lab,
                              help="inter-token latency seconds"),
                reg.histogram("serving_queue_delay_seconds", lab,
                              help="seconds from submission to slot binding"),
            )
            self._slo_hists[tenant] = hs
        return hs

    def _tenant_requests(self, tenant: str):
        """Per-tenant submission counter handle
        (``serving_tenant_requests_total``) — separate name from
        ``serving_requests_total`` so the envelope's fleet total never
        double-counts."""
        h = self._c_tenant_req.get(tenant)
        if h is None:
            h = _obs_registry().counter(
                "serving_tenant_requests_total",
                {**self._obs_labels, "tenant": str(tenant)},
                help="requests submitted, by tenant")
            self._c_tenant_req[tenant] = h
        return h

    def add_request(self, req_id, prompt, max_new_tokens: int = 32,
                    deadline=None, priority: str = "interactive",
                    retries: int = 0, trace=None,
                    tenant: str = "default"):
        """``deadline``: seconds or a ``Deadline`` — the request's total
        budget (queue wait included). None = no deadline. ``priority``
        is the admission class ("interactive" | "batch") — only
        meaningful with admission control on, but always recorded.
        ``retries`` seeds the recovery counter (cluster router /
        journal replay resubmissions carry prior engine deaths so
        poison quarantine counts per REQUEST, not per replica).
        ``trace`` is an optional upstream trace context (a Span, a
        ``{"trace_id", "span_id"}`` dict, or any object carrying those
        attributes): when given, this request's spans parent under it;
        otherwise a fresh trace is minted here. ``tenant`` names the
        submitting tenant — it labels this request's SLO histogram
        series and rides every downstream leg (journal, cluster wire
        record, disagg handoff).
        Returns the :class:`GenRequest`; with admission control a shed
        submission comes back immediately with ``status == "shed"``
        (it is also surfaced through the completed map)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        priority_rank(priority)  # validate before accepting anything
        if prompt.size == 0:
            raise ValueError("prompt length 0 not in [1, ...]")
        if not self.chunked and prompt.size > self.prompt_pad:
            raise ValueError(
                f"prompt length {prompt.size} not in [1, prompt_pad="
                f"{self.prompt_pad}] (enable prefill_chunk to serve "
                "prompts beyond the whole-prompt pad)")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        dl = None if deadline is None else Deadline.coerce(deadline)
        req = GenRequest(req_id, prompt, max_new_tokens, deadline=dl,
                         t_submit=time.perf_counter(), priority=priority,
                         retries=int(retries), tenant=str(tenant))
        if self._blocks_needed(req) > self.manager.num_blocks:
            raise ValueError(
                f"request needs {self._blocks_needed(req)} blocks but the "
                f"pool only has {self.manager.num_blocks} — it could never "
                "be admitted")
        ctx = _obs.trace_ctx(trace)
        req.trace_id = (ctx or {}).get("trace_id") or _obs.new_trace_id()
        self._c_requests.inc()
        self._tenant_requests(req.tenant).inc()
        with _obs.span("admission", trace_id=req.trace_id, parent=ctx,
                       tid="serve", req=str(req_id),
                       priority=priority, tenant=req.tenant) as sp:
            req.span_id = sp.span_id
            out = self._decide_admission(req)
            sp.args["verdict"] = ("shed" if out.status == "shed"
                                  else "admit")
        return out

    def _decide_admission(self, req: GenRequest) -> GenRequest:
        """The admission verdict path (chaos gate + overload control) —
        the body the request's root ``admission`` span wraps."""
        # chaos site: the front door (drop = the submission is shed)
        if not _chaos.inject("serving.submit"):
            return self._shed(req, "chaos-drop")
        if self.admission is None:
            self._queue.append(req)
            return req
        # dead queue entries must not count against the arrival: sweep
        # deadline-lapsed requests (zero token cost) before the load
        # snapshot, or a queue full of expired work would shed live
        # traffic as 'queue-full'/'deadline-infeasible'
        self._expire_queued()
        # decide() reads a fresh load snapshot, but tightening
        # observations only run from step(): the level-hold hysteresis
        # is denominated in ENGINE STEPS, so an arrival burst between
        # steps cannot ratchet the admission level on a stale
        # service-rate estimate. A DRAINED engine never steps, though —
        # without the relax-only tick below, an elevated level would
        # latch forever (shed submissions create no pending work, so
        # nothing ever drives the decay).
        load = self.load()
        if load.active_slots == 0 and load.queue_depth == 0:
            self.admission.observe(load, allow_tighten=False)
        verdict, reason = self.admission.decide(req, load)
        if verdict == "shed":
            return self._shed(req, reason)
        if verdict == "displace":
            # queue full, arrival is interactive: the worst queued
            # batch request (last in priority/deadline order) absorbs
            # the shed so latency-sensitive traffic still gets in. The
            # victim decide() saw can vanish if a step runs between the
            # load snapshot and here — then the queue has room anyway,
            # or (still full of interactive) the arrival is shed.
            victim = next((r for r in reversed(self._queue)
                           if priority_rank(r.priority) >= 1), None)
            if victim is not None:
                try:
                    self._queue.remove(victim)
                except ValueError:
                    victim = None
            if victim is not None:
                self._shed(victim, "displaced")
            elif len(self._queue) >= self.admission.config.max_queue:
                return self._shed(req, "queue-full")
        self._enqueue(req)
        return req

    def _shed(self, req: GenRequest, reason: str) -> GenRequest:
        req.status = "shed"
        req.shed_reason = reason
        self.n_shed[req.priority] = self.n_shed.get(req.priority, 0) + 1
        self._completed[req.req_id] = req
        self._pending_shed.append(req)
        return req

    def drain_shed(self) -> List[GenRequest]:
        """Return (and clear) the requests shed since the last drain.
        Sheds happen BETWEEN steps, so they never appear in a step()
        return — this is the supervisor's O(1)-per-shed way to harvest
        them (incl. displacement victims that were accepted earlier)."""
        out, self._pending_shed = self._pending_shed, []
        return out

    def _enqueue(self, req: GenRequest):
        """Priority insert: interactive ahead of batch; within a class,
        WFQ virtual-finish tag first (0.0 for every request when WFQ is
        off — ordering is then exactly the pre-WFQ behaviour), then
        tighter deadline first (unbounded budgets last, arrival order
        preserved — the sort key is fixed at insert time)."""
        rem = (float("inf") if req.deadline is None
               else req.deadline.remaining())
        tag = 0.0
        if self.admission is not None and self.admission.wfq_enabled:
            start, tag = self.admission.wfq_tag(
                req.tenant, self.admission._cost(req))
            req._wfq_start = start
        req._okey = (priority_rank(req.priority), tag, rem)
        lo = 0
        while lo < len(self._queue) and self._queue[lo]._okey <= req._okey:
            lo += 1
        self._queue.insert(lo, req)

    def requeue(self, req: GenRequest):
        """Re-enter an ALREADY-ACCEPTED request (the supervisor's
        recovery path): bypasses admission control — accepted work is
        never shed for load — and resets generation progress so the
        rebuilt engine reproduces the full output from scratch (greedy
        decode keeps survivors token-exact). One exception: a request
        THIS engine can never serve (journal replayed onto a smaller
        pool / shorter max_len / tighter prompt_pad) is shed instead of
        queued — a permanently unadmittable queue head would livelock
        every request behind it."""
        req.out, req.times, req.status = [], [], "ok"
        if not req.t_submit:
            req.t_submit = time.perf_counter()
        self._completed.pop(req.req_id, None)
        if (int(req.prompt.size) + req.max_new_tokens > self.max_len
                or self._blocks_needed(req) > self.manager.num_blocks
                or (not self.chunked
                    and int(req.prompt.size) > self.prompt_pad)):
            self._shed(req, "unservable-on-this-engine")
            return
        if self.admission is not None:
            self._enqueue(req)
        else:
            self._queue.append(req)

    def fence(self):
        """Retire this engine: every subsequent ``step()`` raises
        :class:`EngineFenced`. Called by the supervisor before it
        rebuilds, so an abandoned hung step thread that later wakes up
        cannot mutate requests now owned by the replacement engine."""
        self._fenced = True

    def _kv_occupancy(self) -> float:
        """Allocated fraction of the KV block pool — the one definition
        the load signal, the pause watermark, and the clamp watermark
        all share."""
        return 1.0 - self.manager.free_blocks / max(self.manager.num_blocks,
                                                    1)

    def load(self) -> EngineLoad:
        """Live load snapshot (the admission controller's input and the
        router/health surface): queue depth + class mix, KV occupancy,
        committed-token backlog, and the measured service rate."""
        # snapshot-style reads throughout: health()/router probes call
        # this from outside the step thread, so a slot may finish (or
        # the queue mutate) mid-scan — bind each reference once and
        # tolerate a request vanishing between reads
        queue = list(self._queue)
        backlog = sum(int(r.prompt.size) + r.max_new_tokens for r in queue)
        backlog_inter = sum(int(r.prompt.size) + r.max_new_tokens
                            for r in queue
                            if priority_rank(r.priority) == 0)
        for slot in self._slots:
            req = slot.req
            if req is not None:
                ahead = (int(req.prompt.size) - slot.prefill_pos
                         + slot.remaining)
                backlog += ahead
                backlog_inter += ahead  # in-flight work delays everyone
        tps = self.ewma_step_tokens or float(
            self.max_num_batched_tokens or self.B)
        delay = (backlog / max(tps, 1e-9)) * (self.ewma_step_s or 0.0)
        cfg = self.admission.config if self.admission is not None else None
        # alertable gauges (ISSUE 15): the alert engine and the fleet
        # aggregator read saturation through the registry, not through
        # EngineLoad objects — refresh them wherever load is snapshotted
        reg = _obs_registry()
        qf = (len(queue) / cfg.max_queue
              if cfg is not None and cfg.max_queue else 0.0)
        reg.gauge("serving_queue_frac", self._obs_labels).set(qf)
        reg.gauge("serving_kv_occupancy", self._obs_labels).set(
            self._kv_occupancy())
        reg.gauge("serving_est_queue_delay_s", self._obs_labels).set(
            delay)
        return EngineLoad(
            queue_depth=len(queue),
            queue_limit=None if cfg is None else cfg.max_queue,
            queued_interactive=sum(
                priority_rank(r.priority) == 0 for r in queue),
            queued_batch=sum(
                priority_rank(r.priority) >= 1 for r in queue),
            token_backlog_interactive=backlog_inter,
            active_slots=self.num_active,
            max_batch=self.B,
            prefilling=self.num_prefilling,
            kv_free_blocks=self.manager.free_blocks,
            kv_total_blocks=self.manager.num_blocks,
            kv_occupancy=self._kv_occupancy(),
            token_backlog=backlog,
            tokens_per_step=tps,
            ewma_step_s=self.ewma_step_s,
            est_queue_delay_s=delay,
            admission_level=0 if self.admission is None
            else self.admission.level,
            prefill_paused=self.prefill_paused,
            n_shed_interactive=self.n_shed.get("interactive", 0),
            n_shed_batch=self.n_shed.get("batch", 0),
            n_expired=self.n_expired,
            host_blocked_frac=self.ewma_blocked_frac or 0.0,
            dispatch_depth=len(self._ring),
        )

    def prefix_stats(self) -> dict:
        """Prefix-cache counters (zeros when the cache is off): the
        router's affinity feedback and the bench's hit-rate source.
        ``hit_rate`` is cached tokens / prompt tokens that entered a
        slot — the fraction of prefill work the cache saved."""
        total = self.prefill_tokens + self.prefix_hit_tokens
        base = {
            "enabled": self.prefix_cache is not None,
            "hit_tokens": self.prefix_hit_tokens,
            "prefill_tokens": self.prefill_tokens,
            "forks": self.prefix_forks,
            "hit_rate": (self.prefix_hit_tokens / total) if total else 0.0,
        }
        if self.prefix_cache is not None:
            tree = self.prefix_cache.stats()
            # NB: only tree-shape keys — the cache's own hits/hit_tokens
            # are LOOKUP-side tallies (a head-of-line-blocked request
            # re-probes every step) and must not clobber the engine's
            # adopted-token truth above
            base.update({
                "nodes": tree["nodes"],
                "lookups": tree["lookups"],
                "evicted_blocks": tree["evicted_blocks"],
            })
        if self.cache_tier is not None:
            base["tier"] = dict(self.cache_tier.stats(),
                                restores=self.tier_restores,
                                restore_tokens=self.tier_restore_tokens)
        return base

    def spec_stats(self) -> dict:
        """Speculative-decoding counters (zeros when off), the
        acceptance-rate feedback the bench rows report. A slot in a
        verify dispatch always emits >= 1 token where the plain decode
        path emits exactly 1, so ``tokens_per_slot_round`` is the
        realized per-slot decode-speed multiplier."""
        return {
            "enabled": self.spec_k is not None,
            "k": self.spec_k,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
            "dispatches": self.spec_dispatches,
            "emitted": self.spec_emitted,
            "tokens_per_slot_round": (
                self.spec_emitted / self.spec_slot_rounds
                if self.spec_slot_rounds else 0.0),
        }

    def _append_token(self, req: GenRequest, tok: int):
        req.out.append(tok)
        now = time.perf_counter()
        req.times.append(now)
        # SLO histograms: the ONE token-emission point feeds TTFT and
        # inter-token latency for every path (prefill first token,
        # decode, spec verify, KV import) — on the request's tenant
        # series (cached handle lookup, one dict hit)
        h_ttft, h_itl, _ = self._slo_handles(req.tenant)
        if len(req.times) == 1:
            h_ttft.observe(now - req.t_submit)
        else:
            h_itl.observe(now - req.times[-2])

    @staticmethod
    def _finish_req_spans(req: GenRequest, **args) -> None:
        """Close any open per-request spans (prefill/decode) — called
        at completion, expiry, and handoff release."""
        for attr in ("_sp_prefill", "_sp_decode"):
            sp = getattr(req, attr, None)
            if sp is not None:
                _obs.finish_span(sp, **args)
                setattr(req, attr, None)

    def _expire(self, req: GenRequest):
        req.status = "expired"
        self.n_expired += 1
        self._finish_req_spans(req, error="expired")
        self._completed[req.req_id] = req

    def _expire_queued(self):
        """Fast path (no token budget spent): ANY queued request whose
        deadline lapsed before its first prefill chunk finishes as
        ``expired`` right here — not just the head-of-line one the
        admission loop happens to look at."""
        live = []
        for r in self._queue:
            if r.expired():
                self._expire(r)
            else:
                live.append(r)
        if len(live) != len(self._queue):
            self._queue[:] = live

    def _evict_expired(self):
        """Reclaim slots whose request's deadline passed: free the
        blocks, point the row at the trash block, surface the request as
        completed-with-status-expired. Works mid-prefill too — a
        partially prefilled slot's blocks recycle the same way (the
        trash table makes the half-written KV unreachable)."""
        for slot_idx, slot in enumerate(self._slots):
            if slot.active and slot.req.expired():
                self.manager.free_sequence(slot.req.req_id)
                self._tables[slot_idx] = self._trash
                self._expire(slot.req)
                if self._graft_ledger is not None:
                    self._graft_ledger.release(
                        "engine.slot", slot.req.req_id)
                slot.req = None
                slot.pending_first = False
                self._mark_dirty(slot_idx)
        # handoff-ready work whose budget lapsed before export: the
        # blocks recycle and the request closes here — a dead client's
        # KV must not sit pinned waiting for a transfer nobody needs
        for rid in [r for r, q in self._handoff_ready.items()
                    if q.expired()]:
            req = self._handoff_ready.pop(rid)
            self.manager.free_sequence(rid)
            if self._graft_ledger is not None:
                self._graft_ledger.release("handoff.hold", rid)
            self._expire(req)

    @property
    def num_active(self):
        return sum(s.active for s in self._slots)

    @property
    def num_prefilling(self):
        return sum(s.prefilling for s in self._slots)

    def _blocks_needed(self, req, max_new_tokens: Optional[int] = None):
        new = req.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if self.role == "prefill_only":
            # no decode happens here: the block budget is the prompt
            # alone (padded-prefill writes past the owned blocks land
            # in the trash row by the OOB-drop scatter contract)
            new = 0
        if self.chunked:
            total = int(req.prompt.size) + new
        else:
            total = max(int(req.prompt.size) + new, self.prompt_pad)
        return self.manager.blocks_for(total)

    def _copy_block(self, src: int, dst: int) -> None:
        """Copy one physical block's KV across every layer pool — the
        device-side half of a copy-on-write fork (rare: only when a
        prefill write starts INSIDE an adopted shared block, i.e. a
        fully-cached prompt recomputing its last token). One compiled
        program with DONATED pools (src/dst are traced scalars, so
        every fork shares it): XLA updates the block in place instead
        of materializing a fresh full-size pool per layer. Scale pools
        (int8 KV) copy the same row — a forked block's quantization
        scales travel with its bytes."""
        if self._copy_jit is None:
            def copy_block(pools, s, d):
                return [tuple(a.at[:, d].set(a[:, s]) for a in entry)
                        for entry in pools]

            self._copy_jit = jax.jit(copy_block, donate_argnums=(0,))
        self._pools = self._copy_jit(
            self._pools, jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32))

    # -- prefix namespaces + host tier ----------------------------------
    def _shared_prefix_len(self, prompt) -> int:
        """Longest registered shared system prompt that prefixes
        ``prompt``, rounded DOWN to full blocks (only full blocks enter
        the tree)."""
        best = 0
        psize = int(prompt.size)
        for sp in self._shared_prefixes:
            n = int(sp.size)
            if n <= psize and n > best and np.array_equal(prompt[:n], sp):
                best = n
        return (best // self.block_size) * self.block_size

    def _prefix_lookup(self, req):
        """Namespace-aware radix lookup: the tenant's own tree, or the
        shared-system-prompt tree when it covers more of the prompt."""
        if not self._tenant_ns:
            return self.prefix_cache.lookup(req.prompt)
        n_t, b_t = self.prefix_cache.lookup(req.prompt, ns=req.tenant)
        n_s, b_s = self.prefix_cache.lookup(req.prompt, ns="*")
        return (n_t, b_t) if n_t >= n_s else (n_s, b_s)

    def _prefix_insert(self, req, blocks) -> None:
        """Register a freshly prefilled prompt's blocks: the tenant's
        namespace (or the default tree), plus the shared namespace for
        any registered system-prompt prefix (same physical blocks,
        multi-pinned — COW sharing across tenants), plus a write-
        through spill of the full-block prefix to the host tier."""
        ns = req.tenant if self._tenant_ns else None
        self.prefix_cache.insert(req.prompt, blocks, ns=ns)
        if self._tenant_ns:
            sh = self._shared_prefix_len(req.prompt)
            if sh:
                self.prefix_cache.insert(
                    req.prompt[:sh], blocks[:sh // self.block_size],
                    ns="*")
        self._tier_spill(req, ns)

    def _tier_spill(self, req, ns) -> None:
        if self.cache_tier is None:
            return
        full = (int(req.prompt.size) // self.block_size) * self.block_size
        if not full:
            return
        try:
            pages, scales, meta = self.manager.export_blocks(
                req.req_id, self._pools, num_tokens=full)
            self.cache_tier.put(ns, req.prompt[:full], pages, scales, meta)
            if self._tenant_ns:
                sh = self._shared_prefix_len(req.prompt)
                if sh:
                    k = sh // self.block_size
                    self.cache_tier.put(
                        "*", req.prompt[:sh], pages[:, :, :, :k],
                        None if scales is None
                        else scales[:, :, :, :k],
                        dict(meta, num_blocks=k))
        except Exception:
            # spill is strictly best-effort: a failed export must never
            # fail the request that triggered it (chaos 'error' lands
            # here too — the frame is simply not stored, i.e. a miss)
            pass

    def _tier_restore(self, req) -> None:
        """Read-through: when the host tier holds a longer prefix than
        the HBM radix tree, import it into fresh blocks and pin it —
        the normal adoption path then treats it as an ordinary hit.
        Any failure (CRC-rejected frame, pool too full even after
        eviction) is a miss, never an error."""
        if self.cache_tier is None:
            return
        cached_len, _ = self._prefix_lookup(req)
        ns = req.tenant if self._tenant_ns else None
        hit = self.cache_tier.lookup(
            ns, req.prompt, block_size=self.block_size,
            min_tokens=cached_len)
        if hit is None and self._tenant_ns:
            hit = self.cache_tier.lookup(
                "*", req.prompt, block_size=self.block_size,
                min_tokens=cached_len)
        if hit is None:
            return
        n_tokens, pages, scales, meta = hit
        need = int(meta["num_blocks"])
        if need > self.manager.free_blocks:
            self.prefix_cache.evict(need - self.manager.free_blocks)
        sid = ("__tier__", self._tier_seq)
        self._tier_seq += 1
        try:
            self._pools, blocks = self.manager.import_blocks(
                sid, pages, scales, meta, self._pools)
        except (BlockImportError, ValueError):
            return  # pool genuinely full / config drift: plain miss
        # pin under the tree first (new nodes take their own refs),
        # then drop the import's ownership — surviving refs are the
        # cache pins alone, exactly like post-free_sequence reuse
        self.prefix_cache.insert(req.prompt[:n_tokens], blocks, ns=ns)
        self.manager.free_sequence(sid)
        self.tier_restores += 1
        self.tier_restore_tokens += int(n_tokens)

    def _reserve_blocks(self, req, eff_new: int):
        """Block-availability half of slot binding, prefix-cache aware.
        Looks up the prompt's cached prefix, ADOPTS those blocks
        (ref-counted — they can no longer be evicted out from under
        this request), and checks the remaining shortfall against the
        free list, reclaiming LRU cache entries when it runs short.
        Returns ``(ok, cached_len, will_fork)``; on ``ok=False`` the
        adoption is undone and nothing else was mutated (head-of-line
        wait, exactly like the old ``can_allocate`` gate).

        ``cached_len`` is capped at ``prompt.size - 1``: the first
        generated token comes from the last prompt position's logits,
        which only a real prefill dispatch produces — so a FULLY cached
        prompt recomputes one token, and because that write position
        lands INSIDE the last shared block, ``will_fork`` asks the
        caller to copy-on-write it first."""
        psize = int(req.prompt.size)
        cached_len, cached_blocks = 0, []
        if self.prefix_cache is not None:
            self._tier_restore(req)
            cached_len, cached_blocks = self._prefix_lookup(req)
            if cached_len >= psize:
                cached_len = psize - 1
        will_fork = bool(cached_len % self.block_size)
        need = (self._blocks_needed(req, eff_new) - len(cached_blocks)
                + (1 if will_fork else 0))
        if cached_blocks:
            # the `need > free_blocks` bail-out below undoes this adopt
            # under the SAME `cached_blocks` guard (path-correlated
            # conditions the analyzer cannot relate)
            self.manager.adopt(req.req_id, cached_blocks)  # graft-lint: disable=OWN001
        if need > self.manager.free_blocks and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.manager.free_blocks)
        if need > self.manager.free_blocks:
            if cached_blocks:
                self.manager.free_sequence(req.req_id)
            return False, 0, False
        return True, cached_len, will_fork

    def _admit(self) -> int:
        """Fill free slots from the queue while blocks last. Whole-
        prompt mode runs one padded prefill per admission (per-slot
        isolation via the trash table); chunked mode only binds the
        slot and reserves its full block budget — the token-budget
        scheduler feeds the prompt in chunks. Returns the number of
        real tokens processed (whole-prompt admissions only).

        Degraded modes (admission control only): when KV occupancy
        crosses ``kv_pause_watermark`` NEW admissions pause — in-flight
        decode keeps draining and freeing blocks — and above
        ``kv_clamp_watermark`` batch-class token grants are clamped to
        ``batch_clamp_tokens`` at slot-binding time (shrinking the
        block reservation with them)."""
        cfg = self.admission.config if self.admission is not None else None
        if cfg is not None:
            if self._kv_occupancy() >= cfg.kv_pause_watermark:
                if self.prefix_cache is not None:
                    # reclaimable cached prefixes must not trip the
                    # degraded pause into a permanent stall: free
                    # enough idle cache to get back under the watermark
                    # before concluding the pool is genuinely scarce
                    want = int(np.ceil(
                        (1.0 - cfg.kv_pause_watermark)
                        * self.manager.num_blocks)) + 1
                    self.prefix_cache.evict(
                        max(want - self.manager.free_blocks, 0))
                if self._kv_occupancy() >= cfg.kv_pause_watermark:
                    self.prefill_paused = True
                    return 0
            self.prefill_paused = False
        used = 0
        for slot_idx, slot in enumerate(self._slots):
            # admission rejects requests whose budget already expired
            # while queued (the client gave up; don't burn a prefill).
            # NOTE on ordering here and below: a supervisor recovering
            # a hung step snapshots queue → slots → completed and
            # relies on every request being visible in AT LEAST ONE of
            # those at any instant — so a request is expired/bound
            # BEFORE it leaves the queue (briefly visible twice, never
            # zero times; the supervisor dedups).
            while self._queue and self._queue[0].expired():
                self._expire(self._queue[0])
                self._queue.pop(0)
            if not self._queue or slot.active:
                continue
            req = self._queue[0]
            # degraded mode: decide the clamp BEFORE the admission
            # gate (under real KV scarcity the clamped footprint is
            # exactly what makes the batch request admittable), but
            # APPLY it only after the gate passes — a request merely
            # peeked at during a transient pressure spike must not
            # keep a stale clamp
            clamp = (cfg is not None and cfg.batch_clamp_tokens is not None
                     and priority_rank(req.priority) >= 1
                     and req.max_new_tokens > cfg.batch_clamp_tokens
                     and self._kv_occupancy() >= cfg.kv_clamp_watermark)
            eff_new = cfg.batch_clamp_tokens if clamp else req.max_new_tokens
            ok, cached_len, will_fork = self._reserve_blocks(req, eff_new)
            if not ok:
                break  # head-of-line; keep FIFO fairness
            if clamp:
                req.max_new_tokens = int(cfg.batch_clamp_tokens)
                req.clamped = True
            self.manager.allocate(
                req.req_id, self._blocks_needed(req) * self.block_size)
            if will_fork:
                # the first prefill write (position cached_len) lands
                # inside the last ADOPTED block: copy-on-write it so
                # the cache (and any other reader) keeps its bytes
                old, new = self.manager.fork(
                    req.req_id, cached_len // self.block_size)
                if new != old:
                    self._copy_block(old, new)
                    self.prefix_forks += 1
            self.prefix_hit_tokens += cached_len
            blocks = self.manager.owned_blocks(req.req_id)
            row = self.manager.table_row(
                req.req_id, self.max_blocks_per_seq, fill=self._trash)
            self._tables[slot_idx] = row
            slot.req = req
            if self._graft_ledger is not None:
                self._graft_ledger.acquire("engine.slot", req.req_id)
            slot.remaining = req.max_new_tokens
            slot.pending_first = False
            self._mark_dirty(slot_idx)
            self._slo_handles(req.tenant)[2].observe(
                time.perf_counter() - req.t_submit)
            req._sp_prefill = _obs.start_span(
                "prefill", parent=req, tid="serve",
                prompt_tokens=int(req.prompt.size),
                cached_tokens=int(cached_len))
            self._queue.pop(0)  # bound above: leaves the queue LAST
            if self.admission is not None:
                # WFQ service feedback: virtual time advances to the
                # start tag of the request entering service
                self.admission.wfq_served(getattr(req, "_wfq_start",
                                                  None))

            if self.chunked:
                slot.prefill_pos = cached_len
                slot.cache_len = cached_len
                continue

            psize = int(req.prompt.size)
            rem = psize - cached_len  # >= 1 by the cached_len cap
            slot.prefill_pos = psize
            slot.cache_len = psize
            # isolated prefill: only this row's table points at real
            # blocks; every other row scatters into the trash block.
            # A cache hit starts the write at the cached offset and
            # feeds only the un-cached remainder of the prompt.
            iso = np.full_like(self._tables, self._trash)
            iso[slot_idx] = row
            ids = np.zeros((self.B, self.prompt_pad), np.int32)
            ids[slot_idx, :rem] = req.prompt[cached_len:]
            cl = np.zeros((self.B,), np.int32)
            cl[slot_idx] = cached_len
            last_idx = np.zeros((self.B,), np.int32)
            last_idx[slot_idx] = rem - 1
            if self._prefill_jit is None:
                self._build_jits()
            firsts, self._pools = self._run_jit(
                self._prefill_jit, self._pools, self._h2d(ids),
                self._h2d(iso), self._h2d(cl), self._h2d(last_idx))
            self._phases_run.add("prefill")
            used += rem
            self.prefill_tokens += rem
            if self.prefix_cache is not None:
                # the prompt's full blocks now hold its exact KV: pin
                # them for reuse BEFORE a possible same-step finish
                # frees the sequence's own references
                self._prefix_insert(req, blocks)
            if self.overlap:
                # the first token rides the copy ring; until it lands
                # the slot must not join a decode dispatch
                slot.pending_first = True
                self._push_entry("first", (firsts,), [(slot_idx, req)])
            else:
                first = int(self._fetch(firsts)[slot_idx])
                self._apply_first_token(slot_idx, req, first)
        return used

    def _finish_if_done(self, slot_idx, last_tok) -> bool:
        slot = self._slots[slot_idx]
        req = slot.req
        done = slot.remaining <= 0 or (
            self.eos_token_id is not None and last_tok == self.eos_token_id)
        if done:
            self.manager.free_sequence(req.req_id)
            self._tables[slot_idx] = self._trash
            self._finish_req_spans(req, tokens=len(req.out))
            self._completed[req.req_id] = req
            slot.req = None
            if self._graft_ledger is not None:
                self._graft_ledger.release("engine.slot", req.req_id)
            slot.pending_first = False
            self._mark_dirty(slot_idx)
        return done

    def _apply_first_token(self, slot_idx: int, req: GenRequest,
                           first: int) -> int:
        """Host bookkeeping for a completed prefill's first generated
        token (inline in sync mode; at harvest, one step later, in
        overlap mode). Returns tokens emitted (0 when the slot was
        evicted while the token was in flight)."""
        slot = self._slots[slot_idx]
        if slot.req is not req:
            return 0  # evicted/reassigned while in flight: discard
        slot.pending_first = False
        sp = getattr(req, "_sp_prefill", None)
        if sp is not None:
            _obs.finish_span(sp)
            req._sp_prefill = None
        self._append_token(req, first)
        slot.remaining -= 1
        self._mark_dirty(slot_idx)
        if self.role != "prefill_only":
            # prefill-only engines never decode: the decode span opens
            # on the decode worker at KV import instead
            req._sp_decode = _obs.start_span("decode", parent=req,
                                             tid="serve")
        if not self._finish_if_done(slot_idx, first) \
                and self.role == "prefill_only":
            self._to_handoff(slot_idx)
        return 1

    def _apply_decode(self, toks: np.ndarray, rows) -> int:
        """Credit one decode dispatch's tokens ([K, B]) to its rows.
        The identity guard discards the ≤1-step over-issue: a row whose
        request finished or was evicted after dispatch no longer owns
        its slot, and its extra token must not be appended (the sync
        loop would never have produced it)."""
        n = 0
        for i, req in rows:
            slot = self._slots[i]
            if slot.req is not req:
                continue
            for j in range(toks.shape[0]):
                t = int(toks[j, i])
                self._append_token(req, t)
                slot.cache_len += 1
                slot.remaining -= 1
                self.decode_tokens += 1
                n += 1
                if self._finish_if_done(i, t):
                    break
        return n

    def _apply_spec(self, toks: np.ndarray, acc: np.ndarray, rows) -> int:
        """Credit one speculative verify dispatch: emit the accepted
        prefix + bonus token per row, clamped by the row's remaining
        budget, with the same over-issue identity guard."""
        self.spec_dispatches += 1
        emitted = 0
        charged = 0
        for i, req, n_real in rows:
            self.spec_slot_rounds += 1
            charged += self.spec_k + 1
            slot = self._slots[i]
            if slot.req is not req:
                continue
            m = min(int(acc[i]) + 1, slot.remaining)
            self.spec_proposed += n_real
            self.spec_accepted += min(int(acc[i]), n_real)
            for j in range(m):
                t = int(toks[i, j])
                self._append_token(req, t)
                slot.cache_len += 1
                slot.remaining -= 1
                self.decode_tokens += 1
                self.spec_emitted += 1
                emitted += 1
                if self._finish_if_done(i, t):
                    break
        # the budget is charged the k+1 dispatched positions per slot,
        # but only the emitted tokens drain real backlog — step() feeds
        # the difference back out of the service-rate telemetry
        self._step_spec_overcharge += charged - emitted
        return emitted

    # -- disaggregated prefill/decode handoff ---------------------------
    def _to_handoff(self, slot_idx: int) -> None:
        """Prefill-role slot release: the prompt's KV is complete and
        the first token attached, so the SLOT frees for the next prompt
        while the BLOCKS stay allocated under the req_id until
        ``export_kv`` + ``release_handoff`` (or deadline expiry). The
        table row is trashed — no further dispatch may touch the rows
        being exported."""
        slot = self._slots[slot_idx]
        req = slot.req
        self._handoff_ready[req.req_id] = req
        self._tables[slot_idx] = self._trash
        slot.req = None
        if self._graft_ledger is not None:
            # the slot frees; the HOLD on the exported blocks begins
            self._graft_ledger.release("engine.slot", req.req_id)
            self._graft_ledger.acquire("handoff.hold", req.req_id)
        slot.pending_first = False
        self._mark_dirty(slot_idx)

    def drain_prefilled(self) -> List[GenRequest]:
        """Return (and claim) the requests whose prefill finished since
        the last drain — the handoff layer's pickup counter. Each
        returned request still OWNS its KV blocks; the caller must
        ``export_kv`` + ``release_handoff`` (successful transfer) or
        ``release_handoff`` alone (abandon: blocks recycle, the caller
        re-routes the request)."""
        out = list(self._handoff_ready.values())
        self._handoff_ready.clear()
        return out

    def export_kv(self, req_id, kv_len: Optional[int] = None):
        """Gather a handoff-ready request's KV blocks into host arrays:
        ``(pages, scales, meta)`` per
        :meth:`~paddle_tpu.ops.paged_attention.BlockManager.export_blocks`,
        with ``meta["kv_len"]`` = the positions actually written
        (``kv_len``, normally the prompt length the caller drained).
        IDEMPOTENT — blocks stay allocated, so a failed transfer leg
        re-exports the identical bytes; call :meth:`release_handoff`
        only once the transfer is acked."""
        if not _chaos.inject("handoff.export"):
            raise ConnectionResetError(
                "chaos: KV export dropped (lost message)")
        if kv_len is None:
            kv_len = (len(self.manager.owned_blocks(req_id))
                      * self.block_size)
        # read-only gather: the handoff hold is keyed by the CALLER's
        # req_id and handed back by the return — the caller settles it
        # via release_handoff on every path (see _begin_handoff)
        pages, scales, meta = self.manager.export_blocks(  # graft-lint: disable=OWN001
            req_id, self._pools, num_tokens=int(kv_len))
        meta["kv_len"] = int(min(
            int(kv_len), meta["num_blocks"] * self.block_size))
        return pages, scales, meta

    def release_handoff(self, req_id) -> None:
        """Drop the exported request's block ownership (transfer acked,
        or the caller is abandoning the handoff): blocks recycle via
        the ref-counted free — prefix-cache pins survive."""
        self.manager.free_sequence(req_id)
        if self._graft_ledger is not None:
            self._graft_ledger.release("handoff.hold", req_id)
        self.n_handed_off += 1

    def import_kv(self, req: GenRequest, first_token: int,
                  pages, scales, meta) -> None:
        """Decode-side entry for a transferred prompt: place the
        exported blocks into this engine's pool (fresh physical ids),
        bind a free slot, and resume decode at the cached offset with
        ``first_token`` already emitted (it came from the prefill
        engine's logits — the decode dispatch that follows writes its
        KV at position ``kv_len`` exactly as a local prefill's first
        decode would).

        Raises :class:`~paddle_tpu.ops.paged_attention.BlockImportError`
        (transient: retry under the request's deadline) when no slot or
        not enough free blocks are available RIGHT NOW; ValueError for
        config mismatches no retry can fix. Failure leaves no state
        behind — the import is atomic."""
        if self._fenced:
            raise EngineFenced(
                "engine was retired by its supervisor; a replacement "
                "already owns the requests")
        psize = int(req.prompt.size)
        kv_len = int(meta.get("kv_len", meta["num_blocks"]
                              * self.block_size))
        if kv_len != psize:
            raise ValueError(
                f"import_kv: transferred kv_len {kv_len} != prompt "
                f"length {psize}")
        if psize + req.max_new_tokens > self.max_len:
            raise ValueError(
                "import_kv: prompt + max_new_tokens exceeds max_len")
        slot_idx = next(
            (i for i, s in enumerate(self._slots) if not s.active), None)
        if slot_idx is None:
            raise BlockImportError(
                "no free decode slot for the imported request")
        total = self.manager.blocks_for(psize + req.max_new_tokens)
        if total > self.manager.num_blocks:
            # permanent: the pool can NEVER fit this payload (smaller
            # pool than the exporter's) — must be ValueError so the
            # caller falls back to colocated serving instead of
            # retrying a BlockImportError that can't ever succeed
            raise ValueError(
                f"import_kv: needs {total} blocks to import + decode, "
                f"pool has {self.manager.num_blocks} TOTAL")
        if total > self.manager.free_blocks:
            raise BlockImportError(
                f"need {total} free blocks to import + decode, "
                f"{self.manager.free_blocks} free")
        self._pools, _ = self.manager.import_blocks(
            req.req_id, pages, scales, meta, self._pools)
        try:
            self.manager.allocate(req.req_id, psize + req.max_new_tokens)
        except RuntimeError as e:  # raced another import on the tail
            self.manager.free_sequence(req.req_id)
            raise BlockImportError(str(e)) from None
        self._tables[slot_idx] = self.manager.table_row(
            req.req_id, self.max_blocks_per_seq, fill=self._trash)
        slot = self._slots[slot_idx]
        req.out, req.times, req.status = [], [], "ok"
        if not req.t_submit:
            req.t_submit = time.perf_counter()
        slot.req = req
        if self._graft_ledger is not None:
            self._graft_ledger.acquire("engine.slot", req.req_id)
        slot.prefill_pos = psize
        slot.cache_len = psize
        slot.remaining = req.max_new_tokens
        slot.pending_first = False
        self._append_token(req, int(first_token))
        slot.remaining -= 1
        self.n_imported += 1
        self._mark_dirty(slot_idx)
        # the imported request's decode leg parents under whatever
        # context the handoff header carried (set by the caller on req)
        req._sp_decode = _obs.start_span("decode", parent=req,
                                         tid="serve", imported=True)
        self._finish_if_done(slot_idx, int(first_token))

    def _schedule_prefill(self, budget_left: int) -> Dict[int, int]:
        """Round-robin chunk scheduler: starting at the fairness
        pointer, grant each prefilling slot one ``prefill_chunk``-sized
        bite of its remaining prompt per pass until the leftover budget
        cannot cover the next bite. Returns {slot_idx: real tokens}."""
        chunk = self.prefill_chunk
        order = sorted(
            (i for i, s in enumerate(self._slots) if s.prefilling),
            key=lambda i: (i - self._rr) % self.B)
        sched = {i: 0 for i in order}
        used, progress = 0, True
        while progress:
            progress = False
            for i in order:
                slot = self._slots[i]
                rem = slot.req.prompt.size - slot.prefill_pos - sched[i]
                if rem <= 0:
                    continue
                real = min(chunk, int(rem))
                if used + real > budget_left:
                    return {i: n for i, n in sched.items() if n}
                sched[i] += real
                used += real
                progress = True
        return {i: n for i, n in sched.items() if n}

    def _prefill_step(self, budget_left: int) -> int:
        """Execute this step's scheduled prefill chunks: one batched
        dispatch per ROUND (every slot with work left advances one
        chunk per round — multiple rounds when the budget grants a slot
        several chunks). Each chunk writes its KV at the slot's current
        ``cache_len`` offset through the slot's own block-table row;
        non-participating rows are isolated via the trash table. The
        slot whose final chunk lands also gets its first generated
        token from that chunk's logits — no extra dispatch."""
        sched = self._schedule_prefill(budget_left)
        if not sched:
            return 0
        chunk = self.prefill_chunk
        used = 0
        if self._prefill_jit is None:
            self._build_jits()
        while sched:
            ids = np.zeros((self.B, chunk), np.int32)
            cl = np.zeros((self.B,), np.int32)
            last_idx = np.zeros((self.B,), np.int32)
            iso = np.full_like(self._tables, self._trash)
            round_rows = []
            for i in list(sched):
                slot = self._slots[i]
                start = slot.prefill_pos
                real = min(chunk, slot.req.prompt.size - start, sched[i])
                ids[i, :real] = slot.req.prompt[start:start + real]
                cl[i] = start
                last_idx[i] = real - 1
                iso[i] = self._tables[i]
                round_rows.append((i, start, real))
                sched[i] -= real
                if sched[i] <= 0:
                    del sched[i]
            firsts, self._pools = self._run_jit(
                self._prefill_jit, self._pools, self._h2d(ids),
                self._h2d(iso), self._h2d(cl), self._h2d(last_idx))
            self._phases_run.add("prefill")
            done_rows = []
            for i, start, real in round_rows:
                slot = self._slots[i]
                slot.prefill_pos = start + real
                slot.cache_len = slot.prefill_pos
                self.prefill_tokens += real
                used += real
                if slot.prefill_pos == slot.req.prompt.size:
                    if self.prefix_cache is not None:
                        # pin the finished prompt's full blocks before
                        # a same-chunk finish frees the sequence
                        self._prefix_insert(
                            slot.req,
                            self.manager.owned_blocks(slot.req.req_id))
                    done_rows.append((i, slot.req))
            if done_rows:
                if self.overlap:
                    for i, _ in done_rows:
                        self._slots[i].pending_first = True
                        self._mark_dirty(i)
                    self._push_entry("first", (firsts,), done_rows)
                else:
                    vals = self._fetch(firsts)  # [B] ints, not [B, chunk]
                    for i, req in done_rows:
                        self._apply_first_token(i, req, int(vals[i]))
        self._rr = (self._rr + 1) % self.B
        return used

    def _propose_drafts(self, active) -> Optional[tuple]:
        """Draft up to ``spec_k`` tokens per decode-phase slot from its
        full history (prompt + generated). Returns ``(drafts, n_real)``
        — [B, k] int32 (zero-padded) and {slot: real draft count} — or
        None when NO slot produced a draft (the round falls back to
        the plain decode path at zero dispatch cost)."""
        drafts = np.zeros((self.B, self.spec_k), np.int32)
        n_real: Dict[int, int] = {}
        any_draft = False
        for i in active:
            req = self._slots[i].req
            hist = np.concatenate(
                [req.prompt, np.asarray(req.out, np.int32)])
            d = np.asarray(
                self.proposer.propose(hist, self.spec_k),
                np.int32).reshape(-1)[: self.spec_k]
            n_real[i] = int(d.size)
            if d.size:
                drafts[i, : d.size] = d
                any_draft = True
        return (drafts, n_real) if any_draft else None

    def _spec_step(self, active, tok, tables, cl, fin, drafts,
                   n_real) -> int:
        """One SYNC speculative round: verify dispatch + host accept
        walk. Emits 1..k+1 tokens per slot (variable tokens/step);
        returns the k+1 real positions per slot the dispatch
        processed."""
        k = self.spec_k
        sp_d = (_obs.start_span(
            "dispatch", parent=self._slots[active[0]].req, tid="device",
            kind="spec", rows=len(active)) if _obs.enabled() else None)
        toks, acc, _, _, _, self._pools = self._run_jit(
            self._spec_jit, self._pools, self._h2d(tok, decode=True),
            self._h2d(tables, decode=True), self._h2d(cl, decode=True),
            self._h2d(fin, decode=True), self._h2d(drafts, decode=True))
        self._phases_run.add("spec_verify")
        self.n_dispatches += 1
        toks, acc = self._fetch(toks, acc)
        if sp_d is not None:
            _obs.finish_span(sp_d)
        rows = [(i, self._slots[i].req, n_real.get(i, 0)) for i in active]
        hsp = (_obs.start_span("harvest", parent=sp_d, tid="serve",
                               kind="spec") if sp_d is not None else None)
        self._apply_spec(toks, acc, rows)
        if hsp is not None:
            _obs.finish_span(hsp)
        return len(active) * (k + 1)

    def _decode_rows(self):
        return [i for i, s in enumerate(self._slots) if s.decode_ready]

    def _decode_step(self, budget_left: Optional[int]) -> int:
        """One SYNC decode round for every decode-phase slot
        (speculative verify, single step, or a ``decode_chunk`` scan).
        Returns real tokens scheduled."""
        if self.role == "prefill_only":
            return 0  # decode belongs to the other pool
        active = self._decode_rows()
        if not active:
            return 0
        if self._decode_jit is None:
            self._build_jits()
        tok = np.zeros((self.B,), np.int32)
        cl = np.zeros((self.B,), np.int32)
        fin = np.ones((self.B,), bool)
        for i in active:
            slot = self._slots[i]
            tok[i] = slot.req.out[-1]
            cl[i] = slot.cache_len
            fin[i] = False
        tables = self._tables
        if self.num_prefilling:
            # the decode program writes EVERY row's (tok, cl) — rows
            # mid-prefill hold real tables now, so their lane's dummy
            # write (token 0 at position 0) would corrupt the KV their
            # first chunk just laid down; point them at the trash block
            # for this dispatch (inactive rows are already trashed)
            tables = self._tables.copy()
            for i, s in enumerate(self._slots):
                if s.prefilling:
                    tables[i] = self._trash
        if self._spec_gate(active, budget_left):
            proposed = self._propose_drafts(active)
            if proposed is not None:
                return self._spec_step(active, tok, tables, cl, fin,
                                       *proposed)
        k = self.decode_chunk
        sp_d = (_obs.start_span(
            "dispatch", parent=self._slots[active[0]].req, tid="device",
            kind="decode", rows=len(active)) if _obs.enabled() else None)
        if self._scan_gate(active, budget_left):
            toks, _, _, _, self._pools = self._run_jit(
                self._chunk_jit, self._pools, self._h2d(tok, decode=True),
                self._h2d(tables, decode=True), self._h2d(cl, decode=True),
                self._h2d(fin, decode=True))
            self._phases_run.add("decode_chunk")
            self.n_dispatches += 1
            toks = np.asarray(self._fetch(toks))  # [K, B]
        else:
            nxt, _, _, self._pools = self._run_jit(
                self._decode_jit, self._pools, self._h2d(tok, decode=True),
                self._h2d(tables, decode=True), self._h2d(cl, decode=True),
                self._h2d(fin, decode=True))
            self._phases_run.add("decode")
            self.n_dispatches += 1
            toks = np.asarray(self._fetch(nxt))[None]  # [1, B]
        if sp_d is not None:
            _obs.finish_span(sp_d)
        hsp = (_obs.start_span("harvest", parent=sp_d, tid="serve",
                               kind="decode") if sp_d is not None else None)
        self._apply_decode(toks,
                           [(i, self._slots[i].req) for i in active])
        if hsp is not None:
            _obs.finish_span(hsp)
        return len(active) * toks.shape[0]

    # -- shared scheduling gates -----------------------------------------
    def _spec_gate(self, active, budget_left) -> bool:
        """Under a token budget a verify round charges active*(k+1) and
        could eat the whole step's budget every step — fall back to
        plain decode (active tokens) while a slot is mid-prefill so its
        chunks keep landing (same starvation guard as the scan)."""
        return self.spec_k is not None and (
            budget_left is None
            or (len(active) * (self.spec_k + 1) <= budget_left
                and self.num_prefilling == 0))

    def _scan_gate(self, active, budget_left) -> bool:
        """A K-step scan must fit every active slot's remaining budget
        and the step's token budget, and must not starve a mid-prefill
        slot for K steps."""
        k = self.decode_chunk
        return (
            k > 1
            and min(self._slots[i].remaining for i in active) >= k
            and (budget_left is None
                 or (len(active) * k <= budget_left
                     and self.num_prefilling == 0)))

    # -- the overlap decode dispatch --------------------------------------
    def _dispatch_decode_async(self, budget_left: Optional[int]) -> int:
        """Issue this step's decode round straight from the persistent
        device state — no host reads, no per-step table/cache_len
        uploads — and park its token outputs on the copy ring. Mid-
        prefill and pending-first rows are already trash on device (the
        dirty flush derives row content from host truth), so no
        per-dispatch table copy is needed. Returns budget charged."""
        if self.role == "prefill_only":
            return 0
        active = self._decode_rows()
        if not active:
            return 0
        if self._ring and self._spec_gate(active, budget_left):
            # speculative rounds keep ONE sync point: the host-side
            # proposer needs the COMPLETE emitted history — drafting
            # against a tail that lags the in-flight dispatch would
            # misalign every draft with its verify position and
            # collapse acceptance (a k+1-wide dispatch per ~1 emitted
            # token, worse than plain decode). The verify ids/state
            # still ride device-resident; a device-side proposer would
            # remove this drain too.
            self._harvest(drain=True)
            self._flush_dirty()  # harvest may have changed membership
            active = self._decode_rows()
            if not active:
                return 0
        if self._decode_jit is None:
            self._build_jits()
        self._ensure_dstate()
        tokd, tabd, cld, find = self._dstate
        if self._spec_gate(active, budget_left):
            proposed = self._propose_drafts(active)
            if proposed is not None:
                drafts, n_real = proposed
                toks, acc, tok2, cl2, fin2, self._pools = self._run_jit(
                    self._spec_jit, self._pools, tokd, tabd, cld, find,
                    self._h2d(drafts, decode=True))
                self._phases_run.add("spec_verify")
                self.n_dispatches += 1
                self._dstate = (tok2, tabd, cl2, fin2)
                self._push_entry(
                    "spec", (toks, acc),
                    [(i, self._slots[i].req, n_real.get(i, 0))
                     for i in active])
                return len(active) * (self.spec_k + 1)
        rows = [(i, self._slots[i].req) for i in active]
        if self._scan_gate(active, budget_left):
            toks, tok2, cl2, fin2, self._pools = self._run_jit(
                self._chunk_jit, self._pools, tokd, tabd, cld, find)
            self._phases_run.add("decode_chunk")
            self.n_dispatches += 1
            self._dstate = (tok2, tabd, cl2, fin2)
            self._push_entry("decode", (toks,), rows)
            return len(active) * self.decode_chunk
        nxt, cl2, fin2, self._pools = self._run_jit(
            self._decode_jit, self._pools, tokd, tabd, cld, find)
        self._phases_run.add("decode")
        self.n_dispatches += 1
        # the sampled-token output IS the next dispatch's input lane:
        # device-resident token recycling, zero host round-trips
        self._dstate = (nxt, tabd, cl2, fin2)
        self._push_entry("decode", (nxt,), rows)
        return len(active)

    def step(self):
        """One engine iteration. Sync mode: evict expired slots, admit,
        then the token-budgeted work — the decode round first
        (decode-priority keeps inter-token latency flat), leftover
        budget spent on prefill chunks round-robin; whole-prompt mode
        keeps the legacy order (prefill inside admission, then decode).
        Overlap mode (lag-1): flush dirty slots, DISPATCH this step's
        decode from device state, then harvest the PREVIOUS step's
        tokens and do the host scheduling work while the device runs.
        Returns the requests completed this iteration (expired ones
        included, with ``status == "expired"``)."""
        if not _chaos.inject("serving.step"):
            return []  # dropped engine iteration: no work this tick
        if self._fenced:
            raise EngineFenced(
                "engine was retired by its supervisor; a replacement "
                "already owns the requests")
        if self.overlap:
            return self._step_overlap()
        t0 = time.perf_counter()
        blocked0 = self.host_blocked_s
        before = set(self._completed)
        self._expire_queued()
        self._evict_expired()
        self._step_spec_overcharge = 0
        used = self._admit()
        budget = self.max_num_batched_tokens
        used += self._decode_step(None if budget is None else budget - used)
        if self.chunked:
            used += self._prefill_step(budget - used)
        real = used - self._step_spec_overcharge
        self._finish_step(t0, blocked0, used, real)
        if self.admission is not None:
            self.admission.observe(self.load())
        return [self._completed[r] for r in set(self._completed) - before]

    def _step_overlap(self):
        """The lag-1 pipelined iteration (module docstring): the decode
        dispatch for step N+1 is issued BEFORE step N's tokens are
        processed, so the host's bookkeeping/scheduling work runs while
        the device computes. Slot membership changes (admissions,
        prefill completions, finishes, evictions) land in the dirty set
        and reach the device at the NEXT step's flush — dispatch
        boundaries, exactly as the device-state design requires."""
        t0 = time.perf_counter()
        blocked0 = self.host_blocked_s
        before = set(self._completed)
        self._step_spec_overcharge = 0
        budget = self.max_num_batched_tokens
        self._harvested_step = 0
        # 1) membership changes decided last step reach the device
        self._flush_dirty()
        # 2) dispatch this step's decode round (no host sync)
        used = self._dispatch_decode_async(budget)
        dispatched = used > 0
        # 3) harvest the previous entry while the device runs this one.
        # When NOTHING was dispatched there is no compute to overlap
        # with — drain fully, or a pending-first slot's token would sit
        # un-harvested (and the slot starved of decode) for as long as
        # another slot's long prefill keeps the step busy
        self._harvest(drain=not dispatched)
        # 4) host scheduling work, overlapped with device compute
        self._expire_queued()
        self._evict_expired()
        a_used = self._admit()
        used += a_used
        pf = 0
        if self.chunked:
            pf = self._prefill_step(budget - used)
            used += pf
        if self._ring and not self.num_active and not self._queue:
            # the engine just went idle with an over-issued dispatch
            # still in flight (every row already finished): fetch +
            # discard so no driver sees a dangling pipeline
            self._harvest(drain=True)
        real = self._harvested_step + a_used + pf
        self._finish_step(t0, blocked0, used, real)
        if self.admission is not None:
            self.admission.observe(self.load())
        return [self._completed[r] for r in set(self._completed) - before]

    def _finish_step(self, t0: float, blocked0: float, used: int,
                     real: int) -> None:
        """Shared per-step accounting: wall/blocked-time EWMAs and the
        service-rate estimate. ``used`` is the budget charged (verify
        rounds charge k+1 per slot); ``real`` is tokens that actually
        drained backlog — the delay estimate must see the drain rate,
        or spec/pipelined engines overstate capacity."""
        self.steps += 1
        self.last_step_tokens = used
        self.max_step_tokens = max(self.max_step_tokens, used)
        self.last_step_s = time.perf_counter() - t0
        self.busy_s += self.last_step_s
        a = (self.admission.config.ewma_alpha
             if self.admission is not None else 0.3)
        if used > 0 or real > 0:
            # idle ticks are excluded so a quiet engine does not decay
            # its measured capacity toward zero
            self.ewma_step_s = self.last_step_s if self.ewma_step_s is None \
                else a * self.last_step_s + (1 - a) * self.ewma_step_s
            self.ewma_step_tokens = float(real) \
                if self.ewma_step_tokens is None \
                else a * real + (1 - a) * self.ewma_step_tokens
            blocked = self.host_blocked_s - blocked0
            frac = min(blocked / self.last_step_s, 1.0) \
                if self.last_step_s > 0 else 0.0
            self.ewma_blocked_frac = frac \
                if self.ewma_blocked_frac is None \
                else a * frac + (1 - a) * self.ewma_blocked_frac

    def overlap_stats(self) -> dict:
        """Host/device pipelining counters (tracked in BOTH modes, so
        the sync engine provides the A/B baseline): decode-phase
        dispatches, cumulative host-blocked seconds, the overlap
        fraction (1 - blocked/busy), tokens per dispatch, and the
        H2D/D2H byte ledgers the persistent-device-state design exists
        to shrink."""
        toks = self.decode_tokens
        return {
            "enabled": self.overlap,
            "pipeline_depth": self.pipeline_depth,
            "in_flight": len(self._ring),
            "dispatches": self.n_dispatches,
            "host_blocked_s": self.host_blocked_s,
            "busy_s": self.busy_s,
            "host_blocked_frac": (self.host_blocked_s / self.busy_s
                                  if self.busy_s > 0 else 0.0),
            "overlap_frac": (1.0 - self.host_blocked_s / self.busy_s
                             if self.busy_s > 0 else 0.0),
            "tokens_per_dispatch": (toks / self.n_dispatches
                                    if self.n_dispatches else 0.0),
            "h2d_bytes": self.h2d_bytes,
            "h2d_decode_bytes": self.h2d_decode_bytes,
            "h2d_decode_bytes_per_token": (self.h2d_decode_bytes / toks
                                           if toks else 0.0),
            "d2h_bytes": self.d2h_bytes,
        }

    def run(self, max_steps: int = 100_000) -> Dict[object, GenRequest]:
        """Drain the queue + active slots; returns {req_id: GenRequest}."""
        while (self._queue or self.num_active) and max_steps > 0:
            self.step()
            max_steps -= 1
        if self._ring:
            # an entry dispatched for rows that all finished at the
            # final harvest: fetch + discard so nothing dangles
            self._harvest(drain=True)
        if self._restore_training:
            self.model.train()
        return dict(self._completed)
