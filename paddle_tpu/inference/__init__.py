"""paddle.inference parity — the deployment API.

ref: python/paddle/inference/__init__.py (Config/Predictor/
create_predictor wrapping the C++ AnalysisPredictor). TPU-native
mapping: a saved model is a StableHLO export (jit.save); Predictor
loads it (jit.load → TranslatedLayer) and runs it jitted. The
TensorRT/IR-pass knobs in Config are recorded but XLA owns optimization
(documented per-method); GPU settings select the accelerator device.
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "Config", "DataType", "PlaceType", "PrecisionType", "Tensor",
    "Predictor", "create_predictor", "get_version", "_get_phi_kernel_name",
    "get_trt_compile_version", "get_trt_runtime_version",
    "convert_to_mixed_precision", "get_num_bytes_of_data_type",
    "PredictorPool", "XpuConfig",
]


class DataType(enum.Enum):
    FLOAT32 = 0
    FLOAT16 = 1
    BFLOAT16 = 2
    INT8 = 3
    INT32 = 4
    INT64 = 5
    UINT8 = 6
    BOOL = 7


_DT_BYTES = {
    DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.BFLOAT16: 2,
    DataType.INT8: 1, DataType.INT32: 4, DataType.INT64: 8,
    DataType.UINT8: 1, DataType.BOOL: 1,
}


class PlaceType(enum.Enum):
    CPU = 0
    GPU = 1  # = the accelerator (TPU) in this build
    XPU = 2
    CUSTOM = 3


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class Config:
    """ref: inference Config — model path + device/precision knobs."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # jit.save writes a single prefix; accept either spelling
        self._path = prog_file
        self._use_accel = False
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._cpu_threads = 1
        self._enable_memory_optim = True

    def set_prog_file(self, path):
        self._path = path

    def prog_file(self):
        return self._path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        """'GPU' selects the accelerator; memory pool sizing is XLA's."""
        self._use_accel = True
        self._device_id = device_id
        self._precision = precision

    def disable_gpu(self):
        self._use_accel = False

    def use_gpu(self):
        return self._use_accel

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = n

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def enable_tensorrt_engine(self, *a, **k):
        """TensorRT has no TPU counterpart; XLA already fuses/compiles —
        recorded as a no-op for ported deployment scripts."""

    def switch_ir_optim(self, flag=True):
        """IR passes are XLA's job; recorded no-op."""

    def summary(self):
        return {
            "model": self._path,
            "device": "tpu" if self._use_accel else "cpu",
            "precision": self._precision.name,
        }


class Tensor:
    """ref: inference Tensor — named feed/fetch handle."""

    def __init__(self, name: str, store: Dict[str, np.ndarray]):
        self._name = name
        self._store = store

    def name(self):
        return self._name

    def copy_from_cpu(self, arr: np.ndarray):
        self._store[self._name] = np.ascontiguousarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._store[self._name])

    def reshape(self, shape):
        if self._name in self._store:
            self._store[self._name] = self._store[self._name].reshape(shape)

    def shape(self):
        return list(self._store[self._name].shape)


class Predictor:
    """ref: inference Predictor — run a saved model. Wraps
    jit.load(TranslatedLayer) with named feed/fetch slots."""

    def __init__(self, config: Config):
        import paddle_tpu.jit as jit

        if config._path is None:
            raise ValueError("Config has no model path (set_prog_file)")
        self._layer = jit.load(config._path)
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        n_in = getattr(self._layer, "num_inputs", None)
        self._input_names = [f"x{i}" for i in range(n_in)] if n_in else ["x0"]
        self._output_names = ["out0"]

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name) -> Tensor:
        return Tensor(name, self._inputs)

    def get_output_handle(self, name) -> Tensor:
        return Tensor(name, self._outputs)

    def run(self):
        import paddle_tpu as paddle

        args = [paddle.to_tensor(self._inputs[n]) for n in self._input_names
                if n in self._inputs]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        for n, o in zip(self._output_names, outs):
            self._outputs[n] = np.asarray(o.numpy())
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """ref: inference PredictorPool — N predictors over one model."""

    def __init__(self, config: Config, size: int = 1):
        self._preds = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


class XpuConfig:
    """XPU deployment config — no TPU counterpart; placeholder bag."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


def get_version() -> str:
    import paddle_tpu

    return paddle_tpu.__version__


def _get_phi_kernel_name(op_name: str) -> str:
    """ref: inference _get_phi_kernel_name — kernels here are XLA
    fusions; the op name is its own kernel name."""
    return op_name


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT on TPU


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype: DataType) -> int:
    return _DT_BYTES[dtype]


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=PrecisionType.Half,
                               backend=PlaceType.GPU, keep_io_types=True,
                               black_list=None, **kw):
    """ref: inference convert_to_mixed_precision. StableHLO exports bake
    dtypes at trace time — re-export the model under amp/bfloat16
    instead (paddle_tpu.amp.auto_cast + jit.save)."""
    raise NotImplementedError(
        "convert_to_mixed_precision operates on ProgramDesc files; with "
        "StableHLO exports, re-trace the model under paddle_tpu.amp."
        "auto_cast (bfloat16) and jit.save it instead."
    )
