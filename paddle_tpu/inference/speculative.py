"""Draft proposers for self-speculative decoding.

Speculative decoding factors each decode step into a cheap DRAFT of k
candidate tokens plus ONE batched verify dispatch that scores all k+1
positions through the normal model (Leviathan et al. 2023; the serving
engine's verify program is the paged prefill path at width k+1, see
inference/serving.py). With greedy (temperature-0) decoding the
accept rule is exact-prefix: position j's draft is accepted iff it
equals the argmax the model produced at position j-1 — so every
emitted token is, by construction, the token the plain one-at-a-time
loop would have produced. Speculation changes THROUGHPUT, never
tokens.

The default draft source needs no second model: prompt-lookup /
n-gram speculation (vLLM's ``ngram`` speculative method, Saxena 2023).
LLM output constantly re-quotes its own context — retrieved spans,
code identifiers, boilerplate — so the best predictor of the next few
tokens is often "the last time this n-gram appeared, what followed
it?". :class:`NgramProposer` keeps that lookup pure-numpy on the host:
the proposal rides along with the token append the host loop already
does, adding ZERO extra device dispatches (the verify result must
surface on host each round anyway to extend ragged per-slot outputs).

:class:`DraftProposer` is the pluggable seam: a small draft MODEL
(Medusa/EAGLE-class) implements the same two methods and slots into
the engine unchanged.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["DraftProposer", "NgramProposer", "accept_length"]


class DraftProposer:
    """Interface the serving engine / generate() drive.

    ``propose`` receives the sequence's FULL token history (prompt +
    generated, host int32 array) and returns up to ``k`` draft tokens
    (1-D int array, possibly empty). Proposals are free to be wrong —
    the verify dispatch accepts only exact greedy prefixes — but every
    proposed-but-rejected token is wasted verify compute, so a proposer
    should return nothing when it has no signal."""

    def propose(self, tokens: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class NgramProposer(DraftProposer):
    """Prompt/output n-gram lookup (vLLM-style prompt lookup decoding).

    Finds the MOST RECENT earlier occurrence of the sequence's trailing
    n-gram (longest n first, ``max_ngram`` down to ``min_ngram``) and
    proposes the tokens that followed it. Pure numpy sliding-window
    match — O(history · max_ngram) per call on small ints, microseconds
    at serving lengths."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, tokens: np.ndarray, k: int) -> np.ndarray:
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n_hist = toks.size
        if k <= 0 or n_hist < self.min_ngram + 1:
            return np.zeros((0,), np.int32)
        for n in range(min(self.max_ngram, n_hist - 1), self.min_ngram - 1,
                       -1):
            tail = toks[n_hist - n:]
            # windows[i] == toks[i:i+n]; exclude the tail itself
            windows = np.lib.stride_tricks.sliding_window_view(
                toks[:-1], n)
            hits = np.flatnonzero((windows == tail).all(axis=1))
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n  # most recent occurrence wins
            cont = toks[start:start + k]
            if cont.size:
                return cont.astype(np.int32, copy=True)
        return np.zeros((0,), np.int32)


def accept_length(drafts: np.ndarray, target: np.ndarray) -> int:
    """Greedy accept-prefix length: how many leading ``drafts`` equal
    the verify dispatch's argmax at the same position. (Any draft that
    matches the argmax IS the greedy token — acceptance by equality is
    what makes speculative output byte-identical.)"""
    drafts = np.asarray(drafts).reshape(-1)
    target = np.asarray(target).reshape(-1)[: drafts.size]
    neq = np.flatnonzero(drafts != target)
    return int(neq[0]) if neq.size else int(drafts.size)
