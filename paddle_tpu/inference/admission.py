"""Overload control for the serving engine: admit cheaply or shed at
the front door.

Under sustained overload an engine that accepts everything burns
prefill tokens on requests it will later deadline-evict — the worst
possible place to spend capacity. The production-proven shape (DAGOR,
WeChat's adaptive overload control; SOSP'19 overload-control study) is
the opposite: reject EXCESS AT SUBMIT TIME from a cheap load signal,
keep a priority order so latency-sensitive traffic rides out the storm,
and adapt the admission threshold to measured queueing delay rather
than a static constant.

Three cooperating pieces, all host-side and model-free:

- :class:`EngineLoad` — the live load signal
  :meth:`ContinuousBatchingEngine.load` snapshots every step: queue
  depth, KV-block occupancy, token backlog (queued + in-flight work),
  EWMA step latency/throughput, and the derived queueing-delay
  estimate. Routers and tests read the same struct the controller
  decides from.
- :class:`AdmissionConfig` — the knobs: bounded waiting queue
  (``max_queue``), shed watermarks, the degraded-mode KV watermarks
  (pause prefill admission / clamp batch token grants), and the
  DAGOR-style delay target driving the adaptive level.
- :class:`AdmissionController` — the decision. Two priority classes
  (``interactive`` ahead of ``batch``; deadline-aware ordering within a
  class), watermark shedding of batch traffic, queue-full displacement
  (an interactive arrival evicts the worst queued batch request instead
  of being shed), a deadline-feasibility test (a request that cannot
  finish inside its budget is shed now, not expired later), and an
  adaptive admission level that tightens batch → everything as the
  measured queueing delay crosses the target (hysteresis + hold to
  avoid flapping).

Per-tenant isolation (ISSUE 19) rides on the same front door. DAGOR
sheds *total* overload but is tenant-blind: one hot tenant fills the
queue and every other tenant's attainment collapses while the engine
is nominally healthy. Two mechanisms close that hole:

- **token-bucket quotas** (:class:`TenantPolicy` ``rate_tokens_per_s``/
  ``burst_tokens``): a tenant that exceeds its refill rate is shed with
  reason ``tenant-quota`` at submit time, before it costs anything.
  Refill is computed from the injected clock, so a seeded schedule
  replays to identical verdicts.
- **weighted fair queueing** (start-time fair queueing / SFQ): each
  admission is stamped with a virtual start/finish tag
  (``finish = start + cost / weight``); the engine orders its queue by
  finish tag within a priority class, and feeds served start tags back
  via :meth:`AdmissionController.wfq_served` to advance virtual time.
  A quiet tenant's first arrival tags at the current virtual time and
  overtakes a hot tenant's long backlog — starvation becomes
  structurally impossible rather than merely visible.

The controller is deliberately engine-agnostic: it consumes
:class:`EngineLoad` values and returns verdicts, so it unit-tests
without a model and could front any engine with the same signal.
"""
from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional, Tuple

__all__ = [
    "PRIORITIES",
    "priority_rank",
    "EngineLoad",
    "TenantPolicy",
    "AdmissionConfig",
    "AdmissionController",
]

# lower rank = more important; admission order and shedding order both
# key off this (batch absorbs the shedding first)
PRIORITIES = ("interactive", "batch")
_RANK = {p: i for i, p in enumerate(PRIORITIES)}


def priority_rank(priority: str) -> int:
    try:
        return _RANK[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority {priority!r}; expected one of {PRIORITIES}"
        ) from None


@dataclass
class EngineLoad:
    """One step's load snapshot (the struct ``engine.load()`` returns).

    ``token_backlog`` counts REAL tokens of committed work: queued
    prompts + their full generation budgets, plus the un-prefilled and
    un-generated remainder of every in-flight slot.
    ``est_queue_delay_s`` is ``token_backlog / tokens_per_step *
    ewma_step_s`` — how long a new arrival waits before its work is
    scheduled, at the measured service rate."""

    queue_depth: int = 0
    queue_limit: Optional[int] = None
    queued_interactive: int = 0
    queued_batch: int = 0
    # tokens AHEAD of a new interactive arrival: in-flight remainders
    # plus queued interactive work (priority insertion puts it in
    # front of every queued batch request, so batch backlog does not
    # delay it)
    token_backlog_interactive: int = 0
    active_slots: int = 0
    max_batch: int = 0
    prefilling: int = 0
    kv_free_blocks: int = 0
    kv_total_blocks: int = 0
    kv_occupancy: float = 0.0
    token_backlog: int = 0
    tokens_per_step: float = 0.0
    ewma_step_s: Optional[float] = None
    est_queue_delay_s: float = 0.0
    admission_level: int = 0
    prefill_paused: bool = False
    n_shed_interactive: int = 0
    n_shed_batch: int = 0
    n_expired: int = 0
    # host/device pipelining occupancy (ISSUE 10): fraction of recent
    # step time the host spent BLOCKED on device fetches (EWMA), and
    # the async pipeline's current in-flight dispatch count. A replica
    # with a high blocked fraction is host-bound — more work queued on
    # it returns later than its queue depth alone suggests, so the
    # router scores it down.
    host_blocked_frac: float = 0.0
    dispatch_depth: int = 0

    @property
    def queue_frac(self) -> float:
        if not self.queue_limit:
            return 0.0
        return self.queue_depth / float(self.queue_limit)

    def as_dict(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant isolation knobs.

    ``weight`` steers WFQ service share (a weight-2 tenant drains twice
    as fast as a weight-1 tenant under contention). ``rate_tokens_per_s``
    enables a token-bucket quota over REAL work (prompt + generation
    budget tokens); ``burst_tokens`` is the bucket depth (defaults to
    one second of rate). ``None`` rate means unmetered."""

    weight: float = 1.0
    rate_tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0.0:
            raise ValueError("weight must be > 0")
        if self.rate_tokens_per_s is not None and self.rate_tokens_per_s <= 0:
            raise ValueError("rate_tokens_per_s must be > 0 or None")
        if self.burst_tokens is not None and self.burst_tokens <= 0:
            raise ValueError("burst_tokens must be > 0 or None")

    @property
    def burst(self) -> Optional[float]:
        if self.rate_tokens_per_s is None:
            return None
        return (self.burst_tokens if self.burst_tokens is not None
                else self.rate_tokens_per_s)


_DEFAULT_POLICY = TenantPolicy()


@dataclass
class AdmissionConfig:
    """Knobs for :class:`AdmissionController` and the engine's degraded
    modes. Defaults are deliberately permissive: only the bounded queue
    and the expired-at-submit fast path are active until watermarks /
    targets are tightened."""

    max_queue: int = 64           # bounded waiting queue (DAGOR front door)
    high_watermark: float = 0.85  # load score that sheds batch traffic
    low_watermark: float = 0.5    # adaptive level relaxes below this
    # degraded modes (engine-side): pause NEW admissions when KV blocks
    # are scarce; clamp batch-class token grants under pressure. 1.0
    # means "only when the pool is fully allocated" — effectively off.
    kv_pause_watermark: float = 1.0
    kv_clamp_watermark: float = 1.0
    batch_clamp_tokens: Optional[int] = None  # None = never clamp
    # DAGOR-style adaptation: tighten the admission level when the
    # estimated queueing delay crosses the target (None = static)
    target_delay_s: Optional[float] = None
    level_hold: int = 8           # observations between level moves
    ewma_alpha: float = 0.3
    # shed requests that cannot finish inside their deadline at the
    # measured service rate (margin > 1 sheds earlier)
    deadline_feasibility: bool = True
    feasibility_margin: float = 1.0
    # per-tenant isolation: policies keyed by tenant name ("*" is the
    # fallback for unlisted tenants). Any policy — or wfq=True — turns
    # on WFQ queue tagging; quotas only meter tenants with a rate.
    tenants: Dict[str, TenantPolicy] = field(default_factory=dict)
    wfq: bool = False

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if self.low_watermark > self.high_watermark:
            raise ValueError("low_watermark must be <= high_watermark")


class AdmissionController:
    """Stateful front door: verdicts from load snapshots.

    ``level`` is the adaptive priority threshold (DAGOR's admission
    level collapsed to this engine's two classes): 0 admits every
    class, 1 sheds batch, 2 sheds everything. It tightens one notch
    when the delay EWMA exceeds ``target_delay_s`` and relaxes when the
    EWMA falls below ``target_delay_s * low_watermark``, holding
    ``level_hold`` observations between moves so one noisy step cannot
    flap the threshold."""

    MAX_LEVEL = 2

    def __init__(self, config: Optional[AdmissionConfig] = None, *,
                 clock=time.monotonic):
        self.config = config if config is not None else AdmissionConfig()
        self._clock = clock
        self.level = 0
        self.delay_ewma = 0.0
        self._since_change = self.config.level_hold  # free first move
        # per-tenant isolation state (SFQ virtual time + token buckets)
        self._vtime = 0.0
        self._tenant_finish: Dict[str, float] = {}
        self._buckets: Dict[str, list] = {}  # tenant -> [level, last_t]
        self.n_quota_shed = 0
        # obs registry mirror (ISSUE 12): the controller's adaptive
        # state, readable from `python -m paddle_tpu.obs dump` without
        # holding a reference to the engine
        from ..obs.metrics import registry as _reg
        self._g_level = _reg().gauge(
            "admission_level", help="adaptive admission level (0..2)")
        self._g_delay = _reg().gauge(
            "admission_delay_ewma_seconds",
            help="EWMA of the estimated queueing delay")
        # per-(verdict, reason) decision counters (ISSUE 15): alert
        # rules watch WHY load is being shed, not just how much
        self._reg = _reg()

    # -- load tracking --------------------------------------------------
    def observe(self, load: EngineLoad, *,
                allow_tighten: bool = True) -> None:
        """Fold one load snapshot into the delay EWMA and maybe move
        the admission level (hysteresis + hold). ``allow_tighten=False``
        restricts this observation to DOWNWARD moves — the idle-decay
        path, where the caller cannot vouch for a fresh service-rate
        estimate."""
        cfg = self.config
        a = cfg.ewma_alpha
        self.delay_ewma = (a * load.est_queue_delay_s
                           + (1.0 - a) * self.delay_ewma)
        self._g_delay.set(self.delay_ewma)
        self._since_change += 1
        if cfg.target_delay_s is None or self._since_change < cfg.level_hold:
            self._g_level.set(self.level)
            return
        if (self.delay_ewma > cfg.target_delay_s
                and self.level < self.MAX_LEVEL and allow_tighten):
            self.level += 1
            self._since_change = 0
        elif (self.delay_ewma < cfg.target_delay_s * cfg.low_watermark
                and self.level > 0):
            self.level -= 1
            self._since_change = 0
        self._g_level.set(self.level)

    def score(self, load: EngineLoad) -> float:
        """Composite load score in [0, inf): the worst of queue
        pressure and (when a target is set) normalized queueing delay.
        KV scarcity is handled by the engine's degraded modes, not the
        shed score — a full pool at steady state is healthy."""
        cfg = self.config
        q = (load.queue_frac if load.queue_limit
             else load.queue_depth / float(cfg.max_queue))
        d = 0.0
        if cfg.target_delay_s:
            d = self.delay_ewma / cfg.target_delay_s
        return max(q, d)

    # -- per-tenant isolation -------------------------------------------
    @property
    def wfq_enabled(self) -> bool:
        return self.config.wfq or bool(self.config.tenants)

    def _policy(self, tenant: str) -> TenantPolicy:
        t = self.config.tenants
        return t.get(tenant) or t.get("*") or _DEFAULT_POLICY

    @staticmethod
    def _cost(req) -> float:
        return float(int(req.prompt.size) + int(req.max_new_tokens))

    def wfq_tag(self, tenant: str, cost: float) -> Tuple[float, float]:
        """Start-time-fair-queueing tags for one admission:
        ``start = max(vtime, tenant's last finish)``,
        ``finish = start + cost / weight``. The engine orders its queue
        by the finish tag (within a priority class) and reports served
        start tags back via :meth:`wfq_served`."""
        w = self._policy(tenant).weight
        start = max(self._vtime, self._tenant_finish.get(tenant, 0.0))
        finish = start + float(cost) / w
        self._tenant_finish[tenant] = finish
        return start, finish

    def wfq_served(self, start: Optional[float]) -> None:
        """Service feedback: virtual time advances to the start tag of
        the request entering service (SFQ). This is what lets a newly
        arrived quiet tenant tag *at* vtime and overtake a hot tenant's
        queued backlog."""
        if start is not None:
            self._vtime = max(self._vtime, float(start))

    def _bucket_level(self, tenant: str, pol: TenantPolicy,
                      now: float) -> float:
        """Refilled bucket level (does not deduct)."""
        burst = pol.burst
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = [burst, now]
        level, last = b
        level = min(burst, level + max(now - last, 0.0)
                    * pol.rate_tokens_per_s)
        b[0], b[1] = level, now
        return level

    def _quota_verdict(self, req) -> bool:
        """True when the tenant's bucket covers this request's cost.
        Unmetered tenants always pass."""
        tenant = getattr(req, "tenant", "default")
        pol = self._policy(tenant)
        if pol.rate_tokens_per_s is None:
            return True
        return self._bucket_level(tenant, pol, self._clock()) \
            >= self._cost(req)

    def _quota_charge(self, req) -> None:
        tenant = getattr(req, "tenant", "default")
        pol = self._policy(tenant)
        if pol.rate_tokens_per_s is None:
            return
        b = self._buckets.get(tenant)
        if b is not None:
            b[0] = max(b[0] - self._cost(req), 0.0)

    # -- the decision ---------------------------------------------------
    def decide(self, req, load: EngineLoad) -> Tuple[str, str]:
        """Verdict for one submission: ``("admit", "")``,
        ``("shed", reason)``, or ``("displace", reason)`` — admit this
        interactive request by shedding the worst queued batch request
        (the engine performs the displacement). ``req`` needs
        ``priority``, ``prompt``, ``max_new_tokens``, ``deadline``/
        ``expired()`` — the engine's GenRequest shape."""
        verdict = self._decide(req, load)
        if verdict[0] in ("admit", "displace"):
            # charge the tenant bucket only for work actually taken on
            self._quota_charge(req)
        self._reg.counter(
            "admission_decisions_total",
            {"verdict": verdict[0],
             "reason": verdict[1] or "ok"}).inc()
        return verdict

    def _decide(self, req, load: EngineLoad) -> Tuple[str, str]:
        cfg = self.config
        rank = priority_rank(req.priority)
        if req.expired():
            # fast path: a dead-on-arrival budget never enters the queue
            return ("shed", "expired-at-submit")
        if not self._quota_verdict(req):
            # over-quota tenants shed at the front door regardless of
            # engine health: isolation, not overload control
            self.n_quota_shed += 1
            return ("shed", "tenant-quota")
        if self.level >= 2:
            return ("shed", "overload")
        if self.level >= 1 and rank >= 1:
            return ("shed", "overload-batch")
        # feasibility BEFORE the queue-full/displace branch: a doomed
        # arrival must never evict viable queued work only to expire
        # itself — shedding it here loses zero requests
        if (cfg.deadline_feasibility and req.deadline is not None
                and load.ewma_step_s):
            tps = max(load.tokens_per_step, 1.0)
            own = int(req.prompt.size) + int(req.max_new_tokens)
            service = own / tps * load.ewma_step_s
            if rank == 0:
                # interactive jumps ahead of queued batch work: only
                # the class-aware backlog delays it — reasoning from
                # the whole backlog would shed exactly the latency-
                # sensitive traffic this controller exists to protect
                wait = (load.token_backlog_interactive / tps
                        * load.ewma_step_s)
            else:
                wait = load.est_queue_delay_s
            if req.deadline.remaining() < (wait + service) * \
                    cfg.feasibility_margin:
                return ("shed", "deadline-infeasible")
        if load.queue_depth >= cfg.max_queue:
            if rank == 0 and load.queued_batch > 0:
                return ("displace", "queue-full-displaces-batch")
            return ("shed", "queue-full")
        if rank >= 1 and self.score(load) >= cfg.high_watermark:
            return ("shed", "watermark")
        return ("admit", "")

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "delay_ewma_s": self.delay_ewma,
            "target_delay_s": self.config.target_delay_s,
            "max_queue": self.config.max_queue,
            "wfq": self.wfq_enabled,
            "vtime": self._vtime,
            "n_quota_shed": self.n_quota_shed,
            "buckets": {t: b[0] for t, b in self._buckets.items()},
        }
