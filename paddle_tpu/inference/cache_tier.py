"""Host-RAM prefix-cache tier: spill cold radix-tree blocks off HBM.

The paged KV pool (HBM) is the only home a cached prefix has today, so
prefix-cache capacity IS the HBM budget: once ``BlockManager`` runs dry
the LRU evictor throws KV away and the next identical prompt re-pays
its whole prefill. This module adds a second, much larger tier — plain
host memory (or any ``MutableMapping[str, bytes]``, e.g. a peer
``KVStore`` wrapper) — underneath the radix tree:

- **spill (write-through)**: whenever the engine registers a prompt's
  full blocks in the :class:`~paddle_tpu.ops.paged_attention.PrefixCache`
  it also exports them (``BlockManager.export_blocks`` — byte-exact for
  bf16 and int8+scales pools) and stores one self-describing frame per
  block-aligned prefix here. HBM eviction then loses nothing: the host
  copy already exists, so the evictor can stay greedy.
- **restore (read-through)**: on a prompt whose HBM radix hit is shorter
  than a spilled prefix, the engine imports the frame back into fresh
  blocks (``import_blocks``) and re-pins it in the tree — the request
  adopts it like any ordinary prefix hit.

Frames carry a CRC32 over header+payload. A corrupt frame (bit-rot,
chaos ``cache.spill``) is rejected at ``lookup`` time and treated as a
cache miss — never served as wrong tokens. The chaos site wraps the
frame bytes at ``put`` so ``corrupt``/``drop`` faults exercise exactly
the failure matrix in README §"Closed-loop fleet control".
"""
from __future__ import annotations

import json
import struct
import zlib
from collections import OrderedDict
from typing import Dict, List, MutableMapping, Optional, Tuple

import numpy as np

from ..testing import chaos as _chaos
from ..utils import resources as _res

__all__ = ["HostTier"]

_MAGIC = b"PTC1"
_SHARED_NS = "*"  # namespace for COW-shared system prompts


def _np_dtype(name: str):
    """Resolve a dtype name, including bfloat16 (ml_dtypes-backed)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _key(ns: Optional[str], tokens) -> str:
    toks = np.asarray(tokens).reshape(-1).astype(np.int64)
    digest = zlib.crc32(toks.tobytes()) & 0xFFFFFFFF
    return f"kvtier/{ns or ''}/{toks.size}/{digest:08x}"


def _encode(tokens, pages: np.ndarray, scales: Optional[np.ndarray],
            meta: dict) -> bytes:
    toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
    pages = np.ascontiguousarray(pages)
    header = {
        "meta": dict(meta),
        "tokens": toks,
        "pages_shape": list(pages.shape),
        "pages_dtype": pages.dtype.name,
    }
    payload = pages.tobytes()
    if scales is not None:
        scales = np.ascontiguousarray(scales)
        header["scales_shape"] = list(scales.shape)
        header["scales_dtype"] = scales.dtype.name
        payload += scales.tobytes()
    hjson = json.dumps(header, sort_keys=True).encode()
    body = _MAGIC + struct.pack(">I", len(hjson)) + hjson + payload
    return body + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)


def _decode(frame: bytes):
    """-> (tokens, pages, scales, meta) or None when the frame fails
    validation (truncated, bad magic, CRC mismatch)."""
    if len(frame) < 12 or frame[:4] != _MAGIC:
        return None
    (crc,) = struct.unpack(">I", frame[-4:])
    if zlib.crc32(frame[:-4]) & 0xFFFFFFFF != crc:
        return None
    (hlen,) = struct.unpack(">I", frame[4:8])
    if len(frame) < 8 + hlen + 4:
        return None
    try:
        header = json.loads(frame[8:8 + hlen].decode())
    except ValueError:
        return None
    payload = frame[8 + hlen:-4]
    pdt = _np_dtype(header["pages_dtype"])
    pshape = tuple(header["pages_shape"])
    nbytes = int(np.prod(pshape)) * pdt.itemsize
    pages = np.frombuffer(payload[:nbytes], dtype=pdt).reshape(pshape)
    scales = None
    if "scales_shape" in header:
        sdt = _np_dtype(header["scales_dtype"])
        sshape = tuple(header["scales_shape"])
        scales = np.frombuffer(
            payload[nbytes:nbytes + int(np.prod(sshape)) * sdt.itemsize],
            dtype=sdt).reshape(sshape)
    tokens = np.asarray(header["tokens"], dtype=np.int64)
    return tokens, pages, scales, header["meta"]


class HostTier:
    """LRU byte-budgeted store of exported prefix-KV frames.

    ``backend`` is any ``MutableMapping[str, bytes]`` (default: a plain
    dict, i.e. host RAM; a peer ``KVStore`` adapter turns this into a
    remote tier with zero code change here). The index — which keys
    exist, their sizes, LRU order — is always kept locally so lookups
    probe the backend only on an index hit.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 backend: Optional[MutableMapping] = None):
        if capacity_bytes is not None and capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be positive or None")
        self.capacity_bytes = capacity_bytes
        self._data: MutableMapping = backend if backend is not None else {}
        self._index: "OrderedDict[str, int]" = OrderedDict()  # key -> size
        self._bytes = 0
        self.puts = 0
        self.put_drops = 0
        self.lookups = 0
        self.hits = 0
        self.corrupt_rejected = 0
        self.evictions = 0
        self._graft_ledger = _res.current()

    # -- write path ------------------------------------------------------
    def put(self, ns: Optional[str], tokens, pages, scales, meta) -> bool:
        """Store one frame for the FULL-block prefix ``tokens``.

        Idempotent per (ns, tokens). Passes the encoded frame through
        the ``cache.spill`` chaos site: ``drop`` -> not stored,
        ``corrupt`` -> stored corrupted (rejected later by CRC, i.e. a
        miss). Returns True when the frame landed in the backend.
        """
        self.puts += 1
        key = _key(ns, tokens)
        if key in self._index:  # refresh LRU only; frames are immutable
            self._index.move_to_end(key)
            return True
        frame = _encode(tokens, np.asarray(pages),
                        None if scales is None else np.asarray(scales),
                        meta)
        frame = _chaos.inject_bytes("cache.spill", frame)
        if frame is None:  # chaos drop: spill silently lost (= miss later)
            self.put_drops += 1
            return False
        if self.capacity_bytes is not None and len(frame) > self.capacity_bytes:
            self.put_drops += 1
            return False
        self._data[key] = bytes(frame)
        self._index[key] = len(frame)
        self._bytes += len(frame)
        if self._graft_ledger is not None:
            self._graft_ledger.acquire("host.frame", key)
        self._evict_to_capacity()
        return True

    def _evict_to_capacity(self) -> None:
        if self.capacity_bytes is None:
            return
        while self._bytes > self.capacity_bytes and self._index:
            key, size = self._index.popitem(last=False)  # LRU first
            self._bytes -= size
            self.evictions += 1
            if self._graft_ledger is not None:
                self._graft_ledger.release("host.frame", key)
            try:
                del self._data[key]
            except KeyError:
                pass

    def _drop(self, key: str) -> None:
        size = self._index.pop(key, None)
        if size is not None:
            self._bytes -= size
            if self._graft_ledger is not None:
                self._graft_ledger.release("host.frame", key)
        try:
            del self._data[key]
        except KeyError:
            pass

    # -- read path -------------------------------------------------------
    def lookup(self, ns: Optional[str], tokens, *, block_size: int,
               min_tokens: int = 0):
        """Longest stored block-aligned prefix of ``tokens`` strictly
        longer than ``min_tokens``. Returns ``(n_tokens, pages, scales,
        meta)`` or None. Corrupt frames are dropped from the index and
        counted in ``corrupt_rejected`` — a miss, never bad KV."""
        self.lookups += 1
        toks = np.asarray(tokens).reshape(-1)
        n_full = (int(toks.size) // int(block_size)) * int(block_size)
        for n in range(n_full, max(int(min_tokens), 0), -int(block_size)):
            key = _key(ns, toks[:n])
            if key not in self._index:
                continue
            frame = self._data.get(key)
            decoded = None if frame is None else _decode(frame)
            if decoded is None:
                self.corrupt_rejected += 1
                self._drop(key)
                continue
            f_toks, pages, scales, meta = decoded
            if f_toks.size != n or not np.array_equal(
                    f_toks, toks[:n].astype(np.int64)):
                self.corrupt_rejected += 1  # key collision/garbage: miss
                self._drop(key)
                continue
            self._index.move_to_end(key)
            self.hits += 1
            return n, pages, scales, meta
        return None

    def __len__(self) -> int:
        return len(self._index)

    def stats(self) -> dict:
        return {
            "entries": len(self._index),
            "bytes": self._bytes,
            "capacity_bytes": self.capacity_bytes,
            "puts": self.puts,
            "put_drops": self.put_drops,
            "lookups": self.lookups,
            "hits": self.hits,
            "corrupt_rejected": self.corrupt_rejected,
            "evictions": self.evictions,
        }
