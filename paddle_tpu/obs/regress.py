"""Bench ledger + statistical perf-regression sentinel (ISSUE 15).

Every benchmark in the repo ends by printing one JSON metric line;
until now each bench hand-rolled that line and the history lived only
in scrollback. This module gives the line a schema and a home:

- :func:`bench_record` — the one shared emitter. Builds a
  ``paddle_tpu.bench/1`` record (bench name, metric, value, unit,
  config, host, timestamp), prints it (flushed, driver-parsable: the
  legacy ``"metric"``/``"value"``/``"unit"``/``"extra"`` keys stay at
  the top level) and appends it to the **bench ledger** — an
  append-only JSONL file named by ``ledger_path`` or the
  ``BENCH_LEDGER`` env var.
- :func:`load_ledger` — reads ledgers back. Accepts both the schema'd
  JSONL and the measurement driver's ``BENCH_r0N.json`` round files
  (``{n, cmd, rc, tail, parsed}``): a round whose ``parsed`` metric
  line is non-null contributes one record; failed/unparsed rounds are
  skipped, not errors.
- :func:`detect_regressions` — the sentinel. Per (bench, metric,
  config, host) group: candidate = newest record, baseline = the
  trailing window before it. Robust center/spread (trimmed mean +
  scaled MAD — one outlier round must not widen the gate), and a
  direction-aware verdict from per-metric **polarity**: tok/s up is
  good, p99 down is good. A candidate beyond
  ``max(mad_k * MAD, min_rel * |center|)`` in the BAD direction is a
  regression; beyond it in the good direction is an improvement;
  groups with fewer than ``min_baseline`` baseline points return
  ``insufficient_data`` (quiet — a 2-point history cannot gate).

CLI: ``python -m paddle_tpu.obs regress --ledger FILE...`` exits 1 on
any regression, 0 otherwise — the CI bench gate.
"""
from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "BENCH_SCHEMA",
    "bench_record",
    "load_ledger",
    "polarity_of",
    "trimmed_mean",
    "mad",
    "detect_regressions",
]

BENCH_SCHEMA = "paddle_tpu.bench/1"

# scale factor that makes the MAD a consistent estimator of the stddev
# under normality — the usual robust-statistics constant
_MAD_SCALE = 1.4826


# ---------------------------------------------------------------------------
# emission


def bench_record(bench: str, metric: str, value, unit: str = "", *,
                 extra: Optional[dict] = None,
                 config: Optional[dict] = None,
                 ledger_path: Optional[str] = None,
                 emit: bool = True,
                 line_prefix: str = "",
                 **fields) -> dict:
    """Build, print and ledger one bench metric record.

    The printed line keeps the legacy driver contract — a single JSON
    object with ``metric``/``value``/``unit``(/``extra``) at the top
    level — and adds the schema'd bookkeeping keys. Extra top-level
    fields the caller's old line carried (``vs_baseline``, ``error``,
    ``row``...) pass through ``**fields`` unchanged. ``emit=False``
    ledgers without printing; ``line_prefix`` preserves framed
    protocols (``BENCH_ROW ...``)."""
    rec: Dict[str, object] = {
        "schema": BENCH_SCHEMA,
        "bench": str(bench),
        "metric": str(metric),
        "value": None if value is None else float(value),
        "unit": str(unit),
    }
    if extra is not None:
        rec["extra"] = extra
    if config is not None:
        rec["config"] = config
    for k, v in fields.items():
        rec.setdefault(k, v)
    rec["host"] = socket.gethostname()
    rec["recorded_unix"] = time.time()
    if emit:
        print(line_prefix + json.dumps(rec), flush=True)
    path = ledger_path or os.environ.get("BENCH_LEDGER")
    if path:
        try:
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
        except OSError:
            pass  # an unwritable ledger must never fail the bench run
    return rec


# ---------------------------------------------------------------------------
# loading


def _from_round_file(doc: dict, path: str) -> Optional[dict]:
    """Convert one driver round file (``{n, cmd, rc, tail, parsed}``)
    into a ledger record; None when the round carried no parsed
    metric line (failed / timed-out rounds)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "metric" not in parsed:
        return None
    if not isinstance(parsed.get("value"), (int, float)):
        return None
    return {
        "schema": BENCH_SCHEMA,
        "bench": str(parsed.get("bench", "bench")),
        "metric": str(parsed["metric"]),
        "value": float(parsed["value"]),
        "unit": str(parsed.get("unit", "")),
        "round": doc.get("n"),
        "source_file": os.path.basename(path),
    }


def _normalize(doc: dict, path: str) -> Optional[dict]:
    if "parsed" in doc and "metric" not in doc:
        return _from_round_file(doc, path)
    if "metric" in doc and isinstance(doc.get("value"), (int, float)):
        out = dict(doc)
        out.setdefault("bench", str(doc.get("bench", "bench")))
        out.setdefault("source_file", os.path.basename(path))
        return out
    return None


def load_ledger(paths: Sequence[str]) -> List[dict]:
    """Read ledger records from ``paths`` in order. Each file may be a
    JSONL ledger, a single JSON record, a JSON list of records, or a
    driver round file; lines/files that carry no usable metric are
    skipped silently (the sentinel grades what exists)."""
    out: List[dict] = []
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        docs: List[dict] = []
        try:
            whole = json.loads(text)
            if isinstance(whole, list):
                docs = [d for d in whole if isinstance(d, dict)]
            elif isinstance(whole, dict):
                docs = [whole]
        except ValueError:
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    docs.append(doc)
        for doc in docs:
            rec = _normalize(doc, path)
            if rec is not None:
                out.append(rec)
    return out


# ---------------------------------------------------------------------------
# polarity


# explicit metric-name registry wins over the token heuristics
_POLARITY: Dict[str, str] = {
    "loadgen_goodput_under_slo": "up",
    "llama_train_tokens_per_sec_per_chip": "up",
}

_UP_TOKENS = ("tok", "throughput", "goodput", "mfu", "hit_rate",
              "speedup", "attainment", "accept", "per_sec", "per_s",
              "qps", "bandwidth", "samples")
_DOWN_TOKENS = ("latency", "ttft", "itl", "delay", "overhead",
                "blocked", "stall", "p999", "p99", "p95", "p50",
                "_ms", "_s", "seconds", "time")


def polarity_of(metric: str, record: Optional[dict] = None) -> str:
    """``"up"`` (bigger is better) or ``"down"`` (smaller is better).
    Resolution order: the record's own ``polarity`` field, the explicit
    registry, then name-token heuristics (up-tokens checked first so
    ``tokens_per_sec`` beats its ``_s`` suffix); unknown names default
    to ``"up"`` — the common case for bench headline numbers."""
    if record is not None:
        p = record.get("polarity")
        if p in ("up", "down"):
            return p
    m = str(metric).lower()
    if m in _POLARITY:
        return _POLARITY[m]
    for tok in _UP_TOKENS:
        if tok in m:
            return "up"
    for tok in _DOWN_TOKENS:
        if tok in m:
            return "down"
    return "up"


# ---------------------------------------------------------------------------
# robust statistics


def trimmed_mean(xs: Sequence[float], trim_frac: float = 0.2) -> float:
    """Mean of the middle (1 - 2*trim_frac) of the sorted sample; the
    ends (``floor(n * trim_frac)`` each side) are dropped so a single
    bad round cannot drag the baseline center."""
    xs = sorted(float(x) for x in xs)
    if not xs:
        raise ValueError("trimmed_mean of empty sequence")
    k = int(len(xs) * trim_frac)
    core = xs[k:len(xs) - k] or xs
    return sum(core) / len(core)


def mad(xs: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation, scaled by 1.4826 to estimate sigma."""
    xs = [float(x) for x in xs]
    if not xs:
        raise ValueError("mad of empty sequence")
    if center is None:
        s = sorted(xs)
        mid = len(s) // 2
        center = (s[mid] if len(s) % 2
                  else 0.5 * (s[mid - 1] + s[mid]))
    dev = sorted(abs(x - center) for x in xs)
    mid = len(dev) // 2
    med = dev[mid] if len(dev) % 2 else 0.5 * (dev[mid - 1] + dev[mid])
    return _MAD_SCALE * med


def _config_sig(rec: dict) -> str:
    cfg = rec.get("config")
    if not cfg:
        return ""
    return json.dumps(cfg, sort_keys=True)


def _group_key(rec: dict) -> Tuple[str, str, str, str]:
    return (str(rec.get("bench", "")), str(rec.get("metric", "")),
            _config_sig(rec), str(rec.get("host", "")))


def detect_regressions(records: Sequence[dict], *,
                       baseline_window: int = 8,
                       trim_frac: float = 0.2,
                       mad_k: float = 4.0,
                       min_rel: float = 0.05,
                       min_baseline: int = 3) -> List[dict]:
    """Grade the NEWEST record of every (bench, metric, config, host)
    group against its trailing baseline window. Returns one verdict
    dict per group (sorted by group key), ``verdict`` in
    ``{"ok", "improvement", "regression", "insufficient_data"}``.

    The gate is ``max(mad_k * scaledMAD, min_rel * |center|)``: the
    MAD term adapts to the metric's own run-to-run noise, the relative
    floor stops a freakishly quiet baseline (MAD 0 after trimming)
    from flagging sub-percent wiggle."""
    groups: Dict[Tuple[str, str, str, str], List[dict]] = {}
    for rec in records:
        if not isinstance(rec.get("value"), (int, float)):
            continue
        groups.setdefault(_group_key(rec), []).append(rec)
    out: List[dict] = []
    for key in sorted(groups):
        recs = groups[key]
        bench, metric, cfg, host = key
        cand = recs[-1]
        base = recs[:-1][-baseline_window:]
        verdict = {
            "bench": bench, "metric": metric, "host": host,
            "config": cfg or None,
            "polarity": polarity_of(metric, cand),
            "n_baseline": len(base),
            "candidate": float(cand["value"]),
        }
        if len(base) < min_baseline:
            verdict.update(verdict="insufficient_data", center=None,
                           threshold=None, delta=None)
            out.append(verdict)
            continue
        vals = [float(r["value"]) for r in base]
        center = trimmed_mean(vals, trim_frac)
        spread = mad(vals)
        threshold = max(mad_k * spread, min_rel * abs(center))
        delta = float(cand["value"]) - center
        verdict.update(center=center, mad=spread, threshold=threshold,
                       delta=delta)
        good_delta = delta if verdict["polarity"] == "up" else -delta
        if good_delta < -threshold:
            verdict["verdict"] = "regression"
        elif good_delta > threshold:
            verdict["verdict"] = "improvement"
        else:
            verdict["verdict"] = "ok"
        out.append(verdict)
    return out


def format_verdicts(verdicts: Sequence[dict]) -> str:
    """Human-readable one-line-per-group report for the CLI."""
    lines = []
    for v in verdicts:
        mark = {"regression": "REGRESSION", "improvement": "improved",
                "ok": "ok", "insufficient_data": "insufficient"}[
            v["verdict"]]
        where = v["metric"] + (f" [{v['config']}]" if v["config"] else "")
        if v["verdict"] == "insufficient_data":
            lines.append(f"{mark:>11}  {v['bench']}/{where}  "
                         f"candidate={v['candidate']:g} "
                         f"(baseline n={v['n_baseline']})")
        else:
            lines.append(
                f"{mark:>11}  {v['bench']}/{where}  "
                f"candidate={v['candidate']:g} center={v['center']:g} "
                f"delta={v['delta']:+g} gate=±{v['threshold']:g} "
                f"({v['polarity']}-is-good, n={v['n_baseline']})")
    return "\n".join(lines)
