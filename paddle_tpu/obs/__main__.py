"""``python -m paddle_tpu.obs`` — render observability state.

Subcommands::

    dump  [file.jsonl]   # JSON metrics snapshot (current process, or
                         # the LAST line of a snapshot_jsonl file)
    prom  [file.jsonl]   # Prometheus text exposition of the same
    trace [out.json]     # Chrome trace-event JSON from this process's
                         # ring (mostly useful with --stitch)
    trace --stitch a.json b.json ... [-o out.json] [--trace-id ID]
                         # merge per-worker ring dumps by trace_id
    agg STORE [--prefix obs] [--summary] [--trace-out f] [--trace-id ID]
                         # fleet aggregation (ISSUE 14): merge every
                         # obs/<id>/ publication in a KVStore (STORE is
                         # tcp://host:port or a FileKVStore directory)
                         # into one snapshot — counters summed, gauges
                         # per-source, histograms bucket-merged —
                         # optionally also the stitched fleet trace

A fresh interpreter has an empty registry, so ``dump``/``prom``
without a file mostly matter for smoke tests; the file forms are the
operational path (workers append snapshots via
``registry().snapshot_jsonl(path)`` and dump their rings at exit).
"""
from __future__ import annotations

import argparse
import json
import sys

from .metrics import registry
from .trace import export_chrome_trace, ring, stitch_traces


def _load_last_snapshot(path: str) -> dict:
    last = None
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise SystemExit(f"{path}: no snapshot lines")
    return json.loads(last)


def _snap_to_text(snap: dict) -> str:
    """Prometheus-ish text from a JSON snapshot (file path: we only
    have the serialized values, not live histograms)."""
    lines = []
    for name in sorted(snap.get("metrics", {})):
        m = snap["metrics"][name]
        lines.append(f"# TYPE {name} {m.get('kind', 'untyped')}")
        for s in m.get("series", []):
            labels = s.get("labels", {})
            body = ",".join(f'{k}="{v}"'
                            for k, v in sorted(labels.items()))
            lab = "{" + body + "}" if body else ""
            v = s.get("value")
            if isinstance(v, dict):  # serialized histogram
                for kk in ("count", "sum", "p50", "p95", "p99"):
                    if v.get(kk) is not None:
                        lines.append(f"{name}_{kk}{lab} {v[kk]}")
            elif v is not None:
                lines.append(f"{name}{lab} {v}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("dump", help="JSON metrics snapshot")
    d.add_argument("file", nargs="?", help="snapshot JSONL to render "
                   "(default: current process registry)")
    p = sub.add_parser("prom", help="Prometheus text exposition")
    p.add_argument("file", nargs="?")
    t = sub.add_parser("trace", help="Chrome trace-event JSON")
    t.add_argument("dumps", nargs="*",
                   help="with --stitch: per-worker ring-dump JSON files")
    t.add_argument("--stitch", action="store_true",
                   help="merge ring-dump files instead of exporting "
                        "this process's ring")
    t.add_argument("--trace-id", default=None,
                   help="restrict the stitch to one trace")
    t.add_argument("-o", "--out", default=None,
                   help="write the Chrome trace JSON here "
                        "(default: stdout)")
    a = sub.add_parser("agg", help="merge fleet publications from a "
                                   "KVStore into one snapshot")
    a.add_argument("store", help="store location: tcp://host:port or a "
                                 "FileKVStore directory")
    a.add_argument("--prefix", default="obs",
                   help="publication key prefix (default: obs)")
    a.add_argument("--summary", action="store_true",
                   help="print the fleet SLO/counter digest "
                        "(fleet_summary) instead of the merged snapshot")
    a.add_argument("--trace-out", default=None,
                   help="also write the stitched fleet Chrome trace here")
    a.add_argument("--trace-id", default=None,
                   help="restrict the stitched trace to one trace id")
    args = ap.parse_args(argv)

    if args.cmd == "dump":
        snap = (_load_last_snapshot(args.file) if args.file
                else registry().snapshot())
        print(json.dumps(snap, indent=2, sort_keys=True))
        return 0
    if args.cmd == "prom":
        if args.file:
            sys.stdout.write(_snap_to_text(_load_last_snapshot(args.file)))
        else:
            sys.stdout.write(registry().expose_text())
        return 0
    if args.cmd == "agg":
        from ..distributed.store import make_store
        from . import agg

        store = make_store(args.store)
        doc = (agg.fleet_summary(store, prefix=args.prefix)
               if args.summary
               else agg.fleet_snapshot(store, prefix=args.prefix))
        print(json.dumps(doc, indent=2, sort_keys=True))
        if args.trace_out:
            events = agg.fleet_trace(store, prefix=args.prefix,
                                     trace_id=args.trace_id)
            export_chrome_trace(events, path=args.trace_out)
        return 0
    # trace
    if args.stitch:
        dumps = []
        for fp in args.dumps:
            with open(fp, encoding="utf-8") as fh:
                dumps.append(json.load(fh))
        events = stitch_traces(dumps, trace_id=args.trace_id)
    else:
        events = ring().dump()
    doc = export_chrome_trace(events, path=args.out)
    if args.out is None:
        print(json.dumps({"traceEvents": doc}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
