"""``python -m paddle_tpu.obs`` — render observability state.

Subcommands::

    dump  [file.jsonl]   # JSON metrics snapshot (current process, or
                         # the LAST line of a snapshot_jsonl file)
    prom  [file.jsonl]   # Prometheus text exposition of the same
    trace [out.json]     # Chrome trace-event JSON from this process's
                         # ring (mostly useful with --stitch)
    trace --stitch a.json b.json ... [-o out.json] [--trace-id ID]
                         # merge per-worker ring dumps by trace_id
    agg STORE [--prefix obs] [--summary] [--trace-out f] [--trace-id ID]
                         # fleet aggregation (ISSUE 14): merge every
                         # obs/<id>/ publication in a KVStore (STORE is
                         # tcp://host:port or a FileKVStore directory)
                         # into one snapshot — counters summed, gauges
                         # per-source, histograms bucket-merged —
                         # optionally also the stitched fleet trace
    alerts STORE [--ttft-slo S] [--objective O] [--absence-age S]
                 [--rules] [--state]
                         # evaluate the stock serving rule set over the
                         # fleet (ISSUE 15); rc 1 when anything FIRES
    top STORE [--interval S] [--once]
                         # live text dashboard off the same store:
                         # per-source freshness, fleet totals,
                         # per-tenant SLO percentiles, active alerts
    regress --ledger FILE... [--window N] [--mad-k K] [--min-rel F]
            [--min-baseline N] [--json]
                         # bench-ledger regression sentinel: rc 1 on a
                         # detected regression (the CI bench gate),
                         # rc 0 on ok/improvement/insufficient data

A fresh interpreter has an empty registry, so ``dump``/``prom``
without a file mostly matter for smoke tests; the file forms are the
operational path (workers append snapshots via
``registry().snapshot_jsonl(path)`` and dump their rings at exit).
"""
from __future__ import annotations

import argparse
import json
import sys

from .metrics import registry
from .trace import export_chrome_trace, ring, stitch_traces


def _load_last_snapshot(path: str) -> dict:
    last = None
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise SystemExit(f"{path}: no snapshot lines")
    return json.loads(last)


def _snap_to_text(snap: dict) -> str:
    """Prometheus-ish text from a JSON snapshot (file path: we only
    have the serialized values, not live histograms)."""
    lines = []
    for name in sorted(snap.get("metrics", {})):
        m = snap["metrics"][name]
        lines.append(f"# TYPE {name} {m.get('kind', 'untyped')}")
        for s in m.get("series", []):
            labels = s.get("labels", {})
            body = ",".join(f'{k}="{v}"'
                            for k, v in sorted(labels.items()))
            lab = "{" + body + "}" if body else ""
            v = s.get("value")
            if isinstance(v, dict):  # serialized histogram
                for kk in ("count", "sum", "p50", "p95", "p99"):
                    if v.get(kk) is not None:
                        lines.append(f"{name}_{kk}{lab} {v[kk]}")
            elif v is not None:
                lines.append(f"{name}{lab} {v}")
    return "\n".join(lines) + "\n"


def _serving_rules(ttft_slo, objective, absence_age):
    from . import alerts as _alerts
    from .slo import SLOClass, SLOSpec

    spec = None
    if ttft_slo is not None:
        spec = SLOSpec(default=SLOClass(ttft_s=float(ttft_slo)))
    return _alerts.default_serving_rules(
        slo=spec, objective=objective, absence_age_s=absence_age)


def _cmd_alerts(args) -> int:
    from . import alerts as _alerts
    from ..distributed.store import make_store

    rules = _serving_rules(args.ttft_slo, args.objective,
                           args.absence_age)
    if args.rules:
        print(json.dumps([r.to_dict() for r in rules], indent=2,
                         sort_keys=True))
        return 0
    mgr = _alerts.AlertManager(rules, emit_trace=False)
    store = make_store(args.store)
    mgr.evaluate_fleet(store, prefix=args.prefix)
    doc = mgr.statuses() if args.state else mgr.active()
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 1 if mgr.firing() else 0


def _fmt_ms(v):
    return "-" if v is None else f"{1e3 * v:8.1f}"


def _top_frame(store, prefix, mgr) -> str:
    """One dashboard frame: source freshness, fleet counter totals,
    per-tenant SLO percentiles, active alerts."""
    import time as _time

    from . import agg

    states = agg.collect(store, prefix=prefix)
    summ = agg.fleet_summary(store, prefix=prefix)
    now = _time.time()
    lines = [f"paddle_tpu.obs top — {len(states)} source(s)  "
             f"{_time.strftime('%H:%M:%S')}"]
    lines.append("")
    lines.append(f"{'SOURCE':<20} {'AGE_S':>7}")
    for sid in sorted(states):
        pub = states[sid].get("published_unix")
        age = "-" if pub is None else f"{max(0.0, now - pub):7.1f}"
        lines.append(f"{sid:<20} {age:>7}")
    lines.append("")
    totals = summ.get("totals", {})
    if totals:
        lines.append("FLEET TOTALS")
        for name in sorted(totals):
            lines.append(f"  {name:<44} {totals[name]:>12g}")
        lines.append("")
    tenants = summ.get("tenants", {})
    if tenants:
        lines.append(f"{'TENANT':<14} {'TTFT_P50MS':>10} "
                     f"{'TTFT_P99MS':>10} {'ITL_P99MS':>10} {'N':>8}")
        for t in sorted(tenants):
            per = tenants[t]
            ttft = per.get("serving_ttft_seconds", {})
            itl = per.get("serving_itl_seconds", {})
            lines.append(
                f"{t:<14} {_fmt_ms(ttft.get('p50')):>10} "
                f"{_fmt_ms(ttft.get('p99')):>10} "
                f"{_fmt_ms(itl.get('p99')):>10} "
                f"{ttft.get('count', 0):>8}")
        lines.append("")
    if mgr is not None:
        mgr.evaluate_fleet(store, prefix=prefix)
        active = mgr.active()
        lines.append(f"ALERTS ({len(active)} active)")
        for a in active:
            lab = ",".join(f"{k}={v}"
                           for k, v in sorted(a["labels"].items()))
            lines.append(f"  [{a['state']:^8}] {a['rule']:<28} "
                         f"{a['severity']:<8} {lab}")
    return "\n".join(lines)


def _cmd_top(args) -> int:
    import time as _time

    from . import alerts as _alerts
    from ..distributed.store import make_store

    store = make_store(args.store)
    mgr = _alerts.AlertManager(
        _serving_rules(args.ttft_slo, args.objective, 5.0),
        emit_trace=False)
    if args.once:
        print(_top_frame(store, args.prefix, mgr))
        return 0
    try:
        while True:
            frame = _top_frame(store, args.prefix, mgr)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            _time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def _cmd_regress(args) -> int:
    from . import regress as _regress

    records = _regress.load_ledger(args.ledger)
    verdicts = _regress.detect_regressions(
        records, baseline_window=args.window, mad_k=args.mad_k,
        min_rel=args.min_rel, min_baseline=args.min_baseline)
    if args.json:
        print(json.dumps(verdicts, indent=2, sort_keys=True))
    elif verdicts:
        print(_regress.format_verdicts(verdicts))
    else:
        print("regress: no graded records in "
              f"{len(args.ledger)} ledger file(s)")
    bad = [v for v in verdicts if v["verdict"] == "regression"]
    if bad:
        print(f"regress: {len(bad)} regression(s) detected",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("dump", help="JSON metrics snapshot")
    d.add_argument("file", nargs="?", help="snapshot JSONL to render "
                   "(default: current process registry)")
    p = sub.add_parser("prom", help="Prometheus text exposition")
    p.add_argument("file", nargs="?")
    t = sub.add_parser("trace", help="Chrome trace-event JSON")
    t.add_argument("dumps", nargs="*",
                   help="with --stitch: per-worker ring-dump JSON files")
    t.add_argument("--stitch", action="store_true",
                   help="merge ring-dump files instead of exporting "
                        "this process's ring")
    t.add_argument("--trace-id", default=None,
                   help="restrict the stitch to one trace")
    t.add_argument("-o", "--out", default=None,
                   help="write the Chrome trace JSON here "
                        "(default: stdout)")
    a = sub.add_parser("agg", help="merge fleet publications from a "
                                   "KVStore into one snapshot")
    a.add_argument("store", help="store location: tcp://host:port or a "
                                 "FileKVStore directory")
    a.add_argument("--prefix", default="obs",
                   help="publication key prefix (default: obs)")
    a.add_argument("--summary", action="store_true",
                   help="print the fleet SLO/counter digest "
                        "(fleet_summary) instead of the merged snapshot")
    a.add_argument("--trace-out", default=None,
                   help="also write the stitched fleet Chrome trace here")
    a.add_argument("--trace-id", default=None,
                   help="restrict the stitched trace to one trace id")
    al = sub.add_parser("alerts", help="evaluate the stock serving "
                                       "alert rules over a fleet store")
    al.add_argument("store", help="tcp://host:port or a FileKVStore dir")
    al.add_argument("--prefix", default="obs")
    al.add_argument("--ttft-slo", type=float, default=None,
                    help="TTFT target (s): enables the SLO burn-rate "
                         "rules")
    al.add_argument("--objective", type=float, default=0.99,
                    help="SLO objective for the error budget "
                         "(default 0.99)")
    al.add_argument("--absence-age", type=float, default=5.0,
                    help="max publication age before a source counts "
                         "as silent (default 5s)")
    al.add_argument("--rules", action="store_true",
                    help="print the rule set as JSON and exit 0")
    al.add_argument("--state", action="store_true",
                    help="print every tracked alert state, not just "
                         "the active ones")
    tp = sub.add_parser("top", help="live fleet text dashboard")
    tp.add_argument("store", help="tcp://host:port or a FileKVStore dir")
    tp.add_argument("--prefix", default="obs")
    tp.add_argument("--interval", type=float, default=2.0)
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clear)")
    tp.add_argument("--ttft-slo", type=float, default=None)
    tp.add_argument("--objective", type=float, default=0.99)
    rg = sub.add_parser("regress", help="bench-ledger regression "
                                        "sentinel (CI gate)")
    rg.add_argument("--ledger", nargs="+", required=True,
                    help="ledger JSONL files and/or driver "
                         "BENCH_r0N.json round files, oldest first")
    rg.add_argument("--window", type=int, default=8,
                    help="baseline window size (default 8)")
    rg.add_argument("--mad-k", type=float, default=4.0)
    rg.add_argument("--min-rel", type=float, default=0.05)
    rg.add_argument("--min-baseline", type=int, default=3)
    rg.add_argument("--json", action="store_true",
                    help="print the verdicts as JSON")
    args = ap.parse_args(argv)

    if args.cmd == "dump":
        snap = (_load_last_snapshot(args.file) if args.file
                else registry().snapshot())
        print(json.dumps(snap, indent=2, sort_keys=True))
        return 0
    if args.cmd == "prom":
        if args.file:
            sys.stdout.write(_snap_to_text(_load_last_snapshot(args.file)))
        else:
            sys.stdout.write(registry().expose_text())
        return 0
    if args.cmd == "agg":
        from ..distributed.store import make_store
        from . import agg

        store = make_store(args.store)
        doc = (agg.fleet_summary(store, prefix=args.prefix)
               if args.summary
               else agg.fleet_snapshot(store, prefix=args.prefix))
        print(json.dumps(doc, indent=2, sort_keys=True))
        if args.trace_out:
            events = agg.fleet_trace(store, prefix=args.prefix,
                                     trace_id=args.trace_id)
            export_chrome_trace(events, path=args.trace_out)
        return 0
    if args.cmd == "alerts":
        return _cmd_alerts(args)
    if args.cmd == "top":
        return _cmd_top(args)
    if args.cmd == "regress":
        return _cmd_regress(args)
    # trace
    if args.stitch:
        dumps = []
        for fp in args.dumps:
            with open(fp, encoding="utf-8") as fh:
                dumps.append(json.load(fh))
        events = stitch_traces(dumps, trace_id=args.trace_id)
    else:
        events = ring().dump()
    doc = export_chrome_trace(events, path=args.out)
    if args.out is None:
        print(json.dumps({"traceEvents": doc}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
