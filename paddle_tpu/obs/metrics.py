"""Process-global metrics registry: counters, gauges, log-bucketed
histograms.

Every runtime stats surface in the repo (``EngineLoad``,
``prefix_stats()``, ``spec_stats()``, ``overlap_stats()``, the
``health()`` envelopes, ``TrainTelemetry`` step times) reads through
here: the legacy call signatures keep returning their historical keys,
but the numbers underneath live in ONE registry the benches, the dump
CLI, and the future autoscaler all see. Design constraints, in order:

- **Cheap hot path.** A counter increment is one attribute add on a
  handle the caller fetched once at construction time — no dict lookup,
  no lock (CPython attribute stores are atomic enough for statistics;
  we never read-modify-write across threads with invariants at stake).
  Histogram observe is one ``log2`` + a dict bump.
- **Labels are frozen tuples.** A series is keyed by
  ``(("engine", "eng3"), ("priority", "batch"))`` — sorted, hashable,
  no string formatting on the hot path.
- **Bounded cardinality, unbounded correctness.** Each metric admits at
  most ``max_series`` label sets into the EXPORTED set; later label
  sets still get fully functional private handles (so a caller's own
  reads — the parity contract — never degrade), but exports aggregate
  them into one ``obs_overflow="true"`` series instead of growing
  without bound.
- **Deterministic snapshots.** ``snapshot()`` sorts metrics and series,
  so two calls over the same state serialize identically — JSONL diffs
  and test pins stay stable.

Exports: ``snapshot()`` (plain dict), ``snapshot_jsonl(path)``
(append-one-line durable log), ``expose_text()`` (Prometheus text
exposition, histogram buckets included), and the
``python -m paddle_tpu.obs dump`` CLI over any of them.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricAttr",
    "MetricsRegistry",
    "registry",
    "labels_of",
]

LabelPairs = Tuple[Tuple[str, str], ...]

# log-bucketed histogram resolution: 4 buckets per octave (factor
# 2**(1/4) ≈ 1.19 between bounds) — ≤ ~9% relative error at the
# geometric bucket midpoint, fine for latency percentiles
_BUCKETS_PER_OCTAVE = 4


def labels_of(labels) -> LabelPairs:
    """Normalize a labels argument (dict / iterable of pairs / None)
    into the canonical sorted tuple-of-pairs form."""
    if not labels:
        return ()
    if isinstance(labels, dict):
        items: Iterable = labels.items()
    else:
        items = labels
    return tuple(sorted((str(k), str(v)) for k, v in items))


class Counter:
    """Monotonic counter handle for ONE label set. ``inc`` is the hot
    path; fetch the handle once, not per event."""

    __slots__ = ("_v",)

    def __init__(self):
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        self._v += n

    @property
    def value(self) -> float:
        return self._v

    def set_(self, v: float) -> None:
        """Test/restore seam (journal replay, engine rebuild): counters
        are monotonic for callers, but a crash-recovery path may need
        to re-seed a rebuilt engine's view."""
        self._v = float(v)


class Gauge:
    """Last-write-wins scalar. ``None`` is a legal value (EWMAs start
    unset); ``None`` series are skipped by the Prometheus exposition
    but preserved in JSON snapshots."""

    __slots__ = ("_v",)

    def __init__(self):
        self._v = None

    def set(self, v) -> None:
        self._v = v

    def inc(self, n: float = 1.0) -> None:
        self._v = (self._v or 0.0) + n

    @property
    def value(self):
        return self._v


class Histogram:
    """Log-bucketed histogram handle: O(1) observe, percentile read by
    bucket walk. Bucket ``i`` spans ``(2**((i-1)/4), 2**(i/4)]``;
    non-positive observations land in a dedicated zero bucket."""

    __slots__ = ("_counts", "_zero", "_n", "_sum", "_min", "_max")

    def __init__(self):
        self._counts: Dict[int, int] = {}
        self._zero = 0
        self._n = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        self._n += 1
        self._sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        if v <= 0.0:
            self._zero += 1
            return
        i = math.ceil(_BUCKETS_PER_OCTAVE * math.log2(v))
        self._counts[i] = self._counts.get(i, 0) + 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> Optional[float]:
        """Approximate p-th percentile (p in [0, 100]); None when
        empty. Error bounded by the bucket width (~±9%)."""
        if self._n == 0:
            return None
        rank = max(1, math.ceil(self._n * p / 100.0))
        seen = self._zero
        if rank <= seen:
            return 0.0
        for i in sorted(self._counts):
            seen += self._counts[i]
            if rank <= seen:
                # geometric midpoint of the bucket, clamped into the
                # observed range so tail percentiles never exceed max
                mid = 2.0 ** ((i - 0.5) / _BUCKETS_PER_OCTAVE)
                return min(max(mid, self._min), self._max)
        return self._max

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(50)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(95)

    @property
    def p99(self) -> Optional[float]:
        return self.percentile(99)

    def count_over(self, threshold: float) -> int:
        """Observations strictly above ``threshold``, counted from
        buckets that lie WHOLLY above it — the straddling bucket is
        excluded, so the result under-counts by at most that one
        bucket's population (~9% band). When ``threshold`` is an exact
        bucket bound (``2**(k/4)``), the count is exact: the alert
        engine's burn-rate rules read SLO violations through this."""
        t = float(threshold)
        if t < 0.0:
            return self._n
        if t == 0.0:
            return self._n - self._zero
        j = _BUCKETS_PER_OCTAVE * math.log2(t)
        # bucket i spans (2**((i-1)/4), 2**(i/4)]: wholly above t iff
        # its lower bound >= t, i.e. i >= j + 1 (epsilon absorbs the
        # log2 round-trip on exact bounds)
        i_min = math.ceil(j - 1e-9) + 1
        return sum(c for i, c in self._counts.items() if i >= i_min)

    def bounds_counts(self) -> List[Tuple[float, int]]:
        """(upper_bound, count) per non-empty bucket, ascending — the
        Prometheus ``le`` exposition reads this."""
        out: List[Tuple[float, int]] = []
        if self._zero:
            out.append((0.0, self._zero))
        for i in sorted(self._counts):
            out.append((2.0 ** (i / _BUCKETS_PER_OCTAVE),
                        self._counts[i]))
        return out

    def to_dict(self) -> dict:
        return {
            "count": self._n,
            "sum": self._sum,
            "min": None if self._n == 0 else self._min,
            "max": None if self._n == 0 else self._max,
            "p50": self.p50,
            "p95": self.p95,
            "p99": self.p99,
        }

    # -- full-fidelity state (fleet aggregation) ------------------------
    # ``to_dict`` is the human/summary view and DROPS the buckets; the
    # aggregator needs them back, so cross-process publication rides
    # ``state_dict``/``merge_state`` instead.

    def state_dict(self) -> dict:
        """JSON-safe full state: buckets included, so a remote copy can
        be bucket-merged losslessly (unlike ``to_dict``)."""
        return {
            "counts": {str(i): c for i, c in sorted(self._counts.items())},
            "zero": self._zero,
            "n": self._n,
            "sum": self._sum,
            "min": None if self._n == 0 else self._min,
            "max": None if self._n == 0 else self._max,
        }

    def merge_state(self, state: dict) -> None:
        """Bucket-merge a ``state_dict`` into this histogram: counts
        add, min/max widen — the union stream's histogram, exactly."""
        for i, c in state.get("counts", {}).items():
            i = int(i)
            self._counts[i] = self._counts.get(i, 0) + int(c)
        self._zero += int(state.get("zero", 0))
        self._n += int(state.get("n", 0))
        self._sum += float(state.get("sum", 0.0))
        lo, hi = state.get("min"), state.get("max")
        if lo is not None and lo < self._min:
            self._min = float(lo)
        if hi is not None and hi > self._max:
            self._max = float(hi)

    def merge(self, other: "Histogram") -> None:
        self.merge_state(other.state_dict())

    @classmethod
    def from_state(cls, state: dict) -> "Histogram":
        h = cls()
        h.merge_state(state)
        return h


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Metric:
    """One named metric: kind + help + its admitted series, plus the
    overflow handles past the cardinality cap."""

    def __init__(self, name: str, kind: str, help_: str,
                 max_series: int):
        self.name = name
        self.kind = kind
        self.help = help_
        self.max_series = max_series
        self.series: Dict[LabelPairs, object] = {}
        self.overflow: List[object] = []

    def get(self, labels: LabelPairs, lock: threading.Lock):
        h = self.series.get(labels)
        if h is not None:
            return h
        with lock:
            h = self.series.get(labels)
            if h is not None:
                return h
            h = _KINDS[self.kind]()
            if len(self.series) < self.max_series:
                self.series[labels] = h
            else:
                # past the cap: the CALLER still gets a fully live
                # handle (its own reads stay exact); only the exported
                # series set stops growing
                self.overflow.append(h)
        return h


class MetricsRegistry:
    """The process-global metric store. ``counter()``/``gauge()``/
    ``histogram()`` return per-label-set handles; snapshot/exposition
    walk every admitted series deterministically."""

    def __init__(self, *, max_series: int = 512):
        self.max_series = int(max_series)
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- handle acquisition (construction-time, not hot path) -----------
    def _get(self, name: str, kind: str, labels, help_: str,
             max_series: Optional[int]):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = _Metric(name, kind, help_,
                                max_series or self.max_series)
                    self._metrics[name] = m
        if m.kind != kind:
            raise ValueError(
                f"metric {name!r} is a {m.kind}, not a {kind}")
        return m.get(labels_of(labels), self._lock)

    def counter(self, name: str, labels=None, *, help: str = "",
                max_series: Optional[int] = None) -> Counter:
        return self._get(name, "counter", labels, help, max_series)

    def gauge(self, name: str, labels=None, *, help: str = "",
              max_series: Optional[int] = None) -> Gauge:
        return self._get(name, "gauge", labels, help, max_series)

    def histogram(self, name: str, labels=None, *, help: str = "",
                  max_series: Optional[int] = None) -> Histogram:
        return self._get(name, "histogram", labels, help, max_series)

    # -- introspection ---------------------------------------------------
    def names(self) -> List[str]:
        return sorted(self._metrics)

    def series_count(self, name: str) -> int:
        m = self._metrics.get(name)
        return 0 if m is None else len(m.series)

    def value(self, name: str, labels=None):
        """Read one series' value (counter/gauge scalar, histogram
        dict); None when the metric or series does not exist."""
        m = self._metrics.get(name)
        if m is None:
            return None
        h = m.series.get(labels_of(labels))
        if h is None:
            return None
        if isinstance(h, Histogram):
            return h.to_dict()
        return h.value

    def total(self, name: str) -> float:
        """Sum of a counter/gauge metric across every series (overflow
        handles included) — the health() envelopes read these."""
        m = self._metrics.get(name)
        if m is None:
            return 0.0
        out = 0.0
        for h in list(m.series.values()) + list(m.overflow):
            v = getattr(h, "value", None)
            if isinstance(v, (int, float)):
                out += v
        return out

    # -- export ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Deterministic plain-dict snapshot of every admitted series
        (overflow aggregated into one marked series per metric)."""
        out: Dict[str, dict] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            series = []
            for labels in sorted(m.series):
                h = m.series[labels]
                v = h.to_dict() if isinstance(h, Histogram) else h.value
                series.append({"labels": dict(labels), "value": v})
            if m.overflow:
                agg = sum(h.value for h in m.overflow
                          if isinstance(getattr(h, "value", None),
                                        (int, float)))
                series.append({"labels": {"obs_overflow": "true"},
                               "value": agg,
                               "dropped_series": len(m.overflow)})
            out[name] = {"kind": m.kind, "help": m.help,
                         "series": series}
        return {"schema": "paddle_tpu.obs.metrics/1", "metrics": out}

    def dump_state(self) -> dict:
        """Full-fidelity export for cross-process aggregation: unlike
        ``snapshot()`` (whose histograms collapse to summary stats),
        this keeps every histogram's buckets and the raw overflow
        handles, so a remote aggregator can bucket-merge losslessly.
        Schema ``paddle_tpu.obs.metrics/state1``."""
        def _state(h):
            return (h.state_dict() if isinstance(h, Histogram)
                    else h.value)

        out: Dict[str, dict] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            series = [{"labels": dict(labels),
                       "state": _state(m.series[labels])}
                      for labels in sorted(m.series)]
            out[name] = {"kind": m.kind, "help": m.help,
                         "series": series,
                         "overflow": [_state(h) for h in m.overflow]}
        return {"schema": "paddle_tpu.obs.metrics/state1", "metrics": out}

    def snapshot_jsonl(self, path: str) -> dict:
        """Append one JSON line (the snapshot) to ``path``; returns the
        snapshot. The dump CLI renders these files."""
        snap = self.snapshot()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(snap, sort_keys=True) + "\n")
        return snap

    def expose_text(self) -> str:
        """Prometheus text exposition (counters/gauges as samples,
        histograms as cumulative ``_bucket``/``_sum``/``_count``)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for labels in sorted(m.series):
                h = m.series[labels]
                if isinstance(h, Histogram):
                    cum = 0
                    for bound, cnt in h.bounds_counts():
                        cum += cnt
                        lab = _prom_labels(labels + (("le", repr(bound)),))
                        lines.append(f"{name}_bucket{lab} {cum}")
                    lab = _prom_labels(labels + (("le", "+Inf"),))
                    lines.append(f"{name}_bucket{lab} {h.count}")
                    lines.append(
                        f"{name}_sum{_prom_labels(labels)} {h.sum}")
                    lines.append(
                        f"{name}_count{_prom_labels(labels)} {h.count}")
                    continue
                v = h.value
                if v is None:
                    continue
                lines.append(f"{name}{_prom_labels(labels)} {v}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric — test isolation only."""
        with self._lock:
            self._metrics.clear()


def _prom_labels(labels: LabelPairs) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in labels)
    return "{" + body + "}"


class MetricAttr:
    """Class-level descriptor: a registry-backed instance attribute.

    The legacy stats surfaces are plain counters mutated in place
    (``self.steps += 1``) and occasionally written from OUTSIDE the
    owning object (the overload bench resets ``eng.ewma_step_s = None``)
    — a data descriptor keeps every such site byte-identical while the
    number itself lives in a registry series labeled by the instance's
    ``_obs_labels`` dict (which must exist before the first access).
    ``kind`` is "counter" (optionally ``as_int`` for surfaces that
    always held ints) or "gauge" (``None`` is a legal value)."""

    __slots__ = ("_metric", "_kind", "_as_int", "_help", "_slot")

    def __init__(self, metric: str, *, kind: str = "counter",
                 as_int: bool = False, help: str = ""):
        if kind not in ("counter", "gauge"):
            raise ValueError(f"MetricAttr kind must be counter|gauge, "
                             f"got {kind!r}")
        self._metric = metric
        self._kind = kind
        self._as_int = as_int
        self._help = help
        self._slot = f"_obsh_{metric}"

    def __set_name__(self, owner, name):  # the attr name is cosmetic
        pass

    def _bind(self, obj):
        reg = _REGISTRY
        labels = getattr(obj, "_obs_labels", None)
        get = reg.counter if self._kind == "counter" else reg.gauge
        h = get(self._metric, labels, help=self._help)
        obj.__dict__[self._slot] = h
        return h

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        h = obj.__dict__.get(self._slot)
        if h is None:
            h = self._bind(obj)
        v = h.value
        if self._kind == "counter" and self._as_int:
            return int(v)
        return v

    def __set__(self, obj, v):
        h = obj.__dict__.get(self._slot)
        if h is None:
            h = self._bind(obj)
        if self._kind == "counter":
            h.set_(float(v))
        else:
            h.set(v)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every stats surface reads through."""
    return _REGISTRY
