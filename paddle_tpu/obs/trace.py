"""Per-request distributed tracing: Dapper-style spans in a bounded
per-process ring, exportable as Chrome trace-event JSON (loadable in
Perfetto / ``chrome://tracing``), stitchable across processes.

Model:

- A **trace** is one request's journey, identified by ``trace_id`` — a
  random 16-hex id minted at admission and carried on ``GenRequest``,
  the cluster wire records, and the disagg handoff payload header, so
  a decode-worker span parents correctly across the process boundary.
- A **span** is one named leg (``admission``, ``route``, ``prefill``,
  ``handoff_send``, ``handoff_recv``, ``decode``, ``dispatch``,
  ``harvest``) with a start time, a duration, and a parent span id.
  Use the :func:`span` context manager for synchronous legs and the
  explicit :func:`start_span`/:func:`finish_span` pair for async legs
  (the overlap copy ring issues a dispatch span at submit time and
  finishes it at harvest, possibly many steps later).
- An **instant** is a zero-duration event (watchdog escalation,
  rollback, chaos injection, XLA compile start) that lands on the same
  timeline as the request spans.

Recording is a deque append — bounded (``TraceRing``), allocation-light,
and togglable: :func:`set_enabled(False)` turns every record into a
no-op while keeping id propagation intact, which is what the
``serving_throughput.py --obs`` A/B measures. Timestamps are wall-clock
(``time.time()``) so per-worker ring dumps from different processes
merge on one axis; :func:`stitch_traces` unions dumps and
:func:`export_chrome_trace` renders either a single ring or a stitched
set.
"""
from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from typing import Dict, Iterable, List, Optional

__all__ = [
    "Span",
    "TraceRing",
    "new_trace_id",
    "span",
    "start_span",
    "finish_span",
    "instant",
    "trace_ctx",
    "ring",
    "set_enabled",
    "enabled",
    "set_process_label",
    "export_chrome_trace",
    "stitch_traces",
]


# ids need uniqueness, not unpredictability: the random module's C
# PRNG (urandom-seeded at import, reseeded after fork below) skips the
# per-span os.urandom syscall — ids are minted on the serving hot path
_ID_RNG = random.Random()


def new_trace_id() -> str:
    return "%016x" % _ID_RNG.getrandbits(64)


def _new_span_id() -> str:
    return "%012x" % _ID_RNG.getrandbits(48)


class Span:
    """One in-flight or finished span. Mutable on purpose: async legs
    hold the object open across steps and attach result args at
    finish."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "dur", "ph", "tid", "args", "_t0")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], tid: str, args: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self._t0 = time.perf_counter()
        self.dur = None
        self.ph = "X"
        self.tid = tid
        self.args = args

    @property
    def ts(self) -> float:
        # wall-clock start derived from the per-process anchor: one
        # clock read per span instead of two, still mergeable across
        # process rings (drift over a serve window is visualization-
        # negligible)
        return _WALL0 + self._t0

    def ctx(self) -> dict:
        """The carryable context: what rides a wire record / handoff
        header so the far side can parent its spans under this one."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_dict(self) -> dict:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "ts": self.ts, "dur": self.dur, "ph": self.ph,
            "proc": _PROC_LABEL, "pid": _PID, "tid": self.tid,
            "args": self.args,
        }


class TraceRing:
    """Bounded ring of FINISHED events (spans + instants)."""

    def __init__(self, capacity: int = 8192):
        self._ring = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.n_recorded = 0

    @property
    def n_dropped(self) -> int:
        # derived, not tracked: keeps record() to an append + counter
        return max(0, self.n_recorded - self._ring.maxlen)

    def record(self, event) -> None:
        # lock-free hot path: deque.append is GIL-atomic (maxlen evicts
        # inside the same bytecode op) and the counter is advisory —
        # the lock guards only the dump/clear snapshots. Accepts a dict
        # OR a finished Span — Spans materialize lazily at dump() so
        # the serving step never pays the 11-key dict build
        self._ring.append(event)
        self.n_recorded += 1

    def dump(self) -> List[dict]:
        with self._lock:
            return [e.to_dict() if isinstance(e, Span) else e
                    for e in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.n_recorded = 0

    def __len__(self) -> int:
        return len(self._ring)


_RING = TraceRing()
_ENABLED = True
# wall-clock anchor for Span.ts: ts = _WALL0 + perf_counter()
_WALL0 = time.time() - time.perf_counter()
_PID = os.getpid()
_PROC_LABEL = f"pid{_PID}"


def _refork():  # keep cached pid + id stream honest in forked workers
    global _PID, _PROC_LABEL
    old, _PID = _PID, os.getpid()
    if _PROC_LABEL == f"pid{old}":
        _PROC_LABEL = f"pid{_PID}"
    _ID_RNG.seed(os.urandom(16))


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_refork)


def ring() -> TraceRing:
    return _RING


def set_enabled(on: bool) -> bool:
    """Toggle span/instant RECORDING (id propagation stays on so a
    re-enable mid-request still stitches). Returns the previous
    state."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(on)
    return prev


def enabled() -> bool:
    return _ENABLED


def set_process_label(label: str) -> None:
    """Name this process's track in exported timelines (e.g. the
    disagg worker id instead of a bare pid)."""
    global _PROC_LABEL
    _PROC_LABEL = str(label)


def trace_ctx(obj) -> Optional[dict]:
    """Extract a carryable trace context from a Span, a context dict,
    or an object with ``trace_id``/``span_id`` attributes (GenRequest);
    None when the object carries no trace."""
    if obj is None:
        return None
    if isinstance(obj, Span):
        return obj.ctx()
    if isinstance(obj, dict):
        tid = obj.get("trace_id")
        return {"trace_id": tid, "span_id": obj.get("span_id")} \
            if tid else None
    tid = getattr(obj, "trace_id", None)
    if not tid:
        return None
    return {"trace_id": tid, "span_id": getattr(obj, "span_id", None)}


def _resolve_parent(trace_id, parent):
    ctx = trace_ctx(parent)
    if ctx is not None:
        return ctx["trace_id"], ctx.get("span_id")
    return trace_id, None


def start_span(name: str, *, trace_id: Optional[str] = None,
               parent=None, tid: str = "main", **args) -> Span:
    """Open a span. ``parent`` may be a Span, a carried context dict,
    or any object with trace_id/span_id attributes; when it carries a
    trace the span joins it, otherwise ``trace_id`` (or a fresh id) is
    used. Always returns a usable Span — recording is decided at
    finish time."""
    ptrace, pspan = _resolve_parent(trace_id, parent)
    return Span(name, ptrace or new_trace_id(), _new_span_id(), pspan,
                tid, args)


def finish_span(sp: Optional[Span], **args) -> Optional[Span]:
    """Close a span and record it (when tracing is enabled). Extra
    kwargs merge into the span's args. Idempotent-ish: a second finish
    records a second event, so callers own at-most-once."""
    if sp is None:
        return None
    sp.dur = time.perf_counter() - sp._t0
    if args:
        sp.args.update(args)
    if _ENABLED:
        _RING.record(sp)
    return sp


class _SpanCtx:
    __slots__ = ("_span",)

    def __init__(self, sp: Span):
        self._span = sp

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._span.args.setdefault("error", exc_type.__name__)
        finish_span(self._span)
        return False


def span(name: str, *, trace_id: Optional[str] = None, parent=None,
         tid: str = "main", **args) -> _SpanCtx:
    """Context-manager form for synchronous legs::

        with obs.span("route", parent=req) as sp:
            ...
    """
    return _SpanCtx(start_span(name, trace_id=trace_id, parent=parent,
                               tid=tid, **args))


def instant(name: str, *, trace_id: Optional[str] = None, parent=None,
            tid: str = "main", **args) -> None:
    """Record a zero-duration event (watchdog/rollback/chaos/compile
    markers) on the same timeline as the spans."""
    if not _ENABLED:
        return
    ptrace, pspan = _resolve_parent(trace_id, parent)
    sp = Span(name, ptrace or "", _new_span_id(), pspan, tid, args)
    sp.ph = "i"
    sp.dur = 0.0
    _RING.record(sp)


# ---------------------------------------------------------------------------
# Export / cross-process stitch


def stitch_traces(dumps: Iterable[List[dict]],
                  trace_id: Optional[str] = None) -> List[dict]:
    """Union per-worker ring dumps into one event list sorted by
    timestamp, optionally filtered to a single ``trace_id`` — the
    cross-process merge a 2-process disagg deployment needs to see one
    request's admission→handoff→decode tree on one timeline."""
    merged: List[dict] = []
    for d in dumps:
        for ev in d:
            if trace_id is None or ev.get("trace_id") == trace_id:
                merged.append(ev)
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("span_id", "")))
    return merged


def export_chrome_trace(events: Optional[List[dict]] = None,
                        path: Optional[str] = None) -> List[dict]:
    """Render ring events (default: this process's ring) as Chrome
    trace-event JSON objects; optionally write ``{"traceEvents": ...}``
    to ``path`` for Perfetto. Span events use phase "X"
    (complete), instants phase "i"; trace/span/parent ids ride in
    ``args`` so the tree is reconstructable from the file alone."""
    if events is None:
        events = _RING.dump()
    procs: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    out: List[dict] = []
    meta: List[dict] = []
    for ev in events:
        proc = str(ev.get("proc", ev.get("pid", 0)))
        if proc not in procs:
            procs[proc] = len(procs) + 1
            meta.append({"ph": "M", "name": "process_name",
                         "pid": procs[proc], "tid": 0,
                         "args": {"name": proc}})
        pid = procs[proc]
        tkey = (proc, str(ev.get("tid", "main")))
        if tkey not in tids:
            tids[tkey] = len([k for k in tids if k[0] == proc]) + 1
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pid, "tid": tids[tkey],
                         "args": {"name": tkey[1]}})
        entry = {
            "name": ev["name"],
            "cat": "obs",
            "ph": ev.get("ph", "X"),
            "ts": ev["ts"] * 1e6,
            "pid": pid,
            "tid": tids[tkey],
            "args": {
                "trace_id": ev.get("trace_id"),
                "span_id": ev.get("span_id"),
                "parent_id": ev.get("parent_id"),
                **(ev.get("args") or {}),
            },
        }
        if entry["ph"] == "X":
            entry["dur"] = max(ev.get("dur") or 0.0, 0.0) * 1e6
        else:
            entry["s"] = "p"
        out.append(entry)
    doc = meta + out
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": doc,
                       "displayTimeUnit": "ms"}, fh)
    return doc
