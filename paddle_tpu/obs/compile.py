"""Device/compile timeline hooks: XLA compile events on the obs
timeline and in the registry.

Reuses the exact jax compile-log seam ``recompile_guard`` listens on
(``analysis/sanitizers.py``: the ``Compiling <name> ...`` records from
``jax._src.interpreters.pxla`` / ``jax._src.compiler``) plus the
``Finished XLA compilation of <name> in <t> sec`` record
``jax._src.dispatch`` emits, so compile COUNT and WALL TIME are both
captured, tagged by program name, with no private jax API touched.
If the logging shape ever changes, counts drop to zero and the pinned
obs tests fail visibly — the same failure contract the guard makes.

Install is explicit and idempotent (:func:`install_compile_events`);
:func:`uninstall_compile_events` restores the loggers exactly, so the
hook composes with ``recompile_guard`` (which snapshots and restores
logger state around its own handler) and never leaks DEBUG levels
into an application's root logging.
"""
from __future__ import annotations

import logging
import re
from typing import List, Optional, Tuple

from ..analysis.sanitizers import COMPILE_LOGGERS, COMPILING_RE
from .metrics import registry
from .trace import instant

__all__ = [
    "install_compile_events",
    "uninstall_compile_events",
    "compile_events_installed",
]

# the wall-time record comes from the dispatch logger (see
# jax._src.dispatch.log_elapsed_time), not the two compile loggers
FINISHED_LOGGER = "jax._src.dispatch"
FINISHED_RE = re.compile(
    r"Finished XLA compilation of (\S+) in ([0-9.eE+-]+) sec")

_ALL_LOGGERS: Tuple[str, ...] = tuple(COMPILE_LOGGERS) + (
    FINISHED_LOGGER,)


class _CompileHandler(logging.Handler):
    """Parses the two record shapes into registry series + timeline
    instants. Counter: ``jax_compiles_total{program}``. Histogram:
    ``jax_compile_seconds{program}``."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)

    def emit(self, record):
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — logging must never raise
            return
        try:
            m = COMPILING_RE.search(msg)
            if m:
                name = m.group(1)
                registry().counter(
                    "jax_compiles_total", {"program": name},
                    help="XLA compilations by program name").inc()
                instant("xla_compile", tid="compile", program=name)
                return
            m = FINISHED_RE.search(msg)
            if m:
                name, secs = m.group(1), float(m.group(2))
                registry().histogram(
                    "jax_compile_seconds", {"program": name},
                    help="XLA compile wall time by program"
                ).observe(secs)
                instant("xla_compile_done", tid="compile",
                        program=name, seconds=secs)
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


_installed: Optional[_CompileHandler] = None
_saved: List[Tuple[logging.Logger, int, bool]] = []


def compile_events_installed() -> bool:
    return _installed is not None


def install_compile_events() -> None:
    """Attach the compile-event handler (idempotent). Lowers only the
    three jax compile/dispatch loggers to DEBUG and stops their
    propagation (the guard's exact discipline) so the temporarily-
    DEBUG records don't spray through the application's root
    handler."""
    global _installed
    if _installed is not None:
        return
    handler = _CompileHandler()
    for name in _ALL_LOGGERS:
        lg = logging.getLogger(name)
        _saved.append((lg, lg.level, lg.propagate))
        if lg.getEffectiveLevel() > logging.DEBUG:
            lg.setLevel(logging.DEBUG)
            lg.propagate = False
        lg.addHandler(handler)
    _installed = handler


def uninstall_compile_events() -> None:
    """Detach and restore every logger exactly (level + propagate)."""
    global _installed
    if _installed is None:
        return
    for lg, lvl, prop in _saved:
        try:
            lg.removeHandler(_installed)
            lg.setLevel(lvl)
            lg.propagate = prop
        except Exception:  # noqa: BLE001 — restore the rest anyway
            pass
    _saved.clear()
    _installed = None
