"""Fleet aggregation over any KVStore (ISSUE 14).

PR 11 made every process observable alone: a metrics registry, a trace
ring, SLO histograms. This module makes the FLEET observable: each
replica/worker periodically publishes a CRC-framed, full-fidelity
registry dump (histogram buckets included — ``snapshot()`` collapses
them, ``dump_state()`` keeps them) and its trace-ring dump under
``obs/<source>/`` in whatever store the deployment already shares
(Mem/File/TCP); an aggregator — the ``fleet_summary()`` API or
``python -m paddle_tpu.obs agg`` — merges them into one fleet snapshot
and one stitched trace.

Merge semantics, per metric name:

- **counters** — summed across sources for identical label sets (two
  workers both label their engine ``eng0``; the fleet total is the sum,
  which is the number that means anything fleet-wide).
- **gauges** — last-write-wins scalars cannot be summed meaningfully,
  so each source's series keeps its value under an added
  ``obs_source=<id>`` label.
- **histograms** — bucket-merged (counts add, min/max widen): the
  merged percentiles are exactly the union stream's percentiles within
  bucket resolution, because the buckets are identical log buckets in
  every process.

Overflow handles (past the registry cardinality cap) are merged into
one ``obs_overflow="true"`` series per metric so nothing is silently
dropped. The merged result is materialized into a fresh
:class:`~paddle_tpu.obs.metrics.MetricsRegistry`, so every existing
reader (``snapshot()``, ``expose_text()``, ``total()``, the dump CLI)
works on the fleet view unchanged.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import trace as _trace
from .metrics import Histogram, MetricsRegistry

__all__ = [
    "publish",
    "Publisher",
    "sources",
    "collect",
    "merge_states",
    "fleet_snapshot",
    "fleet_summary",
    "fleet_trace",
]

_PREFIX = "obs"


def _metrics_key(prefix: str, source_id: str) -> str:
    return f"{prefix}/{source_id}/metrics"


def _trace_key(prefix: str, source_id: str) -> str:
    return f"{prefix}/{source_id}/trace"


def publish(store, source_id: str, *, prefix: str = _PREFIX,
            registry: Optional[MetricsRegistry] = None,
            ring=None) -> None:
    """Publish this process's registry dump and trace-ring dump under
    ``<prefix>/<source_id>/`` — CRC-framed (``put_bytes``), so a torn
    or bit-flipped blob surfaces as :class:`CorruptBlobError` at the
    aggregator instead of a silently wrong fleet number."""
    reg = registry if registry is not None else _metrics.registry()
    rg = ring if ring is not None else _trace.ring()
    state = reg.dump_state()
    state["source"] = str(source_id)
    state["published_unix"] = time.time()
    store.put_bytes(_metrics_key(prefix, source_id),
                    json.dumps(state, sort_keys=True).encode("utf-8"))
    store.put_bytes(_trace_key(prefix, source_id),
                    json.dumps(rg.dump()).encode("utf-8"))


class Publisher:
    """Periodic publication wrapper for serve loops: call
    ``maybe_publish()`` as often as you like — it republishes at most
    every ``interval_s`` (publication walks the whole registry, so it
    must not ride a 50 Hz poll loop at full rate), and ``publish()``
    forces a final flush at exit."""

    def __init__(self, store, source_id: str, *, prefix: str = _PREFIX,
                 interval_s: float = 0.5):
        self.store = store
        self.source_id = str(source_id)
        self.prefix = prefix
        self.interval_s = float(interval_s)
        self._last = 0.0

    def maybe_publish(self) -> bool:
        now = time.monotonic()
        if now - self._last < self.interval_s:
            return False
        self.publish()
        return True

    def publish(self) -> None:
        self._last = time.monotonic()
        publish(self.store, self.source_id, prefix=self.prefix)


def sources(store, *, prefix: str = _PREFIX) -> List[str]:
    """Source ids that have published a metrics dump, sorted."""
    lead = prefix + "/"
    out = set()
    for key in store.keys(lead):
        rest = key[len(lead):]
        if rest.endswith("/metrics"):
            out.add(rest[:-len("/metrics")])
    return sorted(out)


def collect(store, *, prefix: str = _PREFIX) -> Dict[str, dict]:
    """source_id -> its published ``dump_state()`` dict. A source whose
    blob is missing (raced with its first publish) is skipped; a
    CORRUPT blob raises — a wrong fleet total is worse than no total."""
    out: Dict[str, dict] = {}
    for sid in sources(store, prefix=prefix):
        raw = store.get_bytes(_metrics_key(prefix, sid))
        if raw is None:
            continue
        out[sid] = json.loads(raw.decode("utf-8"))
    return out


def merge_states(states: Dict[str, dict]) -> MetricsRegistry:
    """Merge per-source ``dump_state()`` dicts into a fresh registry:
    counters summed, gauges kept per-source (``obs_source`` label),
    histograms bucket-merged; overflow folded into one
    ``obs_overflow="true"`` series per metric."""
    reg = MetricsRegistry()
    for sid in sorted(states):
        st = states[sid]
        for name, m in sorted(st.get("metrics", {}).items()):
            kind, help_ = m["kind"], m.get("help", "")
            series: List[Tuple[dict, object]] = [
                (s["labels"], s["state"]) for s in m.get("series", ())]
            for ov in m.get("overflow", ()):
                series.append(({"obs_overflow": "true"}, ov))
            for labels, state in series:
                if kind == "counter":
                    h = reg.counter(name, labels, help=help_)
                    h.inc(float(state or 0.0))
                elif kind == "gauge":
                    lab = (labels if "obs_overflow" in labels
                           else {**labels, "obs_source": sid})
                    reg.gauge(name, lab, help=help_).set(state)
                else:
                    h = reg.histogram(name, labels, help=help_)
                    h.merge_state(state)
    return reg


def fleet_snapshot(store, *, prefix: str = _PREFIX) -> dict:
    """One merged fleet snapshot (the normal ``snapshot()`` schema, so
    the dump CLI and every snapshot reader render it unchanged) plus
    the contributing ``sources`` list."""
    states = collect(store, prefix=prefix)
    snap = merge_states(states).snapshot()
    snap["sources"] = sorted(states)
    return snap


def fleet_summary(store, *, prefix: str = _PREFIX) -> dict:
    """The fleet-wide SLO/health digest: counter totals summed across
    processes plus the merged SLO histograms, overall and per tenant."""
    from . import SLO_HISTOGRAMS  # package __init__ imports this module's
    # sibling; importing lazily keeps the module graph acyclic
    states = collect(store, prefix=prefix)
    reg = merge_states(states)
    totals = {}
    for name in reg.names():
        m = reg._metrics[name]
        if m.kind == "counter":
            totals[name] = reg.total(name)
    slo: Dict[str, dict] = {}
    tenants: Dict[str, Dict[str, Histogram]] = {}
    for name in SLO_HISTOGRAMS:
        agg = Histogram()
        m = reg._metrics.get(name)
        if m is not None:
            for labels, h in m.series.items():
                agg.merge(h)
                t = dict(labels).get("tenant", "default")
                tenants.setdefault(t, {}).setdefault(
                    name, Histogram()).merge(h)
        slo[name] = agg.to_dict()
    return {
        "schema": "paddle_tpu.obs.agg/1",
        "sources": sorted(states),
        "totals": totals,
        "slo": slo,
        "tenants": {
            t: {name: h.to_dict() for name, h in sorted(per.items())}
            for t, per in sorted(tenants.items())
        },
    }


def fleet_trace(store, *, prefix: str = _PREFIX,
                trace_id: Optional[str] = None,
                extra_dumps: Optional[List[list]] = None) -> list:
    """Stitch every published trace-ring dump (plus any local
    ``extra_dumps``, e.g. the driver's own ring) into one Chrome-trace
    event list, optionally filtered to one ``trace_id``."""
    dumps: List[list] = list(extra_dumps or [])
    for sid in sources(store, prefix=prefix):
        raw = store.get_bytes(_trace_key(prefix, sid))
        if raw is None:
            continue
        dumps.append(json.loads(raw.decode("utf-8")))
    return _trace.stitch_traces(dumps, trace_id=trace_id)
