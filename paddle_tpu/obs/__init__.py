"""paddle_tpu.obs — the unified observability layer (ISSUE 12).

One subsystem, three planes, one timeline:

- **Metrics** (:mod:`.metrics`): a process-global registry of named
  counters / gauges / log-bucketed histograms with frozen-tuple
  labels. Every legacy stats surface (``EngineLoad``,
  ``prefix_stats()``, ``spec_stats()``, ``overlap_stats()``, the
  ``health()`` envelopes, ``TrainTelemetry`` step times, the
  admission counters) is now a VIEW over this registry: old call
  signatures return their historical keys, the numbers live here.
  Built-in SLO histograms: ``serving_ttft_seconds``,
  ``serving_itl_seconds``, ``serving_queue_delay_seconds`` with
  p50/p95/p99 accessors (:func:`slo_summary`).
- **Traces** (:mod:`.trace`): Dapper-style per-request spans carried on
  ``GenRequest`` → cluster wire records → the disagg handoff payload
  header, collected in a bounded per-process ring, exported as Chrome
  trace-event JSON (Perfetto-loadable) and stitched across worker
  processes by trace_id.
- **Device/compile events** (:mod:`.compile`): XLA compile count +
  wall time from the same jax compile-log seam ``recompile_guard``
  uses; dispatch→harvest spans from the serving engine's async copy
  ring; supervisor watchdog / rollback / chaos instants.

- **Reaction** (:mod:`.alerts`, :mod:`.regress` — ISSUE 15): the layer
  that converts the planes above into decisions. Declarative alert
  rules (thresholds, publisher-absence, multi-window SLO burn rates
  with per-(tenant, priority) error budgets) with a deterministic
  pending → firing → resolved lifecycle, surfaced through every
  ``health()`` envelope, the trace ring, and a JSONL journal; plus the
  schema'd bench ledger and its statistical perf-regression sentinel.

CLI: ``python -m paddle_tpu.obs dump|prom|trace|agg|alerts|top|regress``.
"""
from .alerts import (
    AbsenceRule,
    AlertManager,
    BurnRateRule,
    ThresholdRule,
    budget_remaining_frac,
    burn_rate,
    burn_rules_from_slo,
    default_manager,
    default_serving_rules,
    default_training_rules,
    set_default_manager,
)
from .compile import (
    compile_events_installed,
    install_compile_events,
    uninstall_compile_events,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricAttr,
    MetricsRegistry,
    labels_of,
    registry,
)
from .regress import (
    bench_record,
    detect_regressions,
    load_ledger,
    polarity_of,
)
from .trace import (
    Span,
    TraceRing,
    enabled,
    export_chrome_trace,
    finish_span,
    instant,
    new_trace_id,
    ring,
    set_enabled,
    set_process_label,
    span,
    start_span,
    stitch_traces,
    trace_ctx,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricAttr", "MetricsRegistry",
    "registry", "labels_of",
    "Span", "TraceRing", "new_trace_id", "span", "start_span",
    "finish_span", "instant", "trace_ctx", "ring", "set_enabled",
    "enabled", "set_process_label", "export_chrome_trace",
    "stitch_traces",
    "install_compile_events", "uninstall_compile_events",
    "compile_events_installed",
    "slo_summary", "tenant_slo_table",
    "HEALTH_SCHEMA_VERSION", "health_envelope",
    "ThresholdRule", "AbsenceRule", "BurnRateRule", "AlertManager",
    "burn_rate", "budget_remaining_frac", "burn_rules_from_slo",
    "default_serving_rules", "default_training_rules",
    "default_manager", "set_default_manager",
    "bench_record", "load_ledger", "detect_regressions", "polarity_of",
]

# SLO histograms the serving engine feeds (seconds)
SLO_HISTOGRAMS = (
    "serving_ttft_seconds",
    "serving_itl_seconds",
    "serving_queue_delay_seconds",
)


def slo_summary(*, by_tenant: bool = False) -> dict:
    """p50/p95/p99 + count for the built-in TTFT / inter-token-latency
    / queue-delay histograms, aggregated over every label set. The SLO
    series carry a ``tenant`` label (default tenant ``"default"``), so
    the label sets PARTITION the observations and the merged totals
    stay exact. ``by_tenant=True`` adds a ``"tenants"`` key: per-tenant
    sub-summaries (same shape per metric), with every past-the-cap
    overflow handle folded into one ``"(overflow)"`` tenant."""
    out = {}
    tenants: dict = {}
    reg = registry()
    for name in SLO_HISTOGRAMS:
        agg = Histogram()
        m = reg._metrics.get(name)
        if m is not None:
            for labels, h in list(m.series.items()):
                agg.merge(h)
                if by_tenant:
                    t = dict(labels).get("tenant", "default")
                    bucket = tenants.setdefault(t, {}).setdefault(
                        name, Histogram())
                    bucket.merge(h)
            for h in list(m.overflow):
                agg.merge(h)
                if by_tenant:
                    bucket = tenants.setdefault("(overflow)", {}).setdefault(
                        name, Histogram())
                    bucket.merge(h)
        out[name] = agg.to_dict()
    if by_tenant:
        out["tenants"] = {
            t: {name: h.to_dict() for name, h in sorted(per.items())}
            for t, per in sorted(tenants.items())
        }
    return out


def tenant_slo_table() -> dict:
    """Compact per-tenant SLO view for the health() surfaces: requests
    submitted (``serving_tenant_requests_total``) plus TTFT/ITL p50 and
    p99 per tenant. Tenants past the registry cardinality cap fold into
    ``"(overflow)"`` — visible, counted, never unbounded."""
    full = slo_summary(by_tenant=True)
    reg = registry()
    req_by_tenant: dict = {}
    m = reg._metrics.get("serving_tenant_requests_total")
    if m is not None:
        for labels, h in list(m.series.items()):
            t = dict(labels).get("tenant", "default")
            req_by_tenant[t] = req_by_tenant.get(t, 0) + int(h.value)
        if m.overflow:
            req_by_tenant["(overflow)"] = req_by_tenant.get(
                "(overflow)", 0) + int(sum(h.value for h in m.overflow))
    out = {}
    for t in sorted(set(full.get("tenants", {})) | set(req_by_tenant)):
        per = full.get("tenants", {}).get(t, {})
        ttft = per.get("serving_ttft_seconds", {})
        itl = per.get("serving_itl_seconds", {})
        out[t] = {
            "requests": req_by_tenant.get(t, 0),
            "ttft_p50": ttft.get("p50"), "ttft_p99": ttft.get("p99"),
            "itl_p50": itl.get("p50"), "itl_p99": itl.get("p99"),
        }
    return out


# ---------------------------------------------------------------------------
# The shared health() envelope (ISSUE 12 satellite: the two-shapes
# drift fix). Every health() surface wraps its legacy payload with the
# same versioned top-level keys, each sourced from the registry.

HEALTH_SCHEMA_VERSION = 1

# the common top-level keys every health() shape now carries, beyond
# its legacy payload; the schema regression test pins this exact set
HEALTH_COMMON_KEYS = ("schema_version", "kind", "shed_total",
                      "expired_total", "requests_total", "alerts")


def health_envelope(kind: str, payload: dict) -> dict:
    """Wrap one surface's legacy health payload with the shared,
    registry-sourced envelope keys — including the process-default
    alert manager's compact summary (ISSUE 15), so an SLO burn or a
    silenced replica is visible from EVERY health() surface. Legacy
    keys stay at the top level (old readers keep indexing them); the
    envelope keys win on collision only for
    ``schema_version``/``kind``."""
    from . import alerts as _alerts  # lazy: alerts imports .slo

    reg = registry()
    out = dict(payload)
    out["schema_version"] = HEALTH_SCHEMA_VERSION
    out["kind"] = str(kind)
    out["shed_total"] = int(reg.total("serving_shed_total"))
    out["expired_total"] = int(reg.total("serving_expired_total"))
    out["requests_total"] = int(reg.total("serving_requests_total"))
    out["alerts"] = _alerts.health_summary()
    return out
