"""Declarative, deterministic alert engine over the obs stack (ISSUE 15).

PRs 11–14 built the sensing half — registry, traces, SLO attainment,
fleet aggregation. This module WATCHES those signals. Three rule
kinds, evaluated over a live registry snapshot or a merged fleet
registry (:mod:`.agg`):

- :class:`ThresholdRule` — compare any counter/gauge/histogram series
  (``stat``: a gauge/counter ``value``, a cross-series ``total``, a
  histogram percentile ``p50/p95/p99``/``count``, or a windowed
  per-second ``rate`` of a counter) against a bound.
- :class:`AbsenceRule` — a publisher that goes silent is itself an
  alert: grades publication AGE from the fleet store's
  ``published_unix`` stamps; a source that vanishes entirely keeps
  alerting (the manager remembers every source it has ever seen).
- :class:`BurnRateRule` — multi-window SLO burn rate over the
  TTFT/ITL/queue-delay histograms, Google-SRE style: with objective
  ``o`` the error budget is ``1 - o``; the burn rate over a window is
  ``(bad / total) / (1 - o)`` (1.0 = spending exactly the budget).
  The rule fires only when EVERY configured ``(window_s, factor)``
  breaches — the long window proves sustained damage, the short
  window proves it is still happening (fast reset). Latency targets
  resolve per (tenant, priority) from an :class:`~.slo.SLOSpec`, and
  the rule fans out per tenant label automatically.

Alerts carry a full lifecycle so flapping signals don't flap alerts:
``inactive → pending`` on breach, ``pending → firing`` only after the
condition holds ``for_s`` (a flap during pending returns to inactive
with NO event), ``firing → resolved`` only after the condition stays
clear ``resolve_for_s`` (hysteresis; ``resolve_threshold`` optionally
widens the clear band). Transitions are pure functions of the
evaluation clock — pass explicit ``now`` values and the lifecycle
replays byte-identically.

Firing/resolve transitions emit three ways at once: a trace instant
(``alert_firing``/``alert_resolved``) into the span ring, a JSONL
journal record (``journal_path`` / ``PADDLE_ALERT_JOURNAL``), and the
``obs_alerts_fired_total`` / ``obs_alerts_resolved_total`` counters in
the local registry (so the FLEET snapshot shows every replica's alert
activity). Every ``health()`` envelope carries the default manager's
compact summary.

CLI: ``python -m paddle_tpu.obs alerts STORE`` (rc 1 when firing) and
``python -m paddle_tpu.obs top STORE`` (live fleet dashboard).
"""
from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import trace as _trace
from .metrics import Histogram, MetricsRegistry
from .slo import SLOSpec

__all__ = [
    "ALERT_SCHEMA",
    "burn_rate",
    "budget_remaining_frac",
    "ThresholdRule",
    "AbsenceRule",
    "BurnRateRule",
    "DEFAULT_BURN_WINDOWS",
    "burn_rules_from_slo",
    "default_serving_rules",
    "default_training_rules",
    "AlertManager",
    "default_manager",
    "set_default_manager",
    "health_summary",
]

ALERT_SCHEMA = "paddle_tpu.obs.alert/1"

# (window_s, burn factor) pairs — ALL must breach. 5 min of sustained
# burn plus a still-hot 1 min window: sized to this framework's
# in-process serve loops rather than month-long SLO periods.
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float], ...] = (
    (300.0, 14.4), (60.0, 14.4))

# which SLOClass field grades which SLO histogram
_SLO_FIELD = {
    "serving_ttft_seconds": "ttft_s",
    "serving_itl_seconds": "itl_p95_s",
}


# ---------------------------------------------------------------------------
# shared error-budget arithmetic (loadgen's report columns pin against
# these exact functions — one arithmetic, two surfaces)


def burn_rate(bad: float, total: float, objective: float) -> float:
    """How fast the error budget is being spent: observed error rate
    over allowed error rate. 1.0 = spending exactly the budget; 14.4 =
    a 30-day budget gone in 50 h. 0 when there is no traffic."""
    if total <= 0:
        return 0.0
    allowed = 1.0 - float(objective)
    if allowed <= 0.0:
        return math.inf if bad > 0 else 0.0
    return (float(bad) / float(total)) / allowed


def budget_remaining_frac(bad: float, total: float,
                          objective: float) -> float:
    """Fraction of the error budget left over the accounted window:
    1.0 untouched, 0.0 exactly spent, negative = overspent."""
    if total <= 0:
        return 1.0
    allowed = 1.0 - float(objective)
    if allowed <= 0.0:
        return 0.0 if bad > 0 else 1.0
    return 1.0 - (float(bad) / float(total)) / allowed


# ---------------------------------------------------------------------------
# rules


def _cmp(value: float, op: str, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    if op == "<=":
        return value <= threshold
    raise ValueError(f"unknown op {op!r} (want > >= < <=)")


@dataclass(frozen=True)
class ThresholdRule:
    """Compare one stat of a registry metric against a bound.

    ``stat``: ``"total"`` (sum across series — counters/gauges),
    ``"value"`` (each series separately, or one series via
    ``labels``), ``"count"``/``"p50"``/``"p95"``/``"p99"`` (histogram
    series), ``"rate"`` (per-second increase of the cross-series
    total over the trailing ``window_s``)."""

    name: str
    metric: str
    threshold: float
    op: str = ">"
    stat: str = "total"
    labels: Optional[dict] = None
    window_s: float = 60.0
    for_s: float = 0.0
    resolve_for_s: float = 0.0
    resolve_threshold: Optional[float] = None
    severity: str = "warning"
    description: str = ""

    def to_dict(self) -> dict:
        return {"kind": "threshold", "name": self.name,
                "metric": self.metric, "op": self.op,
                "threshold": self.threshold, "stat": self.stat,
                "for_s": self.for_s, "resolve_for_s": self.resolve_for_s,
                "severity": self.severity}


@dataclass(frozen=True)
class AbsenceRule:
    """A publication that stops arriving. Grades per-source age (now
    minus ``published_unix``); ``source=None`` watches every source the
    manager has ever seen — including ones that later disappear from
    the store entirely (age = +inf)."""

    name: str
    source: Optional[str] = None
    max_age_s: float = 5.0
    for_s: float = 0.0
    resolve_for_s: float = 0.0
    severity: str = "critical"
    description: str = ""

    def to_dict(self) -> dict:
        return {"kind": "absence", "name": self.name,
                "source": self.source, "max_age_s": self.max_age_s,
                "for_s": self.for_s, "resolve_for_s": self.resolve_for_s,
                "severity": self.severity}


@dataclass(frozen=True)
class BurnRateRule:
    """Multi-window SLO burn over one SLO histogram. ``bad`` =
    observations over the latency target (:meth:`Histogram.count_over`),
    ``total`` = all observations; both deltas over each window from the
    manager's sample history. Fires when every window's burn >= its
    factor. Target: explicit ``threshold_s``, else resolved per
    (tenant, ``priority``) from ``slo`` (tenant overrides apply —
    that's the per-(tenant, priority) error-budget accounting)."""

    name: str
    metric: str
    objective: float = 0.99
    threshold_s: Optional[float] = None
    slo: Optional[SLOSpec] = None
    tenant: Optional[str] = None
    priority: str = "interactive"
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_BURN_WINDOWS
    for_s: float = 0.0
    resolve_for_s: float = 0.0
    severity: str = "page"
    description: str = ""

    def target_for(self, tenant: str) -> Optional[float]:
        if self.threshold_s is not None:
            return float(self.threshold_s)
        if self.slo is None:
            return None
        cls = self.slo.resolve(tenant, self.priority)
        fld = _SLO_FIELD.get(self.metric)
        if fld is None:
            return None
        return getattr(cls, fld)

    def to_dict(self) -> dict:
        return {"kind": "burn_rate", "name": self.name,
                "metric": self.metric, "objective": self.objective,
                "threshold_s": self.threshold_s, "tenant": self.tenant,
                "priority": self.priority,
                "windows": [list(w) for w in self.windows],
                "for_s": self.for_s, "resolve_for_s": self.resolve_for_s,
                "severity": self.severity}


def burn_rules_from_slo(spec: SLOSpec, *, objective: float = 0.99,
                        windows: Tuple[Tuple[float, float], ...]
                        = DEFAULT_BURN_WINDOWS,
                        priority: str = "interactive",
                        for_s: float = 0.0,
                        resolve_for_s: float = 0.0,
                        severity: str = "page") -> List[BurnRateRule]:
    """One burn-rate rule per SLO histogram the spec constrains. Each
    rule carries the spec itself, so per-tenant target overrides
    resolve lazily as tenants appear in the metric's label sets."""
    out: List[BurnRateRule] = []
    for metric, fld in sorted(_SLO_FIELD.items()):
        default = spec.resolve("__default__", priority)
        if getattr(default, fld) is None and not spec.per_tenant:
            continue
        out.append(BurnRateRule(
            name=f"slo_burn_{metric}", metric=metric,
            objective=objective, slo=spec, priority=priority,
            windows=windows, for_s=for_s, resolve_for_s=resolve_for_s,
            severity=severity))
    return out


def default_serving_rules(*, slo: Optional[SLOSpec] = None,
                          objective: float = 0.99,
                          absence_age_s: float = 5.0,
                          queue_frac_max: float = 0.95) -> list:
    """The serving fleet's stock rule set: silenced-replica absence,
    sustained queue saturation, plus (when a spec is given) the SLO
    burn-rate rules."""
    rules: list = [
        AbsenceRule("replica_silent", max_age_s=absence_age_s,
                    severity="critical",
                    description="a fleet source stopped publishing"),
        ThresholdRule("queue_saturated", "serving_queue_frac",
                      threshold=queue_frac_max, op=">", stat="value",
                      for_s=5.0, resolve_threshold=0.8,
                      severity="warning",
                      description="admission queue near capacity"),
    ]
    if slo is not None:
        rules.extend(burn_rules_from_slo(slo, objective=objective))
    return rules


def default_training_rules(*, max_rollbacks_per_min: float = 3.0,
                           goodput_floor: float = 0.5) -> list:
    """Training-supervisor stock rules: rollback storms (windowed
    rate), goodput_frac floor, and any rank the straggler detector has
    currently flagged."""
    return [
        ThresholdRule("train_rollback_storm", "training_rollbacks_total",
                      threshold=max_rollbacks_per_min / 60.0, op=">",
                      stat="rate", window_s=60.0, resolve_for_s=30.0,
                      severity="critical",
                      description="anomaly rollbacks faster than budget"),
        ThresholdRule("train_goodput_low", "training_goodput_frac",
                      threshold=goodput_floor, op="<", stat="value",
                      for_s=10.0, resolve_for_s=10.0,
                      severity="warning",
                      description="productive fraction of wall time low"),
        ThresholdRule("train_straggler", "training_straggler_ranks",
                      threshold=0.5, op=">", stat="total",
                      severity="warning",
                      description="straggler detector verdict active"),
    ]


# ---------------------------------------------------------------------------
# lifecycle


class _Status:
    """Mutable per-(rule, series) alert state."""

    __slots__ = ("rule", "labels", "state", "pending_since", "fired_at",
                 "clear_since", "resolved_at", "value", "threshold",
                 "annotations")

    def __init__(self, rule, labels: dict):
        self.rule = rule
        self.labels = dict(labels)
        self.state = "inactive"
        self.pending_since = None
        self.fired_at = None
        self.clear_since = None
        self.resolved_at = None
        self.value = None
        self.threshold = None
        self.annotations: dict = {}

    def to_dict(self) -> dict:
        def _r(v):
            return None if v is None else round(float(v), 6)

        return {
            "rule": self.rule.name,
            "severity": self.rule.severity,
            "labels": dict(sorted(self.labels.items())),
            "state": self.state,
            "value": _r(self.value),
            "threshold": _r(self.threshold),
            "pending_since": _r(self.pending_since),
            "fired_at": _r(self.fired_at),
            "resolved_at": _r(self.resolved_at),
            "annotations": self.annotations,
        }


@dataclass
class _Signal:
    """One evaluated (rule, series) condition for this tick."""

    rule: object
    labels: dict
    breach: bool
    value: float
    threshold: float
    hold: Optional[bool] = None  # breach under the resolve threshold
    annotations: dict = field(default_factory=dict)


class AlertManager:
    """Evaluates a rule set against registry/fleet state and owns every
    alert's lifecycle, sample history, journal, and emission."""

    def __init__(self, rules=(), *, journal_path: Optional[str] = None,
                 emit_trace: bool = True, emit_metrics: bool = True,
                 history_len: int = 4096):
        self.rules: list = list(rules)
        self.journal_path = (journal_path
                             or os.environ.get("PADDLE_ALERT_JOURNAL"))
        self.emit_trace = emit_trace
        self.emit_metrics = emit_metrics
        self.events: List[dict] = []  # bounded transition log
        self._history_len = int(history_len)
        self._states: Dict[Tuple[str, Tuple], _Status] = {}
        self._hist: Dict[Tuple[str, Tuple], deque] = {}
        self._known_sources: set = set()
        self._last_now = -math.inf
        self._last_eval_mono = -math.inf

    def add_rule(self, rule) -> "AlertManager":
        self.rules.append(rule)
        return self

    # -- evaluation ------------------------------------------------------

    def evaluate(self, *, registry: Optional[MetricsRegistry] = None,
                 now: Optional[float] = None,
                 ages: Optional[Dict[str, float]] = None) -> List[dict]:
        """One evaluation tick. ``now`` defaults to wall time; explicit
        values are clamped monotonic so test clocks and wall clocks can
        interleave. ``ages`` (source -> seconds since last publication)
        feeds the absence rules; without it they are skipped, not
        cleared. Returns the non-inactive alerts."""
        reg = registry if registry is not None else _metrics.registry()
        if now is None:
            now = time.time()
        now = max(float(now), self._last_now)
        self._last_now = now
        self._last_eval_mono = time.monotonic()
        signals: List[_Signal] = []
        for rule in self.rules:
            if isinstance(rule, AbsenceRule):
                if ages is not None:
                    signals.extend(self._absence_signals(rule, ages, now))
            elif isinstance(rule, BurnRateRule):
                signals.extend(self._burn_signals(rule, reg, now))
            else:
                signals.extend(self._threshold_signals(rule, reg, now))
        seen = set()
        for sig in signals:
            key = (sig.rule.name,
                   tuple(sorted(sig.labels.items())))
            seen.add(key)
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _Status(sig.rule, sig.labels)
            st.value = sig.value
            st.threshold = sig.threshold
            st.annotations = sig.annotations
            self._step(st, sig, now)
        # a series that vanished (registry reset, tenant gone) clears —
        # but an absence status on a tick with no ages was SKIPPED, not
        # graded clear: a registry-only tick must not resolve it
        for key, st in self._states.items():
            if key in seen or st.state in ("inactive", "resolved"):
                continue
            if ages is None and isinstance(st.rule, AbsenceRule):
                continue
            gone = _Signal(st.rule, st.labels, breach=False,
                           value=st.value or 0.0,
                           threshold=st.threshold or 0.0)
            self._step(st, gone, now)
        return self.active()

    def evaluate_fleet(self, store, *, prefix: str = "obs",
                       now: Optional[float] = None) -> List[dict]:
        """Evaluate over the MERGED fleet registry plus per-source
        publication ages — threshold and burn rules see fleet-wide
        series, absence rules see who went quiet."""
        from . import agg as _agg

        states = _agg.collect(store, prefix=prefix)
        reg = _agg.merge_states(states)
        wall = time.time()
        ages = {}
        for sid, st in states.items():
            pub = st.get("published_unix")
            ages[sid] = (math.inf if pub is None
                         else max(0.0, wall - float(pub)))
        return self.evaluate(registry=reg, now=now, ages=ages)

    def maybe_evaluate(self, *, min_interval_s: float = 0.25) -> None:
        """Rate-limited tick for hot paths (health() calls, serve
        loops): evaluates at most every ``min_interval_s``."""
        if time.monotonic() - self._last_eval_mono < min_interval_s:
            return
        self.evaluate()

    # -- signal builders -------------------------------------------------

    def _samples(self, key: Tuple[str, Tuple]) -> deque:
        d = self._hist.get(key)
        if d is None:
            d = self._hist[key] = deque(maxlen=self._history_len)
        return d

    @staticmethod
    def _windowed(samples, now: float, window_s: float):
        """The newest sample at least ``window_s`` old (fall back to
        the oldest) — the reference point for windowed deltas."""
        ref = None
        for s in samples:  # oldest -> newest
            if s[0] <= now - window_s:
                ref = s
            else:
                break
        return ref if ref is not None else (samples[0] if samples
                                            else None)

    def _threshold_signals(self, rule: ThresholdRule,
                           reg: MetricsRegistry,
                           now: float) -> List[_Signal]:
        out: List[_Signal] = []

        def sig(labels: dict, value: Optional[float],
                ann: Optional[dict] = None):
            if value is None:
                return
            breach = _cmp(value, rule.op, rule.threshold)
            hold = (breach if rule.resolve_threshold is None
                    else _cmp(value, rule.op, rule.resolve_threshold))
            out.append(_Signal(rule, labels, breach, float(value),
                               float(rule.threshold), hold=hold,
                               annotations=ann or {}))

        if rule.stat == "total":
            m = reg._metrics.get(rule.metric)
            if m is not None:
                sig({"metric": rule.metric}, reg.total(rule.metric))
        elif rule.stat == "rate":
            m = reg._metrics.get(rule.metric)
            if m is None:
                return out
            key = (rule.name, (("metric", rule.metric),))
            samples = self._samples(key)
            total = reg.total(rule.metric)
            samples.append((now, total))
            ref = self._windowed(samples, now, rule.window_s)
            dt = now - ref[0] if ref else 0.0
            rate = (total - ref[1]) / dt if ref and dt > 0 else 0.0
            sig({"metric": rule.metric}, rate,
                {"window_s": rule.window_s, "total": total})
        else:
            m = reg._metrics.get(rule.metric)
            if m is None:
                return out
            want = (None if rule.labels is None
                    else _metrics.labels_of(rule.labels))
            for labels, h in sorted(m.series.items()):
                if want is not None and labels != want:
                    continue
                lab = dict(labels)
                if lab.get("obs_overflow") == "true":
                    continue
                lab["metric"] = rule.metric
                if isinstance(h, Histogram):
                    v = (h.count if rule.stat == "count"
                         else h.percentile(float(rule.stat[1:])))
                else:
                    v = h.value
                if isinstance(v, (int, float)):
                    sig(lab, float(v))
        return out

    def _absence_signals(self, rule: AbsenceRule,
                         ages: Dict[str, float],
                         now: float) -> List[_Signal]:
        self._known_sources.update(ages)
        targets = ([rule.source] if rule.source
                   else sorted(self._known_sources))
        out = []
        for sid in targets:
            age = ages.get(sid)
            if age is None:
                if sid in self._known_sources:
                    age = math.inf  # vanished from the store entirely
                else:
                    continue  # explicit source never seen yet
            breach = age > rule.max_age_s
            out.append(_Signal(
                rule, {"source": sid}, breach,
                value=(age if math.isfinite(age) else -1.0),
                threshold=float(rule.max_age_s),
                annotations=({"vanished": True}
                             if not math.isfinite(age) else {})))
        return out

    def _burn_signals(self, rule: BurnRateRule, reg: MetricsRegistry,
                      now: float) -> List[_Signal]:
        m = reg._metrics.get(rule.metric)
        if m is None:
            return []
        per_tenant: Dict[str, Histogram] = {}
        for labels, h in m.series.items():
            lab = dict(labels)
            if lab.get("obs_overflow") == "true":
                continue
            t = lab.get("tenant", "default")
            if rule.tenant is not None and t != rule.tenant:
                continue
            per_tenant.setdefault(t, Histogram()).merge(h)
        out: List[_Signal] = []
        for tenant in sorted(per_tenant):
            target = rule.target_for(tenant)
            if target is None:
                continue
            h = per_tenant[tenant]
            bad = h.count_over(target)
            total = h.count
            key = (rule.name, (("metric", rule.metric),
                               ("tenant", tenant)))
            samples = self._samples(key)
            samples.append((now, bad, total))
            burns: Dict[str, float] = {}
            ratios: List[float] = []
            for window_s, factor in rule.windows:
                ref = self._windowed(samples, now, window_s)
                dbad = bad - ref[1] if ref else 0
                dtotal = total - ref[2] if ref else 0
                b = burn_rate(dbad, dtotal, rule.objective)
                burns[f"{window_s:g}s"] = round(b, 6)
                ratios.append((b / factor) if factor > 0
                              else (math.inf if b > 0 else 0.0))
            # the binding window: breach iff the WEAKEST window breaches
            value = min(ratios) if ratios else 0.0
            breach = value >= 1.0
            out.append(_Signal(
                rule, {"metric": rule.metric, "tenant": tenant},
                breach, value=value, threshold=1.0,
                annotations={
                    "objective": rule.objective,
                    "target_s": target,
                    "burn": burns,
                    "bad_total": bad,
                    "observed_total": total,
                    "budget_remaining_frac": round(
                        budget_remaining_frac(bad, total,
                                              rule.objective), 6),
                }))
        return out

    # -- the state machine ----------------------------------------------

    def _step(self, st: _Status, sig: _Signal, now: float) -> None:
        breach = sig.breach
        hold = sig.hold if sig.hold is not None else breach
        if st.state in ("inactive", "resolved") and breach:
            st.state = "pending"
            st.pending_since = now
            st.clear_since = None
        if st.state == "pending":
            if not hold:
                # flap during the hold window: back to inactive, NO
                # event — this is the flap-proofing
                st.state = "inactive"
                st.pending_since = None
                return
            if now - st.pending_since >= st.rule.for_s:
                st.state = "firing"
                st.fired_at = now
                st.resolved_at = None
                self._emit("firing", st, now)
        if st.state == "firing":
            if hold:
                st.clear_since = None
                return
            if st.clear_since is None:
                st.clear_since = now
            if now - st.clear_since >= st.rule.resolve_for_s:
                st.state = "resolved"
                st.resolved_at = now
                st.pending_since = None
                st.clear_since = None
                self._emit("resolved", st, now)

    def _emit(self, event: str, st: _Status, now: float) -> None:
        rec = {
            "schema": ALERT_SCHEMA,
            "t": round(now, 6),
            "event": event,
            "rule": st.rule.name,
            "severity": st.rule.severity,
            "labels": dict(sorted(st.labels.items())),
            "value": None if st.value is None else round(st.value, 6),
            "threshold": (None if st.threshold is None
                          else round(st.threshold, 6)),
        }
        self.events.append(rec)
        if len(self.events) > 1024:
            del self.events[:len(self.events) - 1024]
        if self.journal_path:
            try:
                with open(self.journal_path, "a",
                          encoding="utf-8") as fh:
                    fh.write(json.dumps(rec, sort_keys=True) + "\n")
            except OSError:
                pass
        if self.emit_trace:
            _trace.instant(f"alert_{event}", tid="alerts",
                           rule=st.rule.name,
                           severity=st.rule.severity,
                           labels=dict(sorted(st.labels.items())),
                           value=rec["value"])
        if self.emit_metrics:
            name = ("obs_alerts_fired_total" if event == "firing"
                    else "obs_alerts_resolved_total")
            _metrics.registry().counter(
                name, {"rule": st.rule.name,
                       "severity": st.rule.severity}).inc()

    # -- views -----------------------------------------------------------

    def statuses(self) -> List[dict]:
        return [self._states[k].to_dict()
                for k in sorted(self._states)]

    def active(self) -> List[dict]:
        """Every non-inactive alert (pending / firing / resolved —
        resolved stays visible until its next breach)."""
        return [d for d in self.statuses() if d["state"] != "inactive"]

    def firing(self) -> List[dict]:
        return [d for d in self.statuses() if d["state"] == "firing"]

    def summary(self, *, max_active: int = 8) -> dict:
        """The compact dict the health() envelopes embed."""
        counts = {"pending": 0, "firing": 0, "resolved": 0}
        active = []
        for d in self.statuses():
            if d["state"] in counts:
                counts[d["state"]] += 1
            if d["state"] in ("pending", "firing"):
                active.append({"rule": d["rule"], "state": d["state"],
                               "severity": d["severity"],
                               "labels": d["labels"],
                               "value": d["value"]})
        active.sort(key=lambda a: (a["state"] != "firing", a["rule"],
                                   sorted(a["labels"].items())))
        return {"rules": len(self.rules), **counts,
                "active": active[:max_active]}


# ---------------------------------------------------------------------------
# the process-default manager (what health() envelopes report)

_DEFAULT: Optional[AlertManager] = None

_EMPTY_SUMMARY = {"rules": 0, "pending": 0, "firing": 0,
                  "resolved": 0, "active": []}


def default_manager() -> AlertManager:
    """The process-wide manager; created empty on first use. Serve
    loops add their stock rules to it and tick it; every health()
    envelope embeds its summary."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = AlertManager()
    return _DEFAULT


def set_default_manager(m: Optional[AlertManager]) -> \
        Optional[AlertManager]:
    """Swap the process-default manager (tests); returns the old one."""
    global _DEFAULT
    old, _DEFAULT = _DEFAULT, m
    return old


def health_summary() -> dict:
    """What ``health_envelope`` embeds: a cheap static dict when no
    manager/rules exist; otherwise a rate-limited evaluation tick plus
    the compact summary."""
    m = _DEFAULT
    if m is None:
        return dict(_EMPTY_SUMMARY)
    if m.rules:
        m.maybe_evaluate()
    return m.summary()
