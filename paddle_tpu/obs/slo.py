"""SLO specification + attainment accounting (ISSUE 14).

The measurement half of the open-loop load harness
(``benchmarks/loadgen.py``): given finished requests — anything
shaped like :class:`~paddle_tpu.inference.serving.GenRequest`
(``tenant``/``priority``/``status``/``t_submit``/``times``/``out``) or
the equivalent plain dict — and an :class:`SLOSpec`, compute per-tenant
percentile tables, attainment fractions, and **goodput-under-SLO**
(tokens from SLO-meeting requests / wall time), the metric the serving
papers this stack follows (Sarathi-Serve, DistServe) grade schedulers
by. Closed-loop tok/s rewards a scheduler that starves the tail;
goodput-under-SLO does not.

A request MEETS its SLO iff its submission was served
(``status == "ok"``) and every configured target holds:

- ``ttft_s``    — time to first token ≤ target
- ``itl_p95_s`` — the request's own p95 inter-token latency ≤ target
  (p95, not max: one GC pause should not void 200 good tokens; not
  mean: a bursty stream that averages well still reads badly)
- ``e2e_s``     — last-token wall time since submission ≤ target

Unset targets don't constrain. Reports are deterministic: percentiles
are nearest-rank over sorted lists (no interpolation ambiguity),
floats round to 6 digits, keys sort — two runs over the same inputs
serialize byte-identically.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = [
    "SLOClass",
    "SLOSpec",
    "RequestLatency",
    "attainment_report",
    "report_json",
    "pct",
]


def pct(xs: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile (deterministic, no interpolation):
    the ceil(n*p/100)-th smallest value. None on empty input."""
    if not xs:
        return None
    xs = sorted(xs)
    rank = max(1, math.ceil(len(xs) * p / 100.0))
    return float(xs[rank - 1])


def _r(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 6)


@dataclass(frozen=True)
class SLOClass:
    """One target set. ``None`` fields don't constrain."""

    ttft_s: Optional[float] = None
    itl_p95_s: Optional[float] = None
    e2e_s: Optional[float] = None

    def overlay(self, other: Optional["SLOClass"]) -> "SLOClass":
        """Field-wise override: ``other``'s set fields win."""
        if other is None:
            return self
        return SLOClass(
            ttft_s=other.ttft_s if other.ttft_s is not None else self.ttft_s,
            itl_p95_s=(other.itl_p95_s if other.itl_p95_s is not None
                       else self.itl_p95_s),
            e2e_s=other.e2e_s if other.e2e_s is not None else self.e2e_s,
        )

    def to_dict(self) -> dict:
        return {"ttft_s": _r(self.ttft_s), "itl_p95_s": _r(self.itl_p95_s),
                "e2e_s": _r(self.e2e_s)}


@dataclass
class SLOSpec:
    """Targets resolved per (tenant, priority): start from ``default``,
    overlay the priority class's overrides, then the tenant's — a paying
    tenant's tighter TTFT beats its traffic class's."""

    default: SLOClass = field(default_factory=SLOClass)
    per_priority: Dict[str, SLOClass] = field(default_factory=dict)
    per_tenant: Dict[str, SLOClass] = field(default_factory=dict)

    def resolve(self, tenant: str, priority: str) -> SLOClass:
        out = self.default.overlay(self.per_priority.get(priority))
        return out.overlay(self.per_tenant.get(tenant))

    def to_dict(self) -> dict:
        return {
            "default": self.default.to_dict(),
            "per_priority": {k: v.to_dict()
                             for k, v in sorted(self.per_priority.items())},
            "per_tenant": {k: v.to_dict()
                           for k, v in sorted(self.per_tenant.items())},
        }


@dataclass
class RequestLatency:
    """The per-request facts attainment needs, extracted once from a
    GenRequest-shaped object or dict (``times[i]`` = perf_counter stamp
    of token ``i``; ``t_submit`` same clock)."""

    req_id: object
    tenant: str
    priority: str
    status: str
    tokens: int
    ttft: Optional[float]
    itl_p95: Optional[float]
    e2e: Optional[float]

    @classmethod
    def of(cls, req) -> "RequestLatency":
        get = (req.get if isinstance(req, dict)
               else lambda k, d=None: getattr(req, k, d))
        times = list(get("times") or ())
        t_submit = float(get("t_submit") or 0.0)
        out = get("out") or ()
        itls = [b - a for a, b in zip(times, times[1:])]
        return cls(
            req_id=get("req_id"),
            tenant=str(get("tenant") or "default"),
            priority=str(get("priority") or "interactive"),
            status=str(get("status") or "ok"),
            tokens=len(out),
            ttft=(times[0] - t_submit) if times else None,
            itl_p95=pct(itls, 95),
            e2e=(times[-1] - t_submit) if times else None,
        )

    def meets(self, slo: SLOClass) -> Dict[str, bool]:
        """Per-dimension verdicts plus the conjunction under ``all``.
        A non-ok request fails outright; an unset target passes; a set
        target with no measurement (no tokens) fails."""
        ok = self.status == "ok"

        def dim(target, value):
            if target is None:
                return ok
            return ok and value is not None and value <= target

        v = {
            "ttft": dim(slo.ttft_s, self.ttft),
            "itl": dim(slo.itl_p95_s, self.itl_p95),
            "e2e": dim(slo.e2e_s, self.e2e),
        }
        v["all"] = all(v.values())
        return v


def _table(reqs: List[RequestLatency], spec: SLOSpec,
           wall_s: float) -> dict:
    """One cohort's row: counts, percentile tables, attainment
    fractions, goodput."""
    n = len(reqs)
    statuses: Dict[str, int] = {}
    for r in reqs:
        statuses[r.status] = statuses.get(r.status, 0) + 1
    ttfts = [r.ttft for r in reqs if r.ttft is not None]
    itls = [r.itl_p95 for r in reqs if r.itl_p95 is not None]
    e2es = [r.e2e for r in reqs if r.e2e is not None]
    met = {"ttft": 0, "itl": 0, "e2e": 0, "all": 0}
    tokens_ok = 0
    tokens_total = sum(r.tokens for r in reqs)
    for r in reqs:
        v = r.meets(spec.resolve(r.tenant, r.priority))
        for k in met:
            met[k] += int(v[k])
        if v["all"]:
            tokens_ok += r.tokens
    return {
        "requests": n,
        "statuses": dict(sorted(statuses.items())),
        "tokens": tokens_total,
        "tokens_within_slo": tokens_ok,
        "ttft": {"p50": _r(pct(ttfts, 50)), "p95": _r(pct(ttfts, 95)),
                 "p99": _r(pct(ttfts, 99))},
        # the ITL table is over per-request p95s — the same quantity
        # the attainment verdict uses, so table and fraction agree
        "itl_p95": {"p50": _r(pct(itls, 50)), "p95": _r(pct(itls, 95)),
                    "p99": _r(pct(itls, 99))},
        "e2e": {"p50": _r(pct(e2es, 50)), "p99": _r(pct(e2es, 99))},
        "attainment": {k: _r(met[k] / n) if n else None for k in
                       ("ttft", "itl", "e2e", "all")},
        "goodput_tokens_per_s": _r(tokens_ok / wall_s) if wall_s > 0
        else None,
    }


def attainment_report(requests, spec: SLOSpec, wall_s: float,
                      *, extra: Optional[dict] = None) -> dict:
    """The run report: overall + per-tenant + per-priority attainment
    tables and goodput-under-SLO, schema ``paddle_tpu.obs.slo/1``.
    ``requests`` is any iterable of GenRequest-shaped objects/dicts;
    ``wall_s`` is the measured driving-loop wall time."""
    lats = [RequestLatency.of(r) for r in requests]
    by_tenant: Dict[str, List[RequestLatency]] = {}
    by_priority: Dict[str, List[RequestLatency]] = {}
    for r in lats:
        by_tenant.setdefault(r.tenant, []).append(r)
        by_priority.setdefault(r.priority, []).append(r)
    rep = {
        "schema": "paddle_tpu.obs.slo/1",
        "spec": spec.to_dict(),
        "wall_s": _r(wall_s),
        "overall": _table(lats, spec, wall_s),
        "tenants": {t: _table(rs, spec, wall_s)
                    for t, rs in sorted(by_tenant.items())},
        "priorities": {p: _table(rs, spec, wall_s)
                       for p, rs in sorted(by_priority.items())},
    }
    if extra:
        rep["extra"] = extra
    return rep


def report_json(report: dict) -> str:
    """Canonical serialization — sorted keys, no float noise beyond the
    rounding already applied — so equal runs produce equal bytes."""
    return json.dumps(report, sort_keys=True, indent=2)
