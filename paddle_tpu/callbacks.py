"""paddle.callbacks parity (ref: python/paddle/callbacks.py re-exporting
hapi.callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)

__all__ = [
    "Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL", "LRScheduler",
    "EarlyStopping", "ReduceLROnPlateau", "WandbCallback",
]
