"""paddle_tpu.quantization — quantization-aware training + PTQ observers.

ref: python/paddle/quantization/ — config.py (QuantConfig), qat.py
(QAT.quantize/convert), quanters/abs_max.py
(FakeQuanterWithAbsMaxObserver), observers/abs_max.py; plus the phi
fake_quantize kernels (paddle/phi/kernels/fake_quantize_kernel.cc).

TPU-native notes: fake-quant is a pure elementwise round-through with a
straight-through estimator — implemented as clip+round with the STE
expressed via the stop_gradient identity (x + sg(q - x)), which XLA
fuses into the surrounding ops. int8 inference on TPU runs through the
MXU's int8 path when XLA sees quantized matmuls; `convert` produces the
dequantized-weight inference graph (same contract as the reference's
onnx-format export precursor).
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = [
    "fake_quantize_dequantize_abs_max",
    "FakeQuanterWithAbsMaxObserver",
    "AbsmaxObserver",
    "QuantConfig",
    "QAT",
    "QuantedLinear",
 "BaseQuanter", "BaseObserver", "PTQ", "Int8InferenceLinear"]


def fake_quantize_dequantize_abs_max(x, bit_length: int = 8, scale=None):
    """Round-through fake quant with straight-through gradients
    (ref: fake_quantize_kernel FakeQuantizeDequantizeAbsMax)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def f(a, s):
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
        # straight-through estimator: identity gradient
        return a + jax.lax.stop_gradient(q - a)

    if scale is None:
        def f_auto(a):
            s = jnp.max(jnp.abs(a))
            s = jnp.maximum(s, 1e-9)
            q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
            return a + jax.lax.stop_gradient(q - a)

        return apply(f_auto, x, op_name="fake_quant_abs_max")
    return apply(f, x, scale, op_name="fake_quant_abs_max")


class AbsmaxObserver(Layer):
    """PTQ observer tracking the running abs-max (ref:
    observers/abs_max.py AbsmaxObserver)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        cur = float(np.abs(np.asarray(jax.device_get(x._data))).max())
        self._scale = cur if self._scale is None else max(self._scale, cur)
        return x

    def scale(self) -> float:
        return self._scale or 1e-9


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter: EMA abs-max scale + fake quant round-through
    (ref: quanters/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones(()), _internal=True))
        # accum/state start at ZERO so the FIRST observation yields
        # scale == absmax exactly (state becomes 1): a 1.0 init skews
        # the startup scale toward (r + absmax)/(r + 1) — for small
        # weights that's ~10x too coarse a grid and one-shot PTQ-style
        # calibration quantizes into a handful of levels
        self.register_buffer("accum", Tensor(jnp.zeros(()), _internal=True))
        self.register_buffer("state", Tensor(jnp.zeros(()), _internal=True))

    def forward(self, x):
        if self.training:
            r = self.moving_rate

            def update(a, state, accum):
                cur = jnp.max(jnp.abs(a))
                new_state = r * state + 1.0
                new_accum = r * accum + cur
                return new_accum / new_state, new_state, new_accum

            scale, state, accum = apply(
                update, x, self.state, self.accum, op_name="quant_observer"
            )
            self.scale.set_value(scale._data)
            self.state.set_value(state._data)
            self.accum.set_value(accum._data)
        return fake_quantize_dequantize_abs_max(
            x, self.bit_length, scale=self.scale
        )


class QuantedLinear(Layer):
    """Linear with fake-quanted activations + weights (ref:
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, q_config):
        super().__init__()
        self.linear = linear
        self.activation_quanter = (
            q_config.activation._instance() if q_config.activation else None
        )
        self.weight_quanter = (
            q_config.weight._instance() if q_config.weight else None
        )

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.linear.weight
        if self.weight_quanter is not None:
            wq = self.weight_quanter(w)
        else:
            wq = w
        from ..nn import functional as F

        return F.linear(x, wq, self.linear.bias)


class _QuanterFactory:
    def __init__(self, cls, **kwargs):
        self.cls = cls
        self.kwargs = kwargs

    def _instance(self):
        return self.cls(**self.kwargs)


def _factory_from_instance(inst) -> _QuanterFactory:
    """Rebuild a factory from a configured quanter instance, carrying
    over every __init__ parameter stored as a same-named attribute."""
    import inspect

    sig = inspect.signature(type(inst).__init__)
    kwargs = {
        p: getattr(inst, p)
        for p in list(sig.parameters)[1:]
        if p not in ("args", "kwargs") and hasattr(inst, p)
    }
    return _QuanterFactory(type(inst), **kwargs)


class QuantConfig:
    """ref: quantization/config.py QuantConfig — declares which quanter
    handles activations/weights, globally or per-layer."""

    def __init__(self, activation=None, weight=None):
        self.activation = (
            activation if isinstance(activation, (_QuanterFactory, type(None)))
            else _factory_from_instance(activation)
        )
        self.weight = (
            weight if isinstance(weight, (_QuanterFactory, type(None)))
            else _factory_from_instance(weight)
        )
        self._layer_configs: Dict[Type, "QuantConfig"] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_configs[layer_type] = QuantConfig(activation, weight)

    def config_for(self, layer) -> "QuantConfig":
        return self._layer_configs.get(type(layer), self)


def quanter(cls=None, **kwargs):
    """Factory helper mirroring the reference's quanter registration."""
    return _QuanterFactory(cls or FakeQuanterWithAbsMaxObserver, **kwargs)


class QAT:
    """Quantization-aware training driver (ref: qat.py QAT)."""

    def __init__(self, q_config: QuantConfig):
        self.q_config = q_config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Swap Linear sublayers for QuantedLinear (ref: qat.py
        quantize — the reference walks _sub_layers the same way)."""
        from ..nn import Linear

        target = model  # layer tree is mutated in place (jax arrays are
        # immutable; cloning layers wholesale adds nothing on TPU)
        for name, sub in list(target.named_sublayers(include_self=False)):
            if isinstance(sub, Linear):
                cfg = self.q_config.config_for(sub)
                parent = target
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                setattr(parent, parts[-1], QuantedLinear(sub, cfg))
        return target

    def convert(self, model: Layer, inplace: bool = False,
                execute_dtype: str | None = None) -> Layer:
        """Finalize for inference (ref: qat.py convert).

        Default: fold the quanters into the weights (quant-dequant
        image, float execution) and strip the wrappers — the reference
        behavior. ``execute_dtype="int8"`` instead produces
        Int8InferenceLinear layers holding int8 weights and executing a
        REAL int8 x int8 -> int32 MXU dot with dynamic activation
        quantization (the int8 deploy path the reference lowers to its
        cutlass/llm.int8 kernels)."""
        for name, sub in list(model.named_sublayers(include_self=False)):
            if isinstance(sub, QuantedLinear):
                if execute_dtype == "int8":
                    new = Int8InferenceLinear(sub.linear, sub.weight_quanter)
                else:
                    new = sub.linear
                    if sub.weight_quanter is not None:
                        sub.weight_quanter.eval()
                        wq = sub.weight_quanter(new.weight)
                        new.weight.set_value(wq._data)
                parent = model
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                setattr(parent, parts[-1], new)
        return model


class Int8InferenceLinear(Layer):
    """Inference linear executing with int8 arithmetic: per-out-channel
    int8 weights + scales stored as buffers; forward quantizes
    activations dynamically and runs the int8 dot
    (nn.quant.int8_dynamic_matmul).

    When built from a QAT layer, the weight is first projected onto the
    grid the weight quanter trained against (its fake-quant image) and
    only then int8-encoded, so deployed numerics track the calibrated
    model instead of silently re-quantizing the raw float weight."""

    def __init__(self, linear, weight_quanter=None):
        super().__init__()
        from ..base.tape import no_grad
        from ..nn.quant import weight_quantize

        if weight_quanter is not None:
            bits = getattr(weight_quanter, "bit_length", 8)
            if bits != 8:
                raise ValueError(
                    f"execute_dtype='int8' needs an 8-bit weight config; "
                    f"the QAT weight quanter used bit_length={bits}"
                )
        with no_grad():
            w = linear.weight
            if weight_quanter is not None:
                weight_quanter.eval()
                w = weight_quanter(w)
            qw, scale = weight_quantize(w, algo="weight_only_int8")
        # detach: deployment buffers must not keep the float weight alive
        # through tape nodes, nor be differentiable
        qw._grad_node = None
        scale._grad_node = None
        qw.stop_gradient = True
        scale.stop_gradient = True
        self.register_buffer("qweight", qw)
        self.register_buffer("scale", scale)
        self.bias = linear.bias

    def forward(self, x):
        from ..nn.quant import llm_int8_linear

        return llm_int8_linear(
            x, self.qweight, bias=self.bias, weight_scale=self.scale,
            threshold=None,
        )


class BaseQuanter(Layer):
    """ref: quantization/base_quanter.py BaseQuanter — abstract quant
    transform; subclasses implement forward plus the bit/axis queries."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def quant_axis(self):
        return None

    def bit_length(self):
        return 8


class BaseObserver(BaseQuanter):
    """ref: quantization/base_observer.py BaseObserver — a quanter that
    only collects statistics (PTQ calibration pass)."""

    def forward(self, x):
        return x


class PTQ:
    """Post-training quantization driver (ref: quantization/ptq.py PTQ):
    quantize() inserts observers, the user runs calibration batches,
    convert() folds the observed scales into quant-dequant weights."""

    def __init__(self, q_config: QuantConfig):
        self.q_config = q_config
        self._qat = QAT(q_config)

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        m = self._qat.quantize(model, inplace)
        # observers run in eval mode during calibration
        m.eval()
        return m

    def convert(self, model: Layer, inplace: bool = False,
                execute_dtype: str | None = None) -> Layer:
        return self._qat.convert(model, inplace, execute_dtype=execute_dtype)

# submodule namespaces (ref: quantization/{observers,quanters}/)
from . import observers  # noqa: E402,F401
from . import quanters  # noqa: E402,F401
