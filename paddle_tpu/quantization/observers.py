"""paddle.quantization.observers (ref: python/paddle/quantization/
observers/__init__.py — AbsmaxObserver in abs_max.py,
GroupWiseWeightObserver in groupwise.py:23)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base.tape import apply
from . import AbsmaxObserver, BaseObserver  # noqa: F401

__all__ = ["AbsmaxObserver", "GroupWiseWeightObserver"]


class GroupWiseWeightObserver(BaseObserver):
    """Per-group abs-max over a 2-D weight (ref: groupwise.py:46 — the
    weight-only-quant calibration used for group-quantized int4/int8
    LLM serving): columns are scanned in ``group_size`` chunks of input
    channels; ``scales()`` returns [cin/group_size, out_channels] (the
    reference's transposed layout, matching weight_quantize)."""

    def __init__(self, quant_bits: int = 8, group_size: int = 128):
        super().__init__()
        if group_size not in (64, 128):
            raise ValueError("group_size only supports 64 or 128")
        self.quant_bits = quant_bits
        self.group_size = group_size
        self._max = None

    def forward(self, x):
        def f(w):
            if w.ndim != 2:
                raise ValueError("GroupWiseWeightObserver expects 2-D weights")
            cin, cout = w.shape
            if cin % self.group_size:
                raise ValueError(
                    f"group_size {self.group_size} must divide input "
                    f"channels {cin}"
                )
            g = w.T.reshape(cout, cin // self.group_size, self.group_size)
            m = jnp.abs(g).max(axis=2).astype(jnp.float32)
            # [cin/group, cout] — the reference's final transpose
            # (quantization/observers/groupwise.py _cal_abs_max) and the
            # group-scale layout weight_quantize/weight_only_linear use
            return jnp.maximum(m, 1e-8).T

        self._max = apply(f, x, op_name="groupwise_absmax")
        return x

    def scales(self):
        if self._max is None:
            raise RuntimeError("observer has not seen a weight yet")
        bound = 2 ** (self.quant_bits - 1) - 1
        return self._max / bound

    def zero_points(self):
        return None

    def bit_length(self):
        return self.quant_bits

    def quant_axis(self):
        # -1: with the [cin/group, cout] scale layout the out-channel
        # axis is the last one (ref: groupwise.py:94)
        return -1
