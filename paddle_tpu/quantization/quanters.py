"""paddle.quantization.quanters (ref: python/paddle/quantization/
quanters/__init__.py — FakeQuanterWithAbsMaxObserver in abs_max.py)."""
from . import FakeQuanterWithAbsMaxObserver  # noqa: F401

__all__ = ["FakeQuanterWithAbsMaxObserver"]
