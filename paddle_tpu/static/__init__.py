"""paddle_tpu.static — static-graph compatibility surface.

ref: python/paddle/static/ (25k LoC: Program/Executor/data feeding,
save/load_inference_model, static nn). In the reference this is a whole
second execution engine; here the jaxpr IS the program, so the static
API collapses to:

- ``InputSpec`` — the shape/dtype declaration used by jit.save export
  and to_static input signatures (the genuinely load-bearing piece).
- ``save/load_inference_model`` — thin wrappers over jit.save/load.
- mode toggles (enable/disable_static) re-exported for parity; the
  framework is always "dynamic with compilation", so enable_static only
  flips the flag the reference APIs consult.

Everything else (Program, Executor, feed/fetch) intentionally raises a
guidance error pointing at the jit path rather than silently
pretending to build graphs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from . import nn  # noqa: F401

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "Executor", "default_main_program"]


class InputSpec:
    """Shape/dtype/name declaration (ref: python/paddle/static/
    input.py:38 InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        from ..base.dtype import canonical_dtype

        self.dtype = canonical_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size: int):
        self.shape = (int(batch_size),) + tuple(self.shape)
        return self

    def unbatch(self):
        if not self.shape:
            raise ValueError("cannot unbatch a 0-d InputSpec")
        self.shape = tuple(self.shape[1:])
        return self

    def __repr__(self):
        return (
            f"InputSpec(shape={list(self.shape)}, dtype={self.dtype}, "
            f"name={self.name})"
        )

    def __eq__(self, other):
        return (
            isinstance(other, InputSpec)
            and self.shape == other.shape
            and np.dtype(self.dtype) == np.dtype(other.dtype)
            and self.name == other.name
        )


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref: static/io.py save_inference_model → jit.save with the
    layer found on fetch_vars (the dygraph idiom this build supports)."""
    raise NotImplementedError(
        "static-graph save_inference_model is subsumed by paddle_tpu.jit."
        "save(layer, path, input_spec=[InputSpec(...)]) — the jaxpr is "
        "the inference program"
    )


save_inference_model._guidance_refusal = True


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit

    return jit.load(path_prefix)


class _StaticStub:
    # marks a GUIDANCE REFUSAL: the name resolves (API parity) but use
    # raises with the working alternative. Parity accounting counts
    # these separately from real implementations
    # (tests/test_namespace_parity.py).
    _guidance_refusal = True
    _msg = (
        "the Program/Executor machinery has no TPU counterpart: code under "
        "jit.to_static is traced to a jaxpr and compiled by XLA. Port "
        "static-graph code to the dygraph API + paddle_tpu.jit."
    )

    def __init__(self, *a, **k):
        raise NotImplementedError(self._msg)


class Program(_StaticStub):
    """ref: static Program — intentionally unsupported (see _StaticStub)."""


class Executor(_StaticStub):
    """ref: static Executor — intentionally unsupported (see _StaticStub)."""


def default_main_program():
    raise NotImplementedError(_StaticStub._msg)


def default_startup_program():
    raise NotImplementedError(_StaticStub._msg)


default_main_program._guidance_refusal = True
default_startup_program._guidance_refusal = True


# ---------------------------------------------------------------------------
# parity sweep (ref: python/paddle/static/__init__.py __all__). Names that
# map onto the dygraph+jit runtime are REAL; only ProgramDesc/IPU-bound
# machinery keeps the guided error (see _StaticStub).
# ---------------------------------------------------------------------------
import contextlib as _contextlib
import os as _os

import jax as _jax
import jax.numpy as _jnp


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder declaration (ref: static/input.py data). In the jit
    runtime a placeholder IS an InputSpec — feed it to
    paddle_tpu.jit.to_static(input_spec=...)."""
    return InputSpec(shape, dtype, name)


Variable = None  # assigned below (Tensor alias, ref static Variable)


def _init_variable_alias():
    global Variable
    from ..base.tensor import Tensor as _T

    Variable = _T


_init_variable_alias()


@_contextlib.contextmanager
def name_scope(prefix=None):
    """ref: framework name_scope — prefixes layer/op names (cosmetic in
    the jit runtime; kept as a real stack for tooling)."""
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


_name_scope_stack: list = []


@_contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """ref: static program_guard. The jit runtime has one implicit
    program; the guard is a no-op context kept so ported code runs."""
    yield


@_contextlib.contextmanager
def device_guard(device=None):
    """ref: static device_guard — pins ops to a device; XLA owns
    placement, so this is advisory (kept for ported code)."""
    yield


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_global_scope = _Scope()


def global_scope():
    """ref: executor global_scope — the name->value store; here a plain
    host dict fed by load_program_state."""
    return _global_scope


@_contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """ref: backward.py append_backward — returns [(param, grad)] after
    running the tape backward (the dygraph engine IS the backward
    builder here)."""
    loss.backward()
    params = parameter_list
    if params is None:
        from ..nn.layer.layers import Parameter

        params = [t for t in loss._all_leaf_inputs()] if hasattr(loss, "_all_leaf_inputs") else []
    return [(p, p.grad) for p in params if getattr(p, "grad", None) is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref: backward.py gradients → autograd.grad."""
    from ..autograd import grad as _grad

    outs = _grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref: static/nn/common.py py_func — run a host python function as
    an op; with backward_func it becomes a PyLayer."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    if backward_func is None:
        return func(*xs)
    from ..autograd import PyLayer

    class _PyFunc(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            ctx.save_for_backward(*args)
            return func(*args)

        @staticmethod
        def backward(ctx, *grads):
            saved = ctx.saved_tensor()
            return backward_func(*saved, *grads)

    return _PyFunc.apply(*xs)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,  # noqa: A002
          print_tensor_type=True, print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """ref: static/nn/control_flow.py Print — debug-print that survives
    jit (jax.debug.print)."""
    from ..base.tape import apply as _apply

    msg = message or ""

    def _f(a):
        _jax.debug.print(msg + " {x}", x=a)
        return a

    return _apply(_f, input, op_name="print")


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    """ref: tensor/creation.py create_global_var — a named persistent
    tensor registered in the global scope."""
    from ..base.tensor import Tensor as _T

    t = _T(_jnp.full(tuple(shape), value, dtype=_np_dtype(dtype)), _internal=True)
    t.persistable = persistable
    if name:
        _global_scope[name] = t
    return t


def _np_dtype(d):
    from ..base.dtype import canonical_dtype

    return canonical_dtype(d)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    import paddle_tpu as _p

    return _p.create_parameter(shape, dtype, name=name, attr=attr, is_bias=is_bias,
                               default_initializer=default_initializer)


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """ref: static/nn/metric.py accuracy (top-k)."""
    from ..base.tape import apply as _apply

    def _f(logits, y):
        topk = _jnp.argsort(-logits, axis=-1)[:, :k]
        hit = (topk == y.reshape(-1, 1)).any(axis=-1)
        return hit.astype(_jnp.float32).mean()

    return _apply(_f, input, label, op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1, ins_tag_weight=None):  # noqa: A002
    """ref: static/nn/metric.py auc — batch AUC via the metric package's
    threshold-bucket estimator."""
    from ..metric import Auc as _Auc

    m = _Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(preds=_np_pair(input), labels=_np_label(label))
    import numpy as _np

    from ..base.tensor import to_tensor as _tt

    val = m.accumulate()
    return _tt(_np.asarray(val, _np.float32)), None, None


def _np_pair(t):
    import numpy as _np

    arr = _np.asarray(_jax.device_get(t._data))
    if arr.ndim == 1 or arr.shape[-1] == 1:
        p1 = arr.reshape(-1, 1)
        arr = _np.concatenate([1 - p1, p1], axis=-1)
    return arr


def _np_label(t):
    import numpy as _np

    return _np.asarray(_jax.device_get(t._data)).reshape(-1, 1)


class ExponentialMovingAverage:
    """ref: static/ema.py ExponentialMovingAverage — shadow variables
    with bias-corrected decay; apply()/restore() swap them in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def update(self, parameters=None):
        params = parameters or self._params
        if not self._params:
            self._params = list(params)
        self._step += 1
        decay = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            pid = id(p)
            prev = self._shadow.get(pid, p._data)
            self._shadow[pid] = decay * prev + (1 - decay) * p._data

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            if id(p) in self._shadow:
                p._data = self._shadow[id(p)]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}


from ..base.param_attr import ParamAttr as _ParamAttr


class WeightNormParamAttr(_ParamAttr):
    """ref: static WeightNormParamAttr — ParamAttr carrying the weight-
    norm dim; nn.utils.weight_norm consumes it."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


# -- program (de)serialization over the jit/state-dict runtime ---------------


def save(program, path_prefix, **kwargs):
    """ref: static/io.py save — program here is a Layer (jit runtime);
    persists its state dict."""
    from ..framework.io import save as _save

    _save(program.state_dict(), path_prefix + ".pdparams")


def load(program, path_prefix, executor=None, var_list=None):
    from ..framework.io import load as _load

    program.set_state_dict(_load(path_prefix + ".pdparams"))


def serialize_persistables(feed_vars, fetch_vars, executor=None, program=None, **kw):
    import pickle as _pickle

    import numpy as _np

    layer = program if program is not None else kw.get("layer")
    sd = {k: _np.asarray(_jax.device_get(v._data)) for k, v in layer.state_dict().items()}
    return _pickle.dumps(sd)


def serialize_program(feed_vars, fetch_vars, program=None, **kw):
    """The jit runtime's 'program' is the StableHLO export produced by
    paddle_tpu.jit.save; serialize the fetch signature."""
    import pickle as _pickle

    return _pickle.dumps({"feed": [getattr(v, "name", None) for v in (feed_vars or [])],
                          "fetch": [getattr(v, "name", None) for v in (fetch_vars or [])]})


def deserialize_persistables(program, data, executor=None):
    import pickle as _pickle

    sd = _pickle.loads(data)
    program.set_state_dict(sd)
    return program


def deserialize_program(data):
    import pickle as _pickle

    return _pickle.loads(data)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """ref: static/io.py normalize_program — prunes to the feed/fetch
    closure; the jit trace already is that closure."""
    return program


def load_program_state(model_path, var_list=None):
    """ref: static/io.py load_program_state — returns {name: ndarray}."""
    import numpy as _np

    from ..framework.io import load as _load

    sd = _load(model_path + ".pdparams" if not model_path.endswith(".pdparams") else model_path)
    return {k: _np.asarray(v.numpy() if hasattr(v, "numpy") else v) for k, v in sd.items()}


def set_program_state(program, state):
    program.set_state_dict(state)


def cpu_places(device_count=None):
    from ..base.device import CPUPlace

    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """ref: static cuda_places → accelerator places on TPU."""
    from ..base.device import CUDAPlace

    ids = device_ids if device_ids is not None else range(len(_jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class BuildStrategy:
    """ref: BuildStrategy — fusion/memory knobs. XLA owns all of these;
    the attributes are accepted and recorded so ported setup code runs,
    and the jit pipeline reads none of them (documented no-ops)."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.build_cuda_graph = False

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class CompiledProgram(_StaticStub):
    """ref: CompiledProgram — ProgramDesc-bound; unsupported (use
    paddle_tpu.jit.to_static)."""


class IpuStrategy(_StaticStub):
    """IPU-only machinery — no TPU counterpart."""


class IpuCompiledProgram(_StaticStub):
    """IPU-only machinery — no TPU counterpart."""


@_contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU sharding has no TPU counterpart")
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU sharding has no TPU counterpart")


ipu_shard_guard._guidance_refusal = True
set_ipu_shard._guidance_refusal = True


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    """ref: static/nn/metric.py ctr_metric_bundle — use metric.Auc +
    the accuracy/auc functions above in the dygraph runtime."""
    raise NotImplementedError(
        "ctr_metric_bundle is ProgramDesc-bound; compose paddle_tpu.metric."
        "Auc with static.accuracy/static.auc instead."
    )


ctr_metric_bundle._guidance_refusal = True
