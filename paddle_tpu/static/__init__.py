"""paddle_tpu.static — static-graph compatibility surface.

ref: python/paddle/static/ (25k LoC: Program/Executor/data feeding,
save/load_inference_model, static nn). In the reference this is a whole
second execution engine; here the jaxpr IS the program, so the static
API collapses to:

- ``InputSpec`` — the shape/dtype declaration used by jit.save export
  and to_static input signatures (the genuinely load-bearing piece).
- ``save/load_inference_model`` — thin wrappers over jit.save/load.
- mode toggles (enable/disable_static) re-exported for parity; the
  framework is always "dynamic with compilation", so enable_static only
  flips the flag the reference APIs consult.

Everything else (Program, Executor, feed/fetch) intentionally raises a
guidance error pointing at the jit path rather than silently
pretending to build graphs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Program", "Executor", "default_main_program"]


class InputSpec:
    """Shape/dtype/name declaration (ref: python/paddle/static/
    input.py:38 InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        from ..base.dtype import canonical_dtype

        self.dtype = canonical_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size: int):
        self.shape = (int(batch_size),) + tuple(self.shape)
        return self

    def unbatch(self):
        if not self.shape:
            raise ValueError("cannot unbatch a 0-d InputSpec")
        self.shape = tuple(self.shape[1:])
        return self

    def __repr__(self):
        return (
            f"InputSpec(shape={list(self.shape)}, dtype={self.dtype}, "
            f"name={self.name})"
        )

    def __eq__(self, other):
        return (
            isinstance(other, InputSpec)
            and self.shape == other.shape
            and np.dtype(self.dtype) == np.dtype(other.dtype)
            and self.name == other.name
        )


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref: static/io.py save_inference_model → jit.save with the
    layer found on fetch_vars (the dygraph idiom this build supports)."""
    raise NotImplementedError(
        "static-graph save_inference_model is subsumed by paddle_tpu.jit."
        "save(layer, path, input_spec=[InputSpec(...)]) — the jaxpr is "
        "the inference program"
    )


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit

    return jit.load(path_prefix)


class _StaticStub:
    _msg = (
        "the Program/Executor machinery has no TPU counterpart: code under "
        "jit.to_static is traced to a jaxpr and compiled by XLA. Port "
        "static-graph code to the dygraph API + paddle_tpu.jit."
    )

    def __init__(self, *a, **k):
        raise NotImplementedError(self._msg)


class Program(_StaticStub):
    """ref: static Program — intentionally unsupported (see _StaticStub)."""


class Executor(_StaticStub):
    """ref: static Executor — intentionally unsupported (see _StaticStub)."""


def default_main_program():
    raise NotImplementedError(_StaticStub._msg)
