"""paddle.static.nn — the static-graph layer helpers.

ref: python/paddle/static/nn/__init__.py (38 names; common.py fc/
group_norm/…, control_flow.py cond/case/switch_case/while_loop,
sequence_lod.py sequence_*).

TPU-native design notes:

- The reference's helpers add ops + persistent variables to a Program;
  here execution is eager/jit, so parameter-creating helpers (``fc``,
  ``conv2d``, ``layer_norm``, …) instantiate the matching ``nn`` Layer
  and cache it by ``name`` — a named call reuses its parameters across
  invocations exactly like a named variable in a Program; an unnamed
  call creates fresh parameters each time (each program-build does
  too). The cache lives in ``paddle.static.global_scope()``-like module
  state and is cleared by ``paddle_tpu.static.nn.reset_parameters()``.
- Control flow (``cond``/``case``/``switch_case``/``while_loop``)
  delegates to the dy2static runtime (lax select/while under trace,
  plain Python eagerly — jit/dy2static.py).
- ``sequence_*`` ops: the reference operates on LoD tensors; the
  TPU-native representation of ragged batches is dense padded
  ``[B, T, ...]`` plus an explicit ``length`` tensor, so every
  sequence op here takes/returns padded data (the reference's
  ``sequence_pad``/``sequence_unpad`` convert between the two —
  here padded IS the base layout, and lengths ride alongside).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from ..base.tensor import Tensor
from .. import nn as _nn
from ..nn import functional as F

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate",
]

# name -> (constructed Layer, build signature) — the Program's
# persistent-variable role
_layer_scope: dict = {}
_anon_counter = [0]


def reset_parameters():
    """Drop all name-cached helper parameters (a fresh Program)."""
    _layer_scope.clear()


def _scoped(name: Optional[str], kind: str, build: Callable, sig=None):
    """``sig`` carries the shape-determining arguments: a named reuse
    with a different signature is a programming error (the reference's
    Program raises on a shape-mismatched variable reuse too)."""
    if name is None:
        _anon_counter[0] += 1
        return build()  # fresh params, like a new program op
    key = (kind, name)
    hit = _layer_scope.get(key)
    if hit is not None:
        layer, old_sig = hit
        if sig != old_sig:
            raise ValueError(
                f"static.nn.{kind}(name={name!r}) reused with a different "
                f"configuration: {sig!r} vs cached {old_sig!r}"
            )
        return layer
    layer = build()
    _layer_scope[key] = (layer, sig)
    return layer


# -- parameter-backed helpers ------------------------------------------------

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """ref: static/nn/common.py fc — flatten trailing dims, linear,
    optional activation."""
    shape = list(x.shape)
    if num_flatten_dims < 0:
        num_flatten_dims = len(shape) + num_flatten_dims
    in_features = int(np.prod(shape[num_flatten_dims:]))
    layer = _scoped(name, "fc", lambda: _nn.Linear(
        in_features, size, weight_attr=weight_attr, bias_attr=bias_attr), sig=(in_features, size))
    from ..tensor.manipulation import reshape

    flat = reshape(x, shape[:num_flatten_dims] + [in_features])
    out = layer(flat)
    if activation:
        out = getattr(F, activation)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False, is_test=False):
    """ref: static/nn/common.py batch_norm."""
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = _scoped(name, "batch_norm", lambda: _nn.BatchNorm(
        c, momentum=momentum, epsilon=epsilon, param_attr=param_attr,
        bias_attr=bias_attr, data_layout=data_layout,
        use_global_stats=use_global_stats), sig=(c, data_layout))
    if is_test:
        layer.eval()
    out = layer(input)
    return getattr(F, act)(out) if act else out


def embedding(input, size, is_sparse=False, is_distributed=False,  # noqa: A002
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """ref: static/nn/common.py embedding."""
    layer = _scoped(name, "embedding", lambda: _nn.Embedding(
        size[0], size[1], padding_idx=padding_idx, weight_attr=param_attr), sig=tuple(size))
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None, name=None):
    """ref: static/nn/common.py sparse_embedding — the PS-backed lookup;
    single-process lookups resolve to a dense table (the distributed
    path lives in distributed/ps)."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype, name=name)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """ref: static/nn/common.py bilinear_tensor_product."""
    layer = _scoped(name, "bilinear", lambda: _nn.Bilinear(
        int(x.shape[-1]), int(y.shape[-1]), size, weight_attr=param_attr,
        bias_attr=bias_attr), sig=(int(x.shape[-1]), int(y.shape[-1]), size))
    out = layer(x, y)
    return getattr(F, act)(out) if act else out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    """ref: static/nn/common.py conv2d."""
    c = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    layer = _scoped(name, "conv2d", lambda: _nn.Conv2D(
        c, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format), sig=(c, num_filters, str(filter_size), str(stride), str(padding), str(dilation), groups, data_format))
    out = layer(input)
    return getattr(F, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    """ref: static/nn/common.py conv3d."""
    c = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    layer = _scoped(name, "conv3d", lambda: _nn.Conv3D(
        c, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format), sig=(c, num_filters, str(filter_size), str(stride), str(padding), str(dilation), groups, data_format))
    out = layer(input)
    return getattr(F, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    """ref: static/nn/common.py conv2d_transpose."""
    c = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    layer = _scoped(name, "conv2d_transpose", lambda: _nn.Conv2DTranspose(
        c, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format), sig=(c, num_filters, str(filter_size), str(stride), str(padding), str(dilation), groups, data_format))
    out = layer(input, output_size=output_size)
    return getattr(F, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    """ref: static/nn/common.py conv3d_transpose."""
    c = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    layer = _scoped(name, "conv3d_transpose", lambda: _nn.Conv3DTranspose(
        c, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format), sig=(c, num_filters, str(filter_size), str(stride), str(padding), str(dilation), groups, data_format))
    out = layer(input, output_size=output_size)
    return getattr(F, act)(out) if act else out


def deform_conv2d(input, offset, mask, num_filters, filter_size,  # noqa: A002
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    """ref: static/nn/common.py deform_conv2d → vision deform_conv2d."""
    from ..vision.ops import DeformConv2D

    c = int(input.shape[1])
    layer = _scoped(name, "deform_conv2d", lambda: DeformConv2D(
        c, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups,
        deformable_groups=deformable_groups, weight_attr=param_attr,
        bias_attr=bias_attr), sig=(c, num_filters, str(filter_size), groups, deformable_groups))
    return layer(input, offset, mask)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """ref: static/nn/common.py group_norm."""
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = _scoped(name, "group_norm", lambda: _nn.GroupNorm(
        groups, c, epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_layout), sig=(groups, c))
    out = layer(input)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    """ref: static/nn/common.py instance_norm."""
    c = int(input.shape[1])
    layer = _scoped(name, "instance_norm", lambda: _nn.InstanceNorm2D(
        c, epsilon=epsilon, weight_attr=param_attr, bias_attr=bias_attr), sig=(c,))
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """ref: static/nn/common.py layer_norm."""
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    layer = _scoped(name, "layer_norm", lambda: _nn.LayerNorm(
        list(shape), epsilon=epsilon,
        weight_attr=param_attr if scale else False,
        bias_attr=bias_attr if shift else False), sig=(shape, bool(scale), bool(shift)))
    out = layer(input)
    return getattr(F, act)(out) if act else out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              enable_scale_and_shift=False, name=None, data_layout="NCHW",
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False):
    """ref: static/nn/common.py data_norm — normalization by RUNNING
    batch summaries (size/sum/square-sum accumulators) instead of
    per-batch statistics."""
    c = int(input.shape[-1] if data_layout != "NCHW" or len(input.shape) == 2
            else input.shape[1])

    class _DataNorm(_nn.Layer):
        def __init__(self):
            super().__init__()
            from ..nn.initializer import Constant

            self.batch_size = self.create_parameter(
                [c], default_initializer=Constant(1e4))
            self.batch_sum = self.create_parameter(
                [c], default_initializer=Constant(0.0))
            self.batch_square_sum = self.create_parameter(
                [c], default_initializer=Constant(1e4))
            if enable_scale_and_shift:
                self.scale_w = self.create_parameter(
                    [c], default_initializer=Constant(1.0))
                self.bias = self.create_parameter(
                    [c], default_initializer=Constant(0.0))

        def forward(self, x):
            def f(xx, n, s, ss, *sw):
                mean = s / n
                scale = jnp.sqrt(n / ss)
                y = (xx - mean) * scale
                if sw:
                    y = y * sw[0] + sw[1]
                return y

            args = [x, self.batch_size, self.batch_sum,
                    self.batch_square_sum]
            if enable_scale_and_shift:
                args += [self.scale_w, self.bias]
            return apply(f, *args, op_name="data_norm")

    layer = _scoped(name, "data_norm", _DataNorm, sig=(c, enable_scale_and_shift))
    out = layer(input)
    return getattr(F, act)(out) if act else out


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """ref: static/nn/common.py prelu — modes all/channel/element."""
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = int(x.shape[1] if data_format == "NCHW" else x.shape[-1])
    elif mode == "element":
        num = int(np.prod(x.shape[1:]))
    else:
        raise ValueError("prelu mode must be all/channel/element")
    layer = _scoped(name, f"prelu_{mode}", lambda: _nn.PReLU(
        num_parameters=num, weight_attr=param_attr,
        data_format=data_format), sig=(num, mode))
    if mode == "element":
        from ..tensor.manipulation import reshape

        flat = reshape(x, [int(x.shape[0]), num])
        return reshape(layer(flat), list(x.shape))
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """ref: static/nn/common.py spectral_norm — weight / sigma_max via
    power iteration (stateless: iterations run from a fixed start each
    call, the functional form of nn.utils.spectral_norm)."""

    def f(w):
        w2 = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((w2.shape[0],), w.dtype) / np.sqrt(w2.shape[0])
        for _ in range(max(power_iters, 1)):
            v = w2.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = w2 @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ w2 @ v
        return w / (sigma + eps)

    return apply(f, weight, op_name="spectral_norm")


def row_conv(input, future_context_size, param_attr=None, act=None,  # noqa: A002
             name=None):
    """ref: static/nn/common.py row_conv — lookahead row convolution
    over [B, T, D]: out[t] = sum_{i<=future_context} x[t+i] * w[i].
    ``name`` (or ``param_attr.name``) keys parameter reuse like the
    other helpers; unnamed calls create fresh weights each time."""
    d = int(input.shape[-1])
    k = future_context_size + 1
    if name is None:
        name = getattr(param_attr, "name", None)

    class _RowConv(_nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter([k, d], attr=param_attr)

    layer = _scoped(name, "row_conv", _RowConv, sig=(k, d))

    def f(x, w):
        pads = [(0, 0)] * x.ndim
        pads[1] = (0, k - 1)
        xp = jnp.pad(x, pads)
        out = jnp.zeros_like(x)
        for i in range(k):
            out = out + xp[:, i : i + x.shape[1]] * w[i]
        return out

    out = apply(f, input, layer.weight, op_name="row_conv")
    return getattr(F, act)(out) if act else out


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """ref: static/nn/common.py nce — noise-contrastive estimation loss
    with uniform negative sampling (the reference's default sampler);
    returns the per-example NCE loss."""
    from ..base import random as _random

    d = int(input.shape[-1])
    k = num_neg_samples or 10
    layer = _scoped(name, "nce", lambda: _nn.Linear(
        d, num_total_classes, weight_attr=param_attr, bias_attr=bias_attr), sig=(d, num_total_classes))
    w, b = layer.weight, layer.bias

    def f(x, y, wt, bt):
        n = x.shape[0]
        key = _random.next_key()
        neg = jax.random.randint(key, (n, k), 0, num_total_classes)
        yv = y.reshape(-1)
        pos_logit = jnp.einsum("nd,nd->n", x, wt[:, yv].T) + bt[yv]
        neg_logit = jnp.einsum("nd,nkd->nk", x, wt[:, neg.reshape(-1)].T
                               .reshape(n, k, d)) + bt[neg]
        # NCE: log sigmoid(pos) + sum log sigmoid(-neg)
        loss = -(jax.nn.log_sigmoid(pos_logit)
                 + jax.nn.log_sigmoid(-neg_logit).sum(-1))
        return loss.reshape(n, 1)

    return apply(f, input, label, w, b, op_name="nce")


# -- control flow ------------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """ref: static/nn/control_flow.py cond → dy2static convert_ifelse."""
    from ..jit import dy2static as d2s

    return d2s.convert_ret_ifelse(pred, true_fn or (lambda: None),
                                  false_fn or (lambda: None))


def case(pred_fn_pairs, default=None, name=None):
    """ref: control_flow.py case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")

    def chain(pairs):
        (pred, fn), rest = pairs[0], pairs[1:]
        if not rest:
            fallback = default if default is not None else fn
            return cond(pred, fn, fallback)
        return cond(pred, fn, lambda: chain(rest))

    return chain(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref: control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns)) if callable(branch_fns[0]) \
            else sorted(branch_fns)
    pairs = [(branch_index == idx, fn) for idx, fn in items]
    if default is None:
        default = items[-1][1]
    return case(pairs, default=default)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """ref: control_flow.py while_loop → dy2static convert_while_loop."""
    from ..jit import dy2static as d2s

    def body_tupled(*vs):
        r = body(*vs)
        return tuple(r) if isinstance(r, (list, tuple)) else (r,)

    out = d2s.convert_while_loop(cond_fn, body_tupled, tuple(loop_vars))
    return list(out) if isinstance(out, tuple) else [out]


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """ref: control_flow.py static_pylayer — custom forward/backward
    pair; rides PyLayer (autograd/py_layer.py)."""
    from ..autograd import PyLayer

    class _Op(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            ctx.save_for_backward(*xs)
            out = forward_fn(*xs)
            return out

        @staticmethod
        def backward(ctx, *gouts):
            if backward_fn is None:
                raise RuntimeError("static_pylayer has no backward_fn")
            return backward_fn(*gouts)

    return _Op.apply(*inputs)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref: control_flow.py py_func — host-python op. ``out`` provides
    the result template (shape/dtype) the callback must fill."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    structs = tuple(
        jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(o.dtype)) for o in outs
    )

    def run(*arrs):
        if any(isinstance(a, jax.core.Tracer) for a in arrs):
            res = jax.pure_callback(
                lambda *np_arrs: _host(*np_arrs), structs, *arrs)
        else:
            res = _host(*[np.asarray(a) for a in arrs])
        return res[0] if len(structs) == 1 else res

    def _host(*np_arrs):
        res = func(*[Tensor(jnp.asarray(a), _internal=True) for a in np_arrs])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(
            np.asarray(r.numpy() if isinstance(r, Tensor) else r, s.dtype)
            for r, s in zip(res, structs)
        )

    if backward_func is None:
        return apply(run, *xs, op_name="py_func")

    # backward_func rides PyLayer (same mechanism as static_pylayer):
    # the reference calls it with (inputs, outputs, output-grads) minus
    # ``skip_vars_in_backward_input``, expecting one grad per input
    # (ref: python/paddle/static/nn/control_flow.py py_func backward
    # registration). Previously backward_func was silently ignored.
    from ..autograd import PyLayer

    skip_ids = {id(v) for v in (skip_vars_in_backward_input or ())}
    n_in = len(xs)
    # resolve the skip filter once; save only the tensors backward will
    # actually receive (a skip-listed activation must not be retained)
    keep_in = [i for i in range(n_in) if id(xs[i]) not in skip_ids]
    keep_out = [i for i in range(len(outs)) if id(outs[i]) not in skip_ids]
    in_structs = tuple(
        jax.ShapeDtypeStruct(tuple(t.shape), np.dtype(t.dtype)) for t in xs)

    class _PyFuncOp(PyLayer):
        @staticmethod
        def forward(ctx, *ts):
            res = apply(run, *ts, op_name="py_func")
            res_t = res if isinstance(res, (list, tuple)) else (res,)
            ctx.save_for_backward(*[ts[i] for i in keep_in],
                                  *[res_t[i] for i in keep_out])
            return res

        @staticmethod
        def backward(ctx, *gouts):
            bwd_in = list(ctx.saved_tensor)
            nb = len(bwd_in)

            # same host-callback contract as the forward: backward_func
            # may use .numpy()/plain numpy and return numpy arrays, and
            # must still work when the tape backward itself is traced
            # (jit.to_static jits the whole step including .backward())
            def _bhost(*np_arrs):
                ts_ = [Tensor(jnp.asarray(a), _internal=True)
                       for a in np_arrs]
                g = backward_func(*ts_[:nb], *ts_[nb:])
                g = g if isinstance(g, (list, tuple)) else [g]
                if len(g) != n_in:
                    raise ValueError(
                        f"py_func backward_func returned {len(g)} grads "
                        f"for {n_in} inputs")
                return tuple(
                    np.asarray(r.numpy() if isinstance(r, Tensor) else r,
                               s.dtype)
                    for r, s in zip(g, in_structs))

            def run_bwd(*arrs):
                if any(isinstance(a, jax.core.Tracer) for a in arrs):
                    res = jax.pure_callback(_bhost, in_structs, *arrs)
                else:
                    res = _bhost(*[np.asarray(a) for a in arrs])
                return res[0] if n_in == 1 else res

            g = apply(run_bwd, *bwd_in, *gouts, op_name="py_func_grad")
            return g if n_in == 1 else tuple(g)

    return _PyFuncOp.apply(*xs)


# -- sequence ops over padded [B, T, ...] + lengths --------------------------

def _lengths_mask(length, t):
    larr = length._data if isinstance(length, Tensor) else jnp.asarray(length)
    return jnp.arange(t)[None, :] < larr.reshape(-1, 1)


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """ref: sequence_lod.py sequence_pad. Padded-native: pads the time
    axis to ``maxlen`` with ``pad_value`` and returns (padded, length)
    — the identity-plus-extension in this layout."""
    t = int(x.shape[1])
    maxlen = maxlen or t
    if length is None:
        length = Tensor(jnp.full((int(x.shape[0]),), t, jnp.int32),
                        _internal=True)

    pv = float(np.asarray(
        pad_value.numpy() if isinstance(pad_value, Tensor) else pad_value))

    def f(xx):
        if maxlen <= t:
            return xx[:, :maxlen]
        pads = [(0, 0)] * xx.ndim
        pads[1] = (0, maxlen - t)
        return jnp.pad(xx, pads, mode="constant", constant_values=pv)

    return apply(f, x, op_name="sequence_pad"), length


def sequence_unpad(x, length, name=None):
    """ref: sequence_lod.py sequence_unpad — mask tail positions to 0
    and trim to the longest real length."""
    def f(xx, ll):
        m = _lengths_mask(Tensor(ll, _internal=True), xx.shape[1])
        shape = m.shape + (1,) * (xx.ndim - 2)
        return xx * m.reshape(shape).astype(xx.dtype)

    return apply(f, x, length, op_name="sequence_unpad")


def sequence_softmax(input, use_cudnn=False, name=None, length=None):  # noqa: A002
    """ref: sequence_lod.py sequence_softmax — softmax over each
    sequence's VALID positions."""
    def f(x, *maybe_len):
        logits = x
        if maybe_len:
            m = _lengths_mask(Tensor(maybe_len[0], _internal=True),
                              x.shape[1])
            logits = jnp.where(m, x, jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(logits, axis=1)

    args = [input] + ([length] if length is not None else [])
    return apply(f, *args, op_name="sequence_softmax")


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,  # noqa: A002
                  length=None, name=None):
    """ref: sequence_lod.py sequence_pool — sum/average/sqrt/max/first/
    last over each sequence's valid positions."""
    pool_type = pool_type.lower()

    def f(x, *maybe_len):
        t = x.shape[1]
        if maybe_len:
            larr = maybe_len[0].reshape(-1)
            m = (jnp.arange(t)[None, :] < larr[:, None])
            m = m.reshape(m.shape + (1,) * (x.ndim - 2)).astype(x.dtype)
        else:
            larr = jnp.full((x.shape[0],), t)
            m = jnp.ones((x.shape[0], t) + (1,) * (x.ndim - 2), x.dtype)
        n = larr.reshape((-1,) + (1,) * (x.ndim - 2)).astype(jnp.float32)
        if pool_type == "sum":
            return (x * m).sum(1)
        if pool_type == "average":
            return (x * m).sum(1) / jnp.maximum(n, 1)
        if pool_type == "sqrt":
            return (x * m).sum(1) / jnp.sqrt(jnp.maximum(n, 1))
        if pool_type == "max":
            neg = jnp.finfo(jnp.float32).min
            return jnp.where(m > 0, x, neg).max(1)
        if pool_type == "first":
            return x[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(larr - 1, 0)
            return x[jnp.arange(x.shape[0]), idx]
        raise ValueError(f"unknown pool_type {pool_type!r}")

    args = [input] + ([length] if length is not None else [])
    return apply(f, *args, op_name="sequence_pool")


def sequence_first_step(input, length=None):  # noqa: A002
    """ref: sequence_lod.py sequence_first_step."""
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):  # noqa: A002
    """ref: sequence_lod.py sequence_last_step."""
    return sequence_pool(input, "last", length=length)


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    """ref: sequence_lod.py sequence_slice — per-sequence [offset,
    offset+length) window, gathered into a padded result. The output
    keeps the FULL time width (rows masked past each slice's length)
    so eager and traced shapes agree."""
    def f(x, off, ln):
        t = x.shape[1]
        pos = off.reshape(-1, 1) + jnp.arange(t)[None, :]
        pos = jnp.clip(pos, 0, t - 1)
        g = jnp.take_along_axis(
            x, pos.reshape(pos.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)
        m = jnp.arange(t)[None, :] < ln.reshape(-1, 1)
        return g * m.reshape(m.shape + (1,) * (x.ndim - 2)).astype(x.dtype)

    return apply(f, input, offset, length, op_name="sequence_slice")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,  # noqa: A002
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """ref: sequence_lod.py sequence_conv — a context-window linear over
    the time axis ([B, T, D] padded layout)."""
    d = int(input.shape[-1])
    if filter_stride != 1:
        raise ValueError("sequence_conv supports filter_stride=1")
    layer = _scoped(name, "sequence_conv", lambda: _nn.Linear(
        filter_size * d, num_filters, weight_attr=param_attr,
        bias_attr=bias_attr), sig=(filter_size * d, num_filters))
    start = padding_start if padding_start is not None \
        else -((filter_size - 1) // 2)

    def f(x):
        t = x.shape[1]
        cols = []
        for i in range(filter_size):
            shift = start + i  # time offset this filter row reads from
            xi = jnp.roll(x, -shift, axis=1)
            idx = jnp.arange(t) + shift
            valid = (idx >= 0) & (idx < t)
            cols.append(jnp.where(valid[None, :, None], xi, 0))
        return jnp.concatenate(cols, axis=-1)

    ctx = apply(f, input, op_name="sequence_conv_im2col")
    out = layer(ctx)
    return getattr(F, act)(out) if act else out


def sequence_expand(x, y, ref_level=-1, name=None):
    """ref: sequence_lod.py sequence_expand — repeat each of x's rows
    ``times`` times (padded-native: uniform repeat count derived from
    y's leading-dim ratio)."""
    times = int(y.shape[0]) // int(x.shape[0])

    def f(xx):
        return jnp.repeat(xx, times, axis=0)

    return apply(f, x, op_name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    """ref: sequence_lod.py sequence_expand_as."""
    return sequence_expand(x, y)


def sequence_reshape(input, new_dim):  # noqa: A002
    """ref: sequence_lod.py sequence_reshape — refold the feature dim."""
    from ..tensor.manipulation import reshape

    b = int(input.shape[0])
    total = int(np.prod(input.shape[1:])) * 1
    return reshape(input, [b, (total // new_dim), new_dim])


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    """ref: sequence_lod.py sequence_scatter — per-row scatter-add of
    updates at time indices."""
    def f(x, idx, upd):
        rows = jnp.arange(x.shape[0])[:, None] + 0 * idx
        return x.at[rows, idx].add(upd)

    return apply(f, input, index, updates, op_name="sequence_scatter")


def sequence_enumerate(input, win_size, pad_value=0, name=None):  # noqa: A002
    """ref: sequence_lod.py sequence_enumerate — sliding windows of ids
    ([B, T] -> [B, T, win_size], tail padded)."""
    def f(x):
        t = x.shape[1]
        idx = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
        valid = idx < t
        idx = jnp.clip(idx, 0, t - 1)
        g = x[:, idx]
        return jnp.where(valid[None], g, pad_value)

    return apply(f, input, op_name="sequence_enumerate")
