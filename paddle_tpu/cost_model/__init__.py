"""paddle.cost_model (ref: python/paddle/cost_model/cost_model.py:25 —
CostModel.profile_measure runs a Program under the profiler and
collects per-op costs).

TPU-native: the compiled program's costs come from XLA itself —
``jax.jit(fn).lower(...).compile().cost_analysis()`` exposes the
compiler's FLOP/byte estimates, and wall-time measurement runs the
compiled binary. Both are surfaced here."""
from __future__ import annotations

import time
from typing import Dict

__all__ = ["CostModel"]


class CostModel:
    """Static cost estimates + measured step time for a jittable fn."""

    def profile_measure(self, fn, example_args=(), run_iters: int = 10,
                        device: str = None, fetch_cost_list=None) -> Dict:
        """Compile ``fn`` on the example args and return XLA's cost
        analysis plus a measured mean step time (the reference returns
        per-op profiler times; XLA fuses ops, so the granularity here
        is the fused program)."""
        import jax
        import numpy as np

        from ..base.tensor import Tensor

        raw = [a._data if isinstance(a, Tensor) else a for a in example_args]

        def pure(*xs):
            out = fn(*[Tensor(x, _internal=True) for x in xs])
            return out._data if isinstance(out, Tensor) else out

        compiled = jax.jit(pure).lower(*raw).compile()
        raw_cost = compiled.cost_analysis() or {}
        if isinstance(raw_cost, (list, tuple)):
            # jax <= 0.4.x returns a one-element list of per-device
            # dicts; 0.5+ returns the dict directly
            raw_cost = raw_cost[0] if raw_cost else {}
        cost = dict(raw_cost)
        out = compiled(*raw)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(run_iters):
            out = compiled(*raw)
        jax.block_until_ready(out)
        per_step = (time.perf_counter() - t0) / max(run_iters, 1)
        return {
            "time_ms": per_step * 1e3,
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "cost_analysis": cost,
        }

    # the reference's toy entry (cost_model.py:29) builds a demo fc
    # program; kept for API parity
    def build_program(self):
        import numpy as np

        from .. import nn
        from ..base.tensor import Tensor

        model = nn.Linear(1, 10)

        def fn(x):
            return model(x)

        x = Tensor(np.zeros((4, 1), np.float32), _internal=True)
        return fn, (x,)

    def static_cost_data(self):
        """ref: cost_model.py static_cost_data — the reference loads a
        json table of measured op costs; here the authoritative static
        cost source is XLA's cost_analysis (see profile_measure)."""
        raise NotImplementedError(
            "per-op static cost tables do not exist under XLA fusion; "
            "use profile_measure(fn, args)['cost_analysis']"
        )
