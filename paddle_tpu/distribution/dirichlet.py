"""Dirichlet (ref: python/paddle/distribution/dirichlet.py:25)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Dirichlet"]


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.conc_arr = _as_array(concentration)
        super().__init__(
            batch_shape=self.conc_arr.shape[:-1],
            event_shape=self.conc_arr.shape[-1:],
        )

    @property
    def mean(self):
        def f(a):
            return a / jnp.sum(a, -1, keepdims=True)

        return apply(f, self.conc_arr, op_name="dirichlet_mean")

    @property
    def variance(self):
        def f(a):
            a0 = jnp.sum(a, -1, keepdims=True)
            m = a / a0
            return m * (1 - m) / (a0 + 1)

        return apply(f, self.conc_arr, op_name="dirichlet_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(a):
            g = jax.random.gamma(key, jnp.broadcast_to(a, out_shape))
            return g / jnp.sum(g, -1, keepdims=True)

        return apply(f, self.conc_arr, op_name="dirichlet_rsample")

    def log_prob(self, value):
        def f(v, a):
            return (
                jnp.sum((a - 1) * jnp.log(v), -1)
                + gammaln(jnp.sum(a, -1))
                - jnp.sum(gammaln(a), -1)
            )

        return apply(f, value, self.conc_arr, op_name="dirichlet_log_prob")

    def entropy(self):
        def f(a):
            a0 = jnp.sum(a, -1)
            k = a.shape[-1]
            return (
                jnp.sum(gammaln(a), -1)
                - gammaln(a0)
                + (a0 - k) * digamma(a0)
                - jnp.sum((a - 1) * digamma(a), -1)
            )

        return apply(f, self.conc_arr, op_name="dirichlet_entropy")
