"""Gumbel (ref: python/paddle/distribution/gumbel.py:30)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Gumbel"]

_EULER = float(np.euler_gamma)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_arr = _as_array(loc)
        self.scale_arr = _as_array(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc_arr.shape), tuple(self.scale_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        def f(loc, scale):
            return loc + scale * _EULER

        return apply(f, self.loc_arr, self.scale_arr, op_name="gumbel_mean")

    @property
    def variance(self):
        def f(scale):
            return (np.pi**2 / 6.0) * scale * scale

        return apply(f, self.scale_arr, op_name="gumbel_var")

    @property
    def stddev(self):
        def f(scale):
            return (np.pi / np.sqrt(6.0)) * scale

        return apply(f, self.scale_arr, op_name="gumbel_std")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(loc, scale):
            g = jax.random.gumbel(key, out_shape, jnp.float32)
            return loc + scale * g

        return apply(f, self.loc_arr, self.scale_arr, op_name="gumbel_rsample")

    def log_prob(self, value):
        def f(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="gumbel_log_prob")

    def entropy(self):
        def f(scale):
            return jnp.log(scale) + 1 + _EULER

        return apply(f, self.scale_arr, op_name="gumbel_entropy")

    def cdf(self, value):
        def f(v, loc, scale):
            return jnp.exp(-jnp.exp(-(v - loc) / scale))

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="gumbel_cdf")
