"""Uniform (ref: python/paddle/distribution/uniform.py:32)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Uniform"]


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low_arr = _as_array(low)
        self.high_arr = _as_array(high)
        shape = jnp.broadcast_shapes(tuple(self.low_arr.shape), tuple(self.high_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        def f(lo, hi):
            return (lo + hi) / 2

        return apply(f, self.low_arr, self.high_arr, op_name="uniform_mean")

    @property
    def variance(self):
        def f(lo, hi):
            return (hi - lo) ** 2 / 12

        return apply(f, self.low_arr, self.high_arr, op_name="uniform_var")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(lo, hi):
            u = jax.random.uniform(key, out_shape, jnp.float32)
            return lo + (hi - lo) * u

        return apply(f, self.low_arr, self.high_arr, op_name="uniform_rsample")

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)

        return apply(f, value, self.low_arr, self.high_arr, op_name="uniform_log_prob")

    def entropy(self):
        def f(lo, hi):
            return jnp.log(hi - lo)

        return apply(f, self.low_arr, self.high_arr, op_name="uniform_entropy")
