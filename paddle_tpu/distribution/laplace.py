"""Laplace (ref: python/paddle/distribution/laplace.py:27)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Laplace"]


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc_arr = _as_array(loc)
        self.scale_arr = _as_array(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc_arr.shape), tuple(self.scale_arr.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        def f(loc):
            return jnp.broadcast_to(loc, self._batch_shape)

        return apply(f, self.loc_arr, op_name="laplace_mean")

    @property
    def variance(self):
        def f(scale):
            return jnp.broadcast_to(2 * scale * scale, self._batch_shape)

        return apply(f, self.scale_arr, op_name="laplace_var")

    @property
    def stddev(self):
        def f(scale):
            return jnp.broadcast_to(np.sqrt(2.0) * scale, self._batch_shape)

        return apply(f, self.scale_arr, op_name="laplace_std")

    def rsample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(loc, scale):
            u = jax.random.uniform(key, out_shape, jnp.float32, -0.5 + 1e-7, 0.5)
            return loc - scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))

        return apply(f, self.loc_arr, self.scale_arr, op_name="laplace_rsample")

    def log_prob(self, value):
        def f(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="laplace_log_prob")

    def entropy(self):
        def f(scale):
            return jnp.broadcast_to(1 + jnp.log(2 * scale), self._batch_shape)

        return apply(f, self.scale_arr, op_name="laplace_entropy")

    def cdf(self, value):
        def f(v, loc, scale):
            z = (v - loc) / scale
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="laplace_cdf")

    def icdf(self, value):
        def f(p, loc, scale):
            a = p - 0.5
            return loc - scale * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a))

        return apply(f, value, self.loc_arr, self.scale_arr, op_name="laplace_icdf")
