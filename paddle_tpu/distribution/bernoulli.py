"""Bernoulli (ref: python/paddle/distribution/bernoulli.py:35)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Bernoulli"]

_EPS = 1e-7


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        def clip(p):
            return jnp.clip(p, _EPS, 1 - _EPS)

        self.probs_arr = apply(clip, _as_array(probs), op_name="clip")
        super().__init__(batch_shape=tuple(self.probs_arr.shape))

    @property
    def mean(self):
        def f(p):
            return p

        return apply(f, self.probs_arr, op_name="bernoulli_mean")

    @property
    def variance(self):
        def f(p):
            return p * (1 - p)

        return apply(f, self.probs_arr, op_name="bernoulli_var")

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            return jax.random.bernoulli(key, p, out_shape).astype(jnp.float32)

        out = apply(f, self.probs_arr, op_name="bernoulli_sample")
        out.stop_gradient = True
        return out

    def rsample(self, shape=(), temperature: float = 1.0):
        """Gumbel-softmax relaxation (ref: bernoulli.py rsample)."""
        key = self._next_key()
        out_shape = self._extend_shape(shape)

        def f(p):
            u = jax.random.uniform(key, out_shape, jnp.float32, _EPS, 1 - _EPS)
            logits = jnp.log(p) - jnp.log1p(-p)
            g = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((logits + g) / temperature)

        return apply(f, self.probs_arr, op_name="bernoulli_rsample")

    def log_prob(self, value):
        def f(v, p):
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply(f, value, self.probs_arr, op_name="bernoulli_log_prob")

    def entropy(self):
        def f(p):
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

        return apply(f, self.probs_arr, op_name="bernoulli_entropy")
