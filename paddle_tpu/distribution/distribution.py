"""Distribution base (ref: python/paddle/distribution/distribution.py:57).

Shared plumbing: arg broadcasting to Tensors, key drawing, and the
sample/rsample/log_prob/probs/entropy/kl contract.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..base import random as _random
from ..base.tape import apply
from ..base.tensor import Tensor

__all__ = ["Distribution"]


def _as_array(x, dtype=jnp.float32):
    """Parameter → Tensor, preserving the caller's Tensor identity so
    gradients from log_prob/rsample flow back to it (the reference keeps
    the original Variable for the same reason)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype), stop_gradient=True, _internal=True)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    # -- sampling ------------------------------------------------------
    def _next_key(self):
        return _random.next_key()

    def sample(self, shape: Sequence[int] = ()):
        """Non-reparameterized draw (gradients blocked)."""
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape: Sequence[int] = ()):
        raise NotImplementedError

    # -- densities -----------------------------------------------------
    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        def f(lp):
            return jnp.exp(lp)

        return apply(f, self.log_prob(value), op_name="exp")

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # -- helpers -------------------------------------------------------
    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self._batch_shape})"
