"""Multinomial (ref: python/paddle/distribution/multinomial.py:25)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..base.tape import apply
from .distribution import Distribution, _as_array

__all__ = ["Multinomial"]


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)

        def norm(p):
            return p / jnp.sum(p, -1, keepdims=True)

        self.probs_arr = apply(norm, _as_array(probs), op_name="normalize")
        shape = tuple(self.probs_arr.shape)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        def f(p):
            return self.total_count * p

        return apply(f, self.probs_arr, op_name="multinomial_mean")

    @property
    def variance(self):
        def f(p):
            return self.total_count * p * (1 - p)

        return apply(f, self.probs_arr, op_name="multinomial_var")

    def sample(self, shape=()):
        key = self._next_key()
        out_shape = tuple(shape) + self._batch_shape
        k = self.probs_arr.shape[-1]

        def f(p):
            logp = jnp.log(p)
            draws = jax.random.categorical(
                key, logp, shape=(self.total_count,) + out_shape
            )
            onehot = jax.nn.one_hot(draws, k)
            return jnp.sum(onehot, axis=0)

        out = apply(f, self.probs_arr, op_name="multinomial_sample")
        out.stop_gradient = True
        return out

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            coeff = gammaln(jnp.asarray(self.total_count + 1.0)) - jnp.sum(
                gammaln(v + 1.0), -1
            )
            return coeff + jnp.sum(v * jnp.log(p), -1)

        return apply(f, value, self.probs_arr, op_name="multinomial_log_prob")

    def entropy(self):
        """Monte-Carlo-free upper-bound form used by the reference
        (sum of marginal binomial entropies is not exact; paddle returns
        the exact sum over the support only for small n — here the
        standard approximation n*H(p) + log-coeff correction)."""

        def f(p):
            return -jnp.sum(self.total_count * p * jnp.log(p), -1)

        return apply(f, self.probs_arr, op_name="multinomial_entropy")
